"""Checkpointing: Orbax sharded save/restore + consolidated export.

TPU-native replacement for the reference's two checkpoint mechanisms
(SURVEY.md C17/C18):

- DDP: rank0 pickles {model, optimizer, global_step, tokens_seen, configs}
  (``ddp_trainer.py:370-456``).
- FSDP: FULL_STATE_DICT gather to rank0 with CPU offload, barrier, and a
  broadcast-based load (``fsdp_trainer.py:405-494``) — with the known rank0
  memory-spike limitation its own docstring admits.

Here every host writes its own shards (no gather, no spike) and restore
reshards natively onto whatever mesh/strategy the restoring trainer uses —
save under ZeRO-3, resume under DDP, or vice versa. A consolidated
single-file export (flax msgpack of gathered params) covers the "one file
for inference elsewhere" use the reference's pickle served.

Layout::

    <dir>/step_00000100/state/   # orbax pytree of TrainState (1 process)
    <dir>/step_00000100/meta.json  # step, tokens_seen, configs, data_state

Multi-process runs use a *two-phase commit* instead of the orbax tree
(meta.json carries ``format: "host_shards"``)::

    <dir>/step_00000100/shards/host00000.npz   # this host's shards
    <dir>/step_00000100/shards/host00000.json  # shard manifest
    <dir>/step_00000100/commit/host00000.done  # phase-1 DONE marker
    <dir>/step_00000100/meta.json              # host 0, after ALL markers

Phase 1: every process writes its addressable shards and then an atomic
DONE marker. Phase 2: host 0 polls ``commit/`` (bounded wait with
backoff — a filesystem barrier, deliberately NOT a jax collective, so it
is safe from the AsyncSaver's background thread while the main thread
runs step collectives) and writes meta.json last. Restore reassembles the
global arrays from every host file and reshards onto the restoring
trainer's mesh — including a *different* process count or ``data x fsdp``
factorization (the elastic mesh-resize resume).

Crash-safety contract (the fault-tolerance layer in ``training/cli.py``
builds on all three; identical in both formats):

- A checkpoint is *complete* iff its meta.json parses: meta is written by
  host 0 after every shard landed, so a crash mid-save leaves a directory
  that ``latest_checkpoint``/``list_checkpoints`` simply never report.
- ``restore_latest(verify=True)`` quarantines a checkpoint that fails to
  load (corrupt shards, truncated meta) by renaming it aside and falls
  back to the previous valid step instead of bricking auto-resume.
- ``keep_last_n`` garbage-collects completed checkpoints oldest-first;
  in-flight (meta-less) and quarantined directories are never touched.

All checkpoint-directory filesystem ops (meta read/write, marker writes,
quarantine rename, GC) go through :func:`retry_io` — a small bounded
retry/backoff helper for the transient I/O errors shared filesystems
throw under pod-scale load.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.parallel.mesh import barrier
from tpu_trainer.training.config import TrainingConfig
from tpu_trainer.utils import faults, jax_compat

_STEP_DIR_RE = re.compile(r"^step_(\d{8})$")

# Suffix a failed-to-load checkpoint directory is renamed to. Quarantined
# dirs no longer match _STEP_DIR_RE, so every scan ignores them; they are
# kept on disk for postmortem rather than deleted.
QUARANTINE_SUFFIX = ".corrupt"

# meta.json "format" value for the multi-process two-phase layout.
HOST_SHARDS_FORMAT = "host_shards"
_SHARDS_SUBDIR = "shards"
_COMMIT_SUBDIR = "commit"


def _barrier_timeout_s() -> float:
    # Bound on the filesystem commit barrier: past this, a missing peer
    # marker means a host died mid-save and the surviving hosts must error
    # out (surfaced via AsyncSaver.wait) instead of hanging forever.
    return float(os.environ.get("TPU_TRAINER_CKPT_BARRIER_TIMEOUT_S", "120"))


def retry_io(
    fn: Callable[[], Any],
    *,
    what: str,
    attempts: int = 4,
    base_delay_s: float = 0.05,
    retry_on: Tuple[type, ...] = (OSError,),
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Run ``fn`` with bounded retry + exponential backoff on transient
    filesystem errors (shared filesystems under pod-scale load throw
    EIO/ESTALE-class errors that succeed on the next attempt). The final
    failure re-raises — checkpoint durability errors must surface, not be
    swallowed. ``sleep`` is injectable so tests don't wait."""
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            delay = base_delay_s * (2 ** attempt)
            print(
                f"checkpoint io retry {attempt + 1}/{attempts - 1} for "
                f"{what}: {type(e).__name__}: {e}; backing off {delay:.2f}s",
                file=sys.stderr, flush=True,
            )
            sleep(delay)


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype from its manifest string, including the ml_dtypes extended
    types (bfloat16 etc.) numpy alone can't parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency, always present

        return np.dtype(getattr(ml_dtypes, name))


class CheckpointIncompatibleError(ValueError):
    """The checkpoint loaded fine but belongs to a different run
    configuration (model shapes, optimizer state dtype). Distinguished from
    corruption: ``restore_latest`` quarantines corrupt checkpoints and falls
    back, but a config mismatch is a user error that silently skipping
    would turn into a fresh-start-over-hours-of-progress."""


def step_dir(checkpoint_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir), f"step_{step:08d}")


def _read_meta(path: str) -> Optional[dict]:
    """meta.json of a step dir, or None if missing/empty/torn — an
    unreadable meta means an incomplete or corrupt save and must never
    crash a directory scan (a truncated meta.json used to brick
    auto-resume with JSONDecodeError).

    A *missing* meta.json is the normal in-flight-save case and returns
    None immediately; other OSErrors (transient shared-FS failures) are
    retried before giving up."""
    meta_path = os.path.join(path, "meta.json")

    def _read() -> Optional[str]:
        try:
            with open(meta_path) as f:
                return f.read()
        except FileNotFoundError:
            return None

    try:
        raw = retry_io(_read, what=f"read {meta_path}")
    except OSError:
        return None
    if raw is None:
        return None
    try:
        meta = json.loads(raw)
    except ValueError:
        return None
    return meta if isinstance(meta, dict) else None


def list_checkpoints(checkpoint_dir: str) -> List[Tuple[int, str]]:
    """Completed checkpoints as ascending ``(step, path)`` pairs.

    Completed = the directory name matches ``step_XXXXXXXX`` and its
    meta.json parses. Meta-less directories (in-flight or crashed saves)
    and quarantined ``*.corrupt`` directories are excluded.
    """
    checkpoint_dir = os.path.abspath(checkpoint_dir)
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for name in sorted(os.listdir(checkpoint_dir)):
        m = _STEP_DIR_RE.match(name)
        if not m:
            continue
        path = os.path.join(checkpoint_dir, name)
        if _read_meta(path) is not None:
            out.append((int(m.group(1)), path))
    return out


def latest_checkpoint(checkpoint_dir: str) -> Optional[str]:
    """Newest *readable* step_XXXXXXXX subdirectory, or None. A step dir
    whose meta.json exists but is empty/truncated is skipped and the scan
    keeps looking at older steps."""
    ckpts = list_checkpoints(checkpoint_dir)
    return ckpts[-1][1] if ckpts else None


def quarantine_checkpoint(path: str) -> str:
    """Move a bad checkpoint aside (rename, host 0) so scans stop seeing it;
    returns the quarantine path. Collision-suffixed so repeated corruption
    of the same step never throws."""
    path = os.path.abspath(path)
    dest = path + QUARANTINE_SUFFIX
    n = 1
    while os.path.exists(dest):
        dest = f"{path}{QUARANTINE_SUFFIX}.{n}"
        n += 1
    if jax.process_index() == 0:
        retry_io(lambda: os.rename(path, dest), what=f"quarantine {path}")
    barrier("checkpoint_quarantine")
    return dest


def gc_checkpoints(
    checkpoint_dir: str, keep_last_n: int, *, sync: bool = True
) -> List[str]:
    """Delete completed checkpoints beyond the newest ``keep_last_n``.

    Only completed checkpoints count toward (and are eligible for) the
    budget: an in-flight save's meta-less directory and quarantined dirs
    are never touched. Returns the deleted paths.

    ``sync=False`` skips the trailing jax-collective barrier — required
    when called from the two-phase commit (possibly on the AsyncSaver's
    writer thread, where a collective would race the main thread's step
    collectives; host 0 alone deletes, which is already safe).
    """
    if keep_last_n <= 0:
        return []
    removed = []
    if jax.process_index() == 0:
        complete = list_checkpoints(checkpoint_dir)
        for _, path in complete[:-keep_last_n]:
            try:
                retry_io(lambda p=path: shutil.rmtree(p), what=f"gc {path}")
            except OSError:
                continue  # GC is best-effort; a stuck dir is retried next save
            removed.append(path)
    if sync:
        barrier("checkpoint_gc")
    return removed


class _HostShardSnapshot(list):
    """Host-side copy of one process's addressable shards (what
    :func:`host_shard_snapshot` returns) — a distinct type so
    ``_commit_checkpoint`` can tell it apart from a TrainState."""


def host_shard_snapshot(
    state_like,
    *,
    process_of_device=None,
    host: Optional[int] = None,
) -> _HostShardSnapshot:
    """Copy this process's addressable shards of every leaf to host memory.

    Blocks until pending computation writing into ``state_like`` finishes
    (the mandatory synchronous cost of an async save — ``train_step``
    donates the state buffers, so the very next step would overwrite what
    the writer thread is reading). Returns a list of
    ``{key, global_shape, dtype, shards: [(starts, ndarray)]}`` records;
    ``key`` is ``jax.tree_util.keystr`` of the leaf path, the stable
    cross-mesh leaf identity the restore side reassembles against.

    ``process_of_device``/``host`` are injectable for tests that simulate
    an N-host layout on a single process (the same seam as
    ``parallel/mesh.host_feed_info``).
    """
    pod = process_of_device or (lambda d: d.process_index)
    me = jax.process_index() if host is None else host
    leaves = jax.tree_util.tree_flatten_with_path(state_like)[0]
    out = _HostShardSnapshot()
    for key_path, leaf in leaves:
        key = jax.tree_util.keystr(key_path)
        if isinstance(leaf, jax.Array):
            shards = []
            seen = set()
            for s in leaf.addressable_shards:
                if pod(s.device) != me:
                    continue
                starts = tuple(
                    0 if sl.start is None else int(sl.start) for sl in s.index
                )
                if starts in seen:
                    # Replicated across this host's local devices: one copy.
                    continue
                seen.add(starts)
                shards.append((starts, np.asarray(s.data)))
            out.append({
                "key": key,
                "global_shape": tuple(leaf.shape),
                "dtype": str(leaf.dtype),
                "shards": shards,
            })
        else:
            # Non-jax leaf (plain scalar/ndarray): replicated; host 0 owns it.
            arr = np.asarray(leaf)
            shards = [] if me != 0 else [(tuple(0 for _ in arr.shape), arr)]
            out.append({
                "key": key,
                "global_shape": tuple(arr.shape),
                "dtype": str(arr.dtype),
                "shards": shards,
            })
    return out


def _write_host_shards(
    path: str, snapshot: _HostShardSnapshot, *, host: int, world: int
) -> None:
    """Phase 1a: durably write this host's shards + manifest. Shard bytes go
    into one npz (each array serialized as raw uint8 so extended dtypes like
    bfloat16 round-trip); the manifest records key/shape/dtype/offsets."""
    sdir = os.path.join(path, _SHARDS_SUBDIR)
    os.makedirs(sdir, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {"host": host, "world": world, "leaves": []}
    for li, leaf in enumerate(snapshot):
        entry = {
            "key": leaf["key"],
            "global_shape": list(leaf["global_shape"]),
            "dtype": leaf["dtype"],
            "shards": [],
        }
        for si, (starts, arr) in enumerate(leaf["shards"]):
            name = f"l{li}_s{si}"
            arrays[name] = np.frombuffer(
                np.ascontiguousarray(arr).tobytes(), dtype=np.uint8
            )
            entry["shards"].append({
                "name": name,
                "start": [int(x) for x in starts],
                "shape": [int(x) for x in arr.shape],
            })
        manifest["leaves"].append(entry)
    npz = os.path.join(sdir, f"host{host:05d}.npz")
    man = os.path.join(sdir, f"host{host:05d}.json")

    def _write() -> None:
        with open(npz + ".tmp", "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(npz + ".tmp", npz)
        with open(man + ".tmp", "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(man + ".tmp", man)

    retry_io(_write, what=f"write {npz}")


def _attempt_token() -> Optional[str]:
    """The elastic supervisor's attempt id (``TPU_TRAINER_ATTEMPT``), or
    None for standalone runs. Stamped into DONE markers so the commit
    barrier only trusts markers from *this* attempt — see
    ``_markers_complete``."""
    return os.environ.get("TPU_TRAINER_ATTEMPT")


def _mark_host_done(path: str, *, host: int, world: int) -> None:
    """Phase 1b: atomic per-host DONE marker — this host's shards are
    durable. Written only after ``_write_host_shards`` returned."""
    cdir = os.path.join(path, _COMMIT_SUBDIR)
    os.makedirs(cdir, exist_ok=True)
    marker = os.path.join(cdir, f"host{host:05d}.done")

    def _write() -> None:
        with open(marker + ".tmp", "w") as f:
            json.dump({"host": host, "world": world,
                       "attempt": _attempt_token()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(marker + ".tmp", marker)

    retry_io(_write, what=f"write {marker}")


def _await_commit(
    path: str,
    ready: Callable[[], bool],
    *,
    what: str,
    timeout_s: Optional[float] = None,
) -> None:
    """Bounded filesystem barrier: poll ``ready`` with backoff until true or
    timeout. Deliberately not a jax collective — safe from the AsyncSaver's
    writer thread while the main thread runs step collectives; a peer that
    died mid-save surfaces as TimeoutError instead of a hang."""
    timeout_s = _barrier_timeout_s() if timeout_s is None else timeout_s
    deadline = time.monotonic() + timeout_s
    delay = 0.005
    while not ready():
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"checkpoint commit barrier timed out after {timeout_s:.0f}s "
                f"waiting for {what} in {path}"
            )
        time.sleep(delay)
        delay = min(delay * 2, 0.25)


def _markers_complete(path: str, world: int) -> bool:
    """All ``world`` DONE markers present, written for this world AND by
    this attempt.

    Counting marker files alone is not enough: a dead attempt's leftover
    markers in the same step dir (the elastic supervisor re-saves the same
    step after a restart on a shrunk world) could satisfy the barrier
    before the current attempt's hosts finished writing — committing a mix
    of fresh and stale shard files. Each marker records the world it was
    written for; a marker from a different factorization is ignored, and
    every re-saving host atomically overwrites its own marker.

    The world stamp alone stops being sufficient once the world can GROW
    back (``--allow_grow``): a 2→1→2 run can re-save a step whose dir holds
    a world-2 partial commit from the attempt *before* the shrink — same
    world, stale bytes. A grown attempt must not trust a marker it did not
    write, so markers also carry the supervisor's attempt id
    (``TPU_TRAINER_ATTEMPT``) and the barrier requires an exact match.
    Standalone runs (no supervisor) carry attempt None on both sides."""
    cdir = os.path.join(path, _COMMIT_SUBDIR)
    attempt = _attempt_token()
    for host in range(world):
        marker = os.path.join(cdir, f"host{host:05d}.done")
        try:
            with open(marker) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return False
        if not isinstance(rec, dict) or rec.get("world") != world:
            return False
        if rec.get("attempt") != attempt:
            return False
    return True


def _write_meta(path: str, meta: dict) -> None:
    """Atomic meta.json commit (tmp + fsync + rename): readers see either no
    meta or a complete one, never a torn write from a live host — torn metas
    on disk come only from real crashes (or the truncate_meta fault)."""
    meta_path = os.path.join(path, "meta.json")

    def _write() -> None:
        with open(meta_path + ".tmp", "w") as f:
            json.dump(meta, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(meta_path + ".tmp", meta_path)

    retry_io(_write, what=f"write {meta_path}")


def save_checkpoint(
    checkpoint_dir: str,
    state,
    *,
    model_config: GPTConfig,
    training_config: TrainingConfig,
    tokens_seen: int = 0,
    data_state: Optional[dict] = None,
    keep_last_n: int = 0,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    process_of_device=None,
) -> str:
    """Write a sharded checkpoint; returns its path.

    Every process participates (each writes its addressable shards); the
    meta.json is written by host 0 last, so a checkpoint without meta.json is
    incomplete and ignored by ``latest_checkpoint`` — the barrier-free
    analogue of the reference's save-then-barrier (``fsdp_trainer.py:465``).

    ``data_state`` (a loader ``state_dict()``) rides along in meta.json so a
    resumed run continues the data stream bit-exactly instead of re-reading
    the dataset head. ``keep_last_n > 0`` garbage-collects older completed
    checkpoints after this save lands.

    ``process_index``/``process_count``/``process_of_device`` are injectable
    seams (mirroring ``parallel/mesh.host_feed_info``) so tests can write a
    simulated N-host two-phase checkpoint from a single real process — call
    once per simulated host, host 0 last (host 0's call runs the commit
    barrier and writes meta).
    """
    step = int(jax.device_get(state.step))
    path = step_dir(checkpoint_dir, step)
    if getattr(state, "params_c", None) is not None:
        # Derived data (the compute-dtype param copy): stripping it keeps
        # the on-disk format identical to pre-carry checkpoints and saves
        # the copy's bytes; restore_checkpoint rebuilds it.
        state = state.replace(params_c=None)
    _commit_checkpoint(
        checkpoint_dir,
        path,
        state,
        step=step,
        model_config=model_config,
        training_config=training_config,
        tokens_seen=tokens_seen,
        data_state=data_state,
        keep_last_n=keep_last_n,
        use_async_writer=False,
        process_index=process_index,
        process_count=process_count,
        process_of_device=process_of_device,
    )
    return path


def _commit_checkpoint(
    checkpoint_dir: str,
    path: str,
    state_like,
    *,
    step: int,
    model_config: GPTConfig,
    training_config: TrainingConfig,
    tokens_seen: int,
    data_state: Optional[dict],
    keep_last_n: int,
    use_async_writer: bool,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    process_of_device=None,
) -> None:
    """The durable half of a save, shared by the sync path and AsyncSaver's
    writer thread: write every shard, fire the ``kill_in_save`` fault in the
    window where shards are durable but meta is not, commit meta.json
    (host 0), then GC. ``state_like`` is a TrainState of jax arrays (sync
    path), its ``jax.device_get`` host snapshot (single-process async path),
    or a :class:`_HostShardSnapshot` (multi-process async path).

    Single-process saves keep the orbax tree layout byte-identical to every
    prior release; anything with ``process_count > 1`` (real or injected)
    takes the two-phase host-shards commit, which contains no jax
    collectives and is therefore safe from the writer thread."""
    pidx = jax.process_index() if process_index is None else process_index
    pcount = jax.process_count() if process_count is None else process_count
    if pcount > 1 or isinstance(state_like, _HostShardSnapshot):
        simulated = process_index is not None or process_of_device is not None
        snapshot = (
            state_like
            if isinstance(state_like, _HostShardSnapshot)
            else host_shard_snapshot(
                state_like, process_of_device=process_of_device, host=pidx
            )
        )
        _commit_two_phase(
            checkpoint_dir, path, snapshot,
            step=step, model_config=model_config,
            training_config=training_config, tokens_seen=tokens_seen,
            data_state=data_state, keep_last_n=keep_last_n,
            host=pidx, world=pcount, simulated=simulated,
        )
        return
    state_path = os.path.join(path, "state")
    if use_async_writer and jax_compat.ORBAX_ASYNC_OK:
        # Orbax's own async machinery, when this version has it. We still
        # wait for durability here — the *caller* is the background thread,
        # so the step loop never sees this wait — because meta.json must
        # not land before every shard is on disk.
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        try:
            ckptr.save(state_path, args=ocp.args.StandardSave(state_like),
                       force=True)
            ckptr.wait_until_finished()
        finally:
            ckptr.close()
    else:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(state_path, state_like, force=True)
        ckptr.wait_until_finished()
    barrier("checkpoint_save")
    if faults.fire("kill_in_save", step):
        # Injected crash between the shard writes and the meta write: the
        # exact partial state a mid-save preemption leaves behind.
        faults.kill()
    if jax.process_index() == 0:
        _write_meta(path, _meta_dict(
            step=step, model_config=model_config,
            training_config=training_config, tokens_seen=tokens_seen,
            data_state=data_state,
        ))
    barrier("checkpoint_meta")
    if faults.fire("truncate_meta", step):
        faults.truncate_file(os.path.join(path, "meta.json"))
    if faults.fire("corrupt_shard", step):
        _corrupt_some_shard(path)
    if keep_last_n > 0:
        gc_checkpoints(checkpoint_dir, keep_last_n)


def _meta_dict(
    *,
    step: int,
    model_config: GPTConfig,
    training_config: TrainingConfig,
    tokens_seen: int,
    data_state: Optional[dict],
) -> dict:
    meta = {
        "step": step,
        "tokens_seen": int(tokens_seen),
        "model_config": dataclasses.asdict(model_config),
        "training_config": dataclasses.asdict(training_config),
    }
    if data_state is not None:
        meta["data_state"] = data_state
    return meta


def _commit_two_phase(
    checkpoint_dir: str,
    path: str,
    snapshot: _HostShardSnapshot,
    *,
    step: int,
    model_config: GPTConfig,
    training_config: TrainingConfig,
    tokens_seen: int,
    data_state: Optional[dict],
    keep_last_n: int,
    host: int,
    world: int,
    simulated: bool,
) -> None:
    """Multi-process two-phase commit (one call per process).

    Phase 1: write this host's shards, then its atomic DONE marker.
    Phase 2: host 0 waits (bounded, filesystem-only) for all ``world``
    markers and writes meta.json last; other hosts wait (bounded) for meta
    so a ``wait=True`` save is durable on every host when it returns. The
    ``kill_in_save`` fault fires between marker and meta — dying there
    leaves a meta-less tree every scan ignores, the same crash contract as
    the single-process path.

    ``simulated`` (injected process seams, one real process playing several
    hosts sequentially) skips the cross-host waits except host 0's marker
    check, which is then an instant all-present assertion — run host 0
    last.
    """
    _write_host_shards(path, snapshot, host=host, world=world)
    _mark_host_done(path, host=host, world=world)
    if faults.fire("kill_in_save", step):
        # Injected crash in the window where this host's shards and marker
        # are durable but meta is not: the checkpoint stays invisible to
        # every scan — exactly what a real mid-commit host death leaves.
        faults.kill()
    if host == 0:
        _await_commit(
            path,
            lambda: _markers_complete(path, world),
            what=f"{world} host DONE markers",
            timeout_s=1.0 if simulated else None,
        )
        _write_meta(path, dict(_meta_dict(
            step=step, model_config=model_config,
            training_config=training_config, tokens_seen=tokens_seen,
            data_state=data_state,
        ), format=HOST_SHARDS_FORMAT, shard_world=world))
        if faults.fire("truncate_meta", step):
            faults.truncate_file(os.path.join(path, "meta.json"))
        if faults.fire("corrupt_shard", step):
            _corrupt_some_shard(path)
        if keep_last_n > 0:
            # Non-collective GC: host 0 deletes alone (sync=False) — this
            # may run on the async writer thread where a jax barrier would
            # race the main thread's step collectives.
            gc_checkpoints(checkpoint_dir, keep_last_n, sync=False)
    elif not simulated:
        _await_commit(
            path,
            lambda: os.path.exists(os.path.join(path, "meta.json")),
            what="meta.json from host 0",
        )


_SYNC_FALLBACK_WARNED = False


def warn_sync_fallback(reason: str) -> bool:
    """One-time (per process) warning that an async save degraded to the
    synchronous path, so the full save cost lands on the step critical path.
    Returns True when the warning was emitted, False when already warned —
    the cost itself shows up under ``checkpoint_save`` in the goodput
    ledger, which is exactly where callers attribute the blocking
    ``AsyncSaver.save()`` call."""
    global _SYNC_FALLBACK_WARNED
    if _SYNC_FALLBACK_WARNED:
        return False
    _SYNC_FALLBACK_WARNED = True
    print(
        f"WARNING: async checkpointing degraded to a synchronous save "
        f"({reason}); save cost is on the step critical path and attributed "
        f"to checkpoint_save in the goodput ledger",
        file=sys.stderr, flush=True,
    )
    return True


class AsyncSaver:
    """Background checkpoint writer: snapshot now, commit later.

    ``save()`` blocks only for the device→host copy of the train state (the
    *snapshot* — mandatory anyway, because ``train_step`` donates the state
    buffers and the very next step would overwrite what orbax is reading),
    then hands the host tree to a writer thread that runs the same commit
    sequence as :func:`save_checkpoint`: shards → ``kill_in_save`` fault
    window → meta.json → GC. The crash-safety contract is unchanged — a
    checkpoint is complete iff meta.json parses, and an injected or real
    death mid-commit leaves a meta-less tree that every scan ignores.

    At most one save is in flight: ``save()`` drains the previous commit
    first (callers attribute that wait to ``checkpoint_commit_wait`` in the
    goodput ledger), and rollback/SIGTERM/exit paths call ``wait()`` before
    restoring or returning. The writer is a daemon thread, so an injected
    ``kill_in_save`` (``os._exit``) or a real SIGKILL dies exactly like the
    sync path — mid-commit, meta unwritten.

    Multi-process runs stay async too: the snapshot captures this process's
    *addressable* shards and the writer thread runs the two-phase commit,
    whose commit barrier is pure filesystem polling — no jax collectives
    that could race the main thread's step collectives (the reason the old
    implementation degraded to synchronous saves at ``process_count > 1``).
    A defensive synchronous fallback remains for snapshot failures, behind
    a one-time warning so the degradation is visible.
    """

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._path: Optional[str] = None

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Drain the in-flight commit (if any); returns its path. Re-raises
        a writer-thread failure here, on the step loop's thread, so a bad
        disk surfaces as a crash-with-traceback instead of silent loss of
        every subsequent checkpoint.

        ``timeout`` (seconds) bounds the drain — the ``--preemption_grace_s``
        path must not let one slow commit eat the whole grace window. On
        timeout, returns None with the commit still in flight (the daemon
        writer dies with the process, leaving the usual meta-less tree)."""
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                return None
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        return self._path

    def save(
        self,
        checkpoint_dir: str,
        state,
        *,
        model_config: GPTConfig,
        training_config: TrainingConfig,
        tokens_seen: int = 0,
        data_state: Optional[dict] = None,
        keep_last_n: int = 0,
    ) -> str:
        """Snapshot ``state`` to host and schedule the commit; returns the
        checkpoint path (which is complete only once the commit lands —
        ``wait()`` to require it)."""
        self.wait()
        if getattr(state, "params_c", None) is not None:
            state = state.replace(params_c=None)
        # The snapshot: blocks until every pending step that writes into
        # this state has finished and the bytes are host-side. This is the
        # whole synchronous cost of an async save.
        if jax.process_count() > 1:
            try:
                snapshot = host_shard_snapshot(state)
                step = int(jax.device_get(state.step))
            except Exception as e:
                # Defensive only: an addressable-shard snapshot failing is
                # unexpected, but losing async-ness silently was the old
                # behavior this PR removes — degrade loudly instead.
                warn_sync_fallback(f"{type(e).__name__}: {e}")
                return save_checkpoint(
                    checkpoint_dir, state,
                    model_config=model_config,
                    training_config=training_config,
                    tokens_seen=tokens_seen, data_state=data_state,
                    keep_last_n=keep_last_n,
                )
        else:
            snapshot = jax.device_get(state)
            step = int(snapshot.step)
        path = step_dir(checkpoint_dir, step)

        def _commit() -> None:
            try:
                _commit_checkpoint(
                    checkpoint_dir, path, snapshot,
                    step=step, model_config=model_config,
                    training_config=training_config, tokens_seen=tokens_seen,
                    data_state=data_state, keep_last_n=keep_last_n,
                    use_async_writer=True,
                )
            except BaseException as e:  # surfaced by the next wait()
                self._error = e

        self._path = path
        self._thread = threading.Thread(
            target=_commit, name=f"ckpt-commit-{step}", daemon=True
        )
        self._thread.start()
        return path


def _corrupt_some_shard(path: str) -> None:
    """Byte-flip every file under <path>/state (orbax layout) and
    <path>/shards (host_shards layout) — the injected version of storage
    corruption (driven by the corrupt_shard fault). All files, not a
    sample: tensorstore does not checksum every byte it reads back, so
    flipping one data chunk can restore "successfully" as garbage — the
    fault must deterministically fail the restore for the quarantine path
    to be testable. (npz IS integrity-checked: a flipped byte fails the
    zip CRC on load, which is the deterministic failure we need.)"""
    for sub in ("state", _SHARDS_SUBDIR):
        for root, _, names in os.walk(os.path.join(path, sub)):
            for name in names:
                faults.corrupt_file(os.path.join(root, name))


def load_meta(path: str) -> dict:
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)


def _assemble_host_shards(
    path: str,
    abstract,
    *,
    expected_world: Optional[int] = None,
    key_prefix: str = "",
):
    """Reassemble a host_shards checkpoint onto ``abstract``'s shardings.

    Reads every host's manifest + npz, stitches the global numpy array per
    leaf key, and builds jax arrays via ``make_array_from_callback`` — so a
    checkpoint written by mesh A restores onto mesh B with a different
    process count or ``data x fsdp`` factorization (every host file is
    visible on the shared checkpoint filesystem). Raises ValueError on
    missing host files or leaf keys (→ the quarantine/fallback path in
    ``restore_latest``); a flipped byte fails the npz CRC the same way.

    ``key_prefix`` maps ``abstract``'s leaf paths into the saved TrainState
    key space (e.g. ``.params`` when ``abstract`` is the bare params tree).
    """
    sdir = os.path.join(path, _SHARDS_SUBDIR)
    try:
        manifests = sorted(
            n for n in os.listdir(sdir)
            if n.startswith("host") and n.endswith(".json")
        )
    except OSError as e:
        raise ValueError(f"unreadable shards dir {sdir}: {e}")
    if expected_world is not None and len(manifests) < expected_world:
        raise ValueError(
            f"host_shards checkpoint {path} incomplete: "
            f"{len(manifests)}/{expected_world} host manifests"
        )
    globals_np: Dict[str, np.ndarray] = {}
    for man_name in manifests:
        with open(os.path.join(sdir, man_name)) as f:
            manifest = json.load(f)
        npz_name = man_name[:-len(".json")] + ".npz"
        with np.load(os.path.join(sdir, npz_name)) as data:
            for leaf in manifest["leaves"]:
                dtype = _resolve_dtype(leaf["dtype"])
                shape = tuple(leaf["global_shape"])
                buf = globals_np.get(leaf["key"])
                if buf is None:
                    buf = np.zeros(shape, dtype=dtype)
                    globals_np[leaf["key"]] = buf
                for sh in leaf["shards"]:
                    arr = np.frombuffer(
                        data[sh["name"]].tobytes(), dtype=dtype
                    ).reshape(sh["shape"])
                    idx = tuple(
                        slice(st, st + ln)
                        for st, ln in zip(sh["start"], sh["shape"])
                    )
                    buf[idx] = arr
    leaves, treedef = jax.tree_util.tree_flatten_with_path(abstract)
    out = []
    for key_path, s in leaves:
        key = key_prefix + jax.tree_util.keystr(key_path)
        if key not in globals_np:
            raise ValueError(
                f"host_shards checkpoint {path} is missing leaf {key!r}"
            )
        buf = globals_np[key]
        if tuple(buf.shape) != tuple(s.shape):
            raise ValueError(
                f"host_shards leaf {key!r} has shape {buf.shape}, "
                f"expected {s.shape}"
            )
        if buf.dtype != s.dtype:
            buf = buf.astype(s.dtype)
        out.append(jax.make_array_from_callback(
            tuple(s.shape), s.sharding, lambda idx, b=buf: b[idx]
        ))
    return jax.tree_util.tree_unflatten(treedef, out)


def _pick_export_axis(shape: Tuple[int, ...], world: int) -> Optional[int]:
    """Wire-slicing rule for :func:`export_param_shards`: the largest axis
    with at least ``world`` elements (ties -> lowest axis index), or None
    to ship the leaf whole in worker 0's file. Deliberately looser than
    the device-placement rule (serving/sharding.pick_shard_axis, which
    needs exact divisibility for ``NamedSharding``): the wire layout is
    independent of the device layout — workers stitch the full tree and
    the engine re-commits it to its own mesh — so near-equal chunks of a
    non-divisible axis (a 50257-row embedding over tp=8) still split and
    keep per-worker bytes ~ P/world."""
    best = None
    for ax, n in enumerate(shape):
        if n >= world and (best is None or n > shape[best]):
            best = ax
    return best


def export_param_shards(params, path: str, *, world: int) -> str:
    """Write an inference params tree as a ``world``-way sharded
    two-phase ``host_shards`` checkpoint — the shard-streaming serving
    launch format. Worker *i* of a tp=``world`` fleet ships (or mounts)
    only ``shards/host0000i.npz`` — ~P/world bytes — instead of a full
    npz copy per worker; :func:`load_param_shards` reassembles.

    Each leaf splits into near-equal contiguous chunks on its largest
    axis (see ``_pick_export_axis``); leaves too small to split ride
    whole in worker 0's file. Slicing is pure ``np.ndarray`` copying —
    byte-lossless round-trip, no dtype or value changes — and reuses the
    training checkpoint's shard/manifest/DONE-marker/meta machinery, so
    the on-disk format (and its torn-write crash contract) is the one
    restore tooling already understands. ``params`` is a (possibly
    nested) dict of arrays; keys are joined with ``/``."""
    if world < 1:
        raise ValueError(f"world={world} < 1")
    flat: Dict[str, np.ndarray] = {}

    def walk(prefix: str, node) -> None:
        if isinstance(node, dict):
            for k in sorted(node):
                walk(f"{prefix}/{k}" if prefix else str(k), node[k])
        else:
            flat[prefix] = np.asarray(node)

    walk("", params)
    for host in range(world):
        snap = _HostShardSnapshot()
        for key, arr in flat.items():
            ax = _pick_export_axis(arr.shape, world) if world > 1 else None
            if ax is None:
                shards = (
                    [(tuple(0 for _ in arr.shape), arr)] if host == 0
                    else [])
            else:
                n = arr.shape[ax]
                base, extra = divmod(n, world)
                start = host * base + min(host, extra)
                size = base + (1 if host < extra else 0)
                sl = [slice(None)] * arr.ndim
                sl[ax] = slice(start, start + size)
                starts = tuple(
                    start if a == ax else 0 for a in range(arr.ndim))
                shards = [(starts, np.ascontiguousarray(arr[tuple(sl)]))]
            snap.append({
                "key": key,
                "global_shape": tuple(arr.shape),
                "dtype": str(arr.dtype),
                "shards": shards,
            })
        _write_host_shards(path, snap, host=host, world=world)
        _mark_host_done(path, host=host, world=world)
    _write_meta(path, {
        "format": HOST_SHARDS_FORMAT,
        "shard_world": world,
        "kind": "param_shards",
    })
    return path


def load_param_shards(path: str) -> dict:
    """Stitch an :func:`export_param_shards` directory back into the
    nested numpy params dict (byte-identical to the exported tree). The
    meta/manifest completeness checks mirror ``_assemble_host_shards``:
    a missing host file or torn meta raises ValueError rather than
    returning a silently partial tree."""
    meta = load_meta(path)
    if meta.get("format") != HOST_SHARDS_FORMAT:
        raise ValueError(f"{path} is not a host_shards export")
    world = meta.get("shard_world")
    sdir = os.path.join(path, _SHARDS_SUBDIR)
    try:
        manifests = sorted(
            n for n in os.listdir(sdir)
            if n.startswith("host") and n.endswith(".json")
        )
    except OSError as e:
        raise ValueError(f"unreadable shards dir {sdir}: {e}")
    if world is not None and len(manifests) < world:
        raise ValueError(
            f"param_shards export {path} incomplete: "
            f"{len(manifests)}/{world} host manifests")
    globals_np: Dict[str, np.ndarray] = {}
    for man_name in manifests:
        with open(os.path.join(sdir, man_name)) as f:
            manifest = json.load(f)
        npz_name = man_name[:-len(".json")] + ".npz"
        with np.load(os.path.join(sdir, npz_name)) as data:
            for leaf in manifest["leaves"]:
                dtype = _resolve_dtype(leaf["dtype"])
                shape = tuple(leaf["global_shape"])
                buf = globals_np.get(leaf["key"])
                if buf is None:
                    buf = np.zeros(shape, dtype=dtype)
                    globals_np[leaf["key"]] = buf
                for sh in leaf["shards"]:
                    arr = np.frombuffer(
                        data[sh["name"]].tobytes(), dtype=dtype
                    ).reshape(sh["shape"])
                    idx = tuple(
                        slice(st, st + ln)
                        for st, ln in zip(sh["start"], sh["shape"])
                    )
                    buf[idx] = arr
    out: dict = {}
    for key, arr in globals_np.items():
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def remap_data_state(
    data_state: Optional[dict],
    *,
    new_global_batch_size: int,
    new_feed_world: Optional[int] = None,
) -> Tuple[Optional[dict], int]:
    """Remap a persisted loader cursor onto a resized run; returns
    ``(new_state, replayed_sequences)``.

    The cursor's stream position is ``batch_index * global_batch_size``
    sequences consumed (loader sharding reconstructs each rank's slice from
    the global position, so a changed ``feed_world`` alone needs no index
    change). When the global batch size differs, the index floor-divides
    onto the new batch granularity::

        new_index = (batch_index * old_gbs) // new_gbs

    The flooring means up to one new-sized batch of already-seen sequences
    replays — the documented **at-least-once** window, at batch
    granularity; sequences are never skipped. Exact for the dummy and
    map-style text loaders (their global order is independent of the feed
    world); best-effort at the same granularity for the streaming loader,
    whose line-modulo shards re-partition with the feed world.

    A ``kind == "mixture"`` cursor (``data/mixture.py``) needs no special
    casing here: its top-level ``batch_index`` remaps exactly like any
    other, and ``MixtureDataLoader.load_state_dict`` re-derives the
    per-source sub-cursors from the remapped index (the draw counts are a
    pure function of ``(seed, weights, batch_index)``), so the ``sources``
    sub-dicts passing through untouched is correct.
    """
    if data_state is None:
        return None, 0
    st = dict(data_state)
    if new_feed_world is not None:
        st["feed_world"] = int(new_feed_world)
    old_gbs = st.get("global_batch_size")
    st["global_batch_size"] = int(new_global_batch_size)
    if not old_gbs or int(old_gbs) == int(new_global_batch_size):
        return st, 0
    consumed = int(st.get("batch_index", 0)) * int(old_gbs)
    new_index = consumed // int(new_global_batch_size)
    st["batch_index"] = new_index
    return st, consumed - new_index * int(new_global_batch_size)


def restore_checkpoint(path: str, trainer) -> Tuple[Any, dict]:
    """Restore a TrainState onto the trainer's mesh/sharding (resharding as
    needed) plus the saved metadata. ``trainer`` is a
    ``tpu_trainer.training.trainer.Trainer``.

    Raises ValueError (naming the differing config fields) when the saved
    model shapes don't match the trainer's — otherwise a stale checkpoint
    dir surfaces as an impenetrable orbax shape error mid-restore (the
    auto-resume path makes this easy to hit: same ``--checkpoint_dir``,
    different ``--model_size``)."""
    path = os.path.abspath(path)  # orbax requires absolute paths
    meta = load_meta(path)
    shapes = jax.eval_shape(trainer._make_state, jax.random.PRNGKey(0))
    saved_cfg = meta.get("model_config")
    now = dataclasses.asdict(trainer.model_config)
    # Cheap dict compare first: the common auto-resume case (identical
    # config) must not pay a second full-model trace. Only on a config
    # delta do we check whether it is SHAPE-bearing (dtype/dropout/knob
    # changes restore fine), and a saved config this build can't even
    # construct (renamed/removed fields across versions) counts as
    # incompatible rather than dying on a bare TypeError.
    if saved_cfg is not None and saved_cfg != now:
        from tpu_trainer.models.gpt import GPT  # local: avoid cycle

        known = {f.name for f in dataclasses.fields(GPTConfig)}
        mismatch = any(k not in known for k in saved_cfg)
        if not mismatch:
            try:
                saved_shapes = jax.eval_shape(
                    lambda rng: GPT(GPTConfig(**saved_cfg)).init(
                        rng, np.zeros((1, 8), np.int32)
                    )["params"],
                    jax.random.PRNGKey(0),
                )
                here = jax.tree_util.tree_map(
                    lambda s: s.shape, shapes.params)
                there = jax.tree_util.tree_map(
                    lambda s: s.shape, saved_shapes)
                mismatch = here != there
            except Exception:
                mismatch = True
        if mismatch:
            diff = sorted(
                k for k in set(saved_cfg) | set(now)
                if saved_cfg.get(k) != now.get(k)
            )
            raise CheckpointIncompatibleError(
                f"checkpoint {path} holds an incompatible model "
                f"(differing config fields: {', '.join(diff) or 'shapes'}); "
                f"point --checkpoint_dir at a fresh directory, pass "
                f"--no_auto_resume to start over, or match the saved config"
            )
    # A different on-device Adam storage dtype changes the opt_state TREE
    # (quantized moments are QuantPack nodes — utils/quant.py) — fail with
    # the knob's name instead of an orbax structure error.
    saved_tc = meta.get("training_config") or {}
    saved_osd = saved_tc.get("optimizer_state_dtype", "float32")
    now_osd = trainer.training_config.optimizer_state_dtype
    if saved_osd != now_osd:
        raise CheckpointIncompatibleError(
            f"checkpoint {path} was saved with optimizer_state_dtype="
            f"{saved_osd!r} but this run uses {now_osd!r}; pass "
            f"--optimizer_state_dtype {saved_osd} to resume it"
        )
    # Checkpoints never hold params_c (stripped on save — derived data);
    # restore against the stripped structure, then rebuild the copy.
    shapes = shapes.replace(params_c=None)
    shardings = trainer.state_shardings
    if getattr(shardings, "params_c", None) is not None:
        shardings = shardings.replace(params_c=None)
    abstract = jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        shardings,
    )
    if meta.get("format") == HOST_SHARDS_FORMAT:
        # Two-phase multi-process checkpoint: reassemble the global arrays
        # from every host's shard file and place onto this trainer's mesh —
        # the saved and restoring process counts are fully decoupled.
        state = _assemble_host_shards(
            path, abstract, expected_world=meta.get("shard_world")
        )
    else:
        state = ocp.StandardCheckpointer().restore(
            os.path.join(path, "state"), abstract)
    return trainer.with_params_c(state), meta


def restore_latest(
    checkpoint_dir: str,
    trainer,
    *,
    verify: bool = True,
) -> Optional[Tuple[Any, dict, str]]:
    """Restore the newest loadable checkpoint; ``(state, meta, path)`` or
    ``None`` when the directory holds no completed checkpoint.

    With ``verify=True`` (the auto-resume path), a checkpoint that fails to
    load — corrupt shards, torn files, a meta.json that parses but lies —
    is quarantined (renamed ``*.corrupt``) and the scan falls back to the
    previous valid step, so one bad save never bricks a multi-day run.
    ``CheckpointIncompatibleError`` (config mismatch, a user error) always
    propagates: silently skipping it would restart training from step 0.
    """
    for _, path in reversed(list_checkpoints(checkpoint_dir)):
        try:
            state, meta = restore_checkpoint(path, trainer)
            return state, meta, path
        except CheckpointIncompatibleError:
            raise
        except Exception as e:
            if not verify:
                raise
            dest = quarantine_checkpoint(path)
            print(
                f"checkpoint {path} failed to load "
                f"({type(e).__name__}: {e}); quarantined to {dest}, "
                f"falling back to the previous step",
                file=sys.stderr, flush=True,
            )
    return None


def restore_params(path: str):
    """Restore only the model params — the inference path (↔ reference
    ``infer.py:53-57``, minus the pickle shims). Accepts a step dir (builds a
    trainer from the checkpoint's own meta.json and restores onto the default
    devices) or a consolidated ``.msgpack`` file. Returns ``(params, config)``.
    """
    path = os.path.abspath(path)  # orbax requires absolute paths
    if os.path.isfile(path):  # consolidated export
        import flax.serialization as ser

        with open(path, "rb") as f:
            return ser.msgpack_restore(f.read()), None
    meta = load_meta(path)
    from tpu_trainer.models.gpt import GPT  # local: avoid cycle

    config = GPTConfig(**meta["model_config"])
    shapes = jax.eval_shape(
        lambda rng: GPT(config).init(rng, np.zeros((1, 8), np.int32))["params"],
        jax.random.PRNGKey(0),
    )
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding), shapes
    )
    if meta.get("format") == HOST_SHARDS_FORMAT:
        # The saved keys are TrainState paths; the abstract tree here is the
        # bare params dict — bridge with the ".params" attribute prefix.
        params = _assemble_host_shards(
            path, abstract, expected_world=meta.get("shard_world"),
            key_prefix=".params",
        )
        return params, config
    # Partial restore: only the params subtree is read — an xl inference load
    # must not pull the (2x param-sized) Adam moments off disk.
    try:
        args = ocp.args.PyTreeRestore(
            item={"params": abstract}, partial_restore=True
        )
    except TypeError:
        # Pre-partial_restore orbax (<= 0.7): the legacy transforms API
        # spells the same thing as "restore item's keys only", but then
        # insists on explicit per-leaf restore_args.
        restore_args = jax.tree_util.tree_map(
            lambda s: ocp.ArrayRestoreArgs(
                sharding=sharding, dtype=s.dtype, global_shape=s.shape
            ),
            shapes,
        )
        args = ocp.args.PyTreeRestore(
            item={"params": abstract}, transforms={},
            restore_args={"params": restore_args},
        )
    restored = ocp.PyTreeCheckpointer().restore(os.path.join(path, "state"),
                                                args=args)
    return restored["params"], config


def export_consolidated(path: str, params, out_path: Optional[str] = None) -> str:
    """Gather params to host 0 and write one msgpack file (↔ the reference's
    single-file ``torch.save`` artifact, C17/C18 'export path')."""
    import flax.serialization as ser

    out_path = out_path or os.path.join(path, "params.msgpack")
    if jax.process_count() > 1:
        # Shards live on non-addressable devices: gather across processes
        # first (np.asarray alone would raise on a multi-host sharded array).
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(params, tiled=True)
    else:
        gathered = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
    if jax.process_index() == 0:
        with open(out_path, "wb") as f:
            f.write(ser.msgpack_serialize(gathered))
    barrier("export_consolidated")
    return out_path
