"""Crash flight recorder: last-N JSONL records + run snapshot on failure.

When a run dies — SIGTERM from the scheduler, a divergence that exhausts
its rollback budget, an unhandled exception — the JSONL on disk shows the
*emitted* history but not the run's identity (configs, mesh, env, jax
version) in one artifact, and a preempted pod may not flush anything at
all. The flight recorder keeps a bounded in-memory ring of every record
the MetricLogger emits plus a one-time environment snapshot, and dumps
both as ``crash_report.json`` (atomic write) from the existing
SIGTERM/rollback/fault paths in ``run_training``. Postmortem = one file.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import sys
import time
import traceback
from typing import Any, Optional

from tpu_trainer.utils.logging import SCHEMA_VERSION

_ENV_PREFIXES = ("JAX", "XLA", "TPU", "LIBTPU", "TF_CPP")


def env_snapshot(trainer=None, model_config=None, training_config=None,
                 argv=None) -> dict:
    """One-time run-identity snapshot: versions, devices, mesh, configs,
    accelerator-relevant env vars, argv. Everything best-effort — a
    snapshot field that fails to collect is omitted, never fatal."""
    snap: dict = {
        "python": sys.version.split()[0],
        "argv": list(argv) if argv is not None else sys.argv[1:],
        "env": {
            k: v for k, v in sorted(os.environ.items())
            if any(k.startswith(p) for p in _ENV_PREFIXES)
        },
    }
    try:
        import jax

        snap["jax_version"] = jax.__version__
        dev = jax.devices()[0]
        snap["platform"] = dev.platform
        snap["device_kind"] = getattr(dev, "device_kind", "unknown")
        snap["device_count"] = jax.device_count()
        snap["process_index"] = jax.process_index()
        snap["process_count"] = jax.process_count()
    except Exception:
        pass
    if trainer is not None:
        try:
            snap["mesh"] = dict(trainer.mesh.shape)
            snap["strategy"] = trainer.strategy
        except Exception:
            pass
    for name, cfg in (("model_config", model_config),
                      ("training_config", training_config)):
        if cfg is not None:
            try:
                snap[name] = dataclasses.asdict(cfg)
            except Exception:
                pass
    return snap


class FlightRecorder:
    """Bounded ring of emitted JSONL records + snapshot, dumpable on crash.

    Fed by ``MetricLogger(recorder=...)`` — every record that reaches the
    JSONL also lands here, so the ring IS the tail of the metrics stream
    (train/eval/goodput/telemetry/comms_model/recompile/rollback alike).
    """

    def __init__(self, capacity: int = 256, snapshot: Optional[dict] = None):
        self.capacity = int(capacity)
        self.snapshot = snapshot or {}
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)

    def observe(self, record: dict) -> None:
        self._ring.append(record)

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, directory: str, *, reason: str,
             exc: Optional[BaseException] = None,
             step: Optional[int] = None) -> str:
        """Write ``crash_report.json`` under ``directory`` and return its
        path. Atomic (tmp + rename): a crash during the dump never leaves
        a torn report. Non-zero hosts write ``crash_report_host{k}.json``.
        The last dump of a run wins — later events overwrite earlier ones,
        which is the postmortem-relevant ordering."""
        host = 0
        try:
            import jax

            host = jax.process_index()
        except Exception:
            pass
        name = ("crash_report.json" if host == 0
                else f"crash_report_host{host}.json")
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, name)
        report: dict = {
            "kind": "crash_report",
            "schema_version": SCHEMA_VERSION,
            "reason": reason,
            "step": step,
            "written_unix": time.time(),
            "exception": _format_exc(exc),
            "snapshot": self.snapshot,
            "records": list(self._ring),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, indent=1, default=str)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path


def _format_exc(exc: Optional[BaseException]) -> Optional[dict]:
    if exc is None:
        return None
    return {
        "type": type(exc).__name__,
        "message": str(exc),
        "traceback": "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__)),
    }


class HeartbeatWriter:
    """Per-host liveness beats for the elastic run supervisor.

    One JSONL file per host (``heartbeat_host{k}.jsonl`` — per-host files,
    so concurrent writers never interleave), one line per training step,
    plus an entry beat at loop start (``step == start_step``) so a host is
    live before its first multi-second compile::

        {"kind": "heartbeat", "host": k, "pid": ..., "step": n,
         "unix": t, "schema_version": ...}

    The supervisor (``training/elastic.py``) reads only the tail: a host
    whose newest beat is older than the heartbeat timeout is declared hung
    even though its process is still alive — the failure mode exit codes
    cannot catch. ``stop()`` freezes the stream without killing the process
    (what the ``hang_host`` chaos fault drives).

    Beats share the flight-recorder dump directory and record shape (same
    schema_version), so a recovery timeline reads straight out of the run
    dir: heartbeats flatline -> supervisor death record -> restart beats.
    ``min_interval_s`` throttles beat *writes* (a beat arriving inside the
    window is dropped); 0 writes every step.
    """

    def __init__(self, directory: str, *, host: int,
                 min_interval_s: float = 0.0,
                 recorder: Optional[FlightRecorder] = None,
                 start_step: Optional[int] = None):
        self.host = int(host)
        self.min_interval_s = float(min_interval_s)
        # start_step = the step this attempt resumed at: every beat carries
        # it so the supervisor can compute rolled-back work exactly
        # (last beat of the dead attempt minus the next attempt's
        # start_step) without having to catch the first beat in flight.
        self.start_step = None if start_step is None else int(start_step)
        self.path = os.path.join(
            directory, f"heartbeat_host{self.host:05d}.jsonl")
        self._recorder = recorder
        self._stopped = False
        self._last_write = 0.0
        os.makedirs(directory, exist_ok=True)

    def stop(self) -> None:
        """Freeze the beat stream (the hang_host fault): the process keeps
        running but looks dead to the supervisor's staleness check."""
        self._stopped = True

    def beat(self, step: int) -> None:
        if self._stopped:
            return
        now = time.time()
        if self.min_interval_s and now - self._last_write < self.min_interval_s:
            return
        self._last_write = now
        record = {
            "kind": "heartbeat",
            "schema_version": SCHEMA_VERSION,
            "host": self.host,
            "pid": os.getpid(),
            "step": int(step),
            "unix": now,
        }
        if self.start_step is not None:
            record["start_step"] = self.start_step
        if self._recorder is not None:
            self._recorder.observe(record)
        try:
            with open(self.path, "a") as fh:
                fh.write(json.dumps(record) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            # Liveness reporting must never kill the run it reports on; a
            # beat lost to a transient FS error just looks like one slow
            # step to the supervisor.
            pass


def read_heartbeat(directory: str, host: int) -> Optional[dict]:
    """Newest beat of ``host``'s stream, or None before its first beat.
    Tail-read only — beat files grow unboundedly during long runs and the
    supervisor polls this every few hundred ms."""
    path = os.path.join(directory, f"heartbeat_host{host:05d}.jsonl")
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 4096))
            lines = fh.read().splitlines()
    except OSError:
        return None
    for raw in reversed(lines):
        try:
            rec = json.loads(raw)
        except ValueError:
            continue  # torn tail line mid-write
        if isinstance(rec, dict) and rec.get("kind") == "heartbeat":
            return rec
    return None


def write_drain(directory: str, host: int, *, step: int, cause: str,
                deadline_unix=None) -> str:
    """Deregister ``host`` from the attempt: an atomic drain marker in the
    heartbeat directory, written by a proactively-draining host (preemption
    notice received) *before* it exits. The supervisor reads these to tell
    a planned departure (reform without this host, nobody crashed) from a
    crash (every other exit path). Per-attempt heartbeat dirs make the
    markers self-scoping, like the beats."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"drain_host{int(host):05d}.json")
    record = {
        "kind": "drain",
        "schema_version": SCHEMA_VERSION,
        "host": int(host),
        "pid": os.getpid(),
        "step": int(step),
        "cause": cause,
        "deadline_unix": deadline_unix,
        "unix": time.time(),
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        fh.write(json.dumps(record))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def read_drains(directory: str) -> list:
    """All drain markers of an attempt's heartbeat dir (sorted by host)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in sorted(names):
        if not (name.startswith("drain_host") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            continue  # torn marker: the atomic replace makes this transient
        if isinstance(rec, dict) and rec.get("kind") == "drain":
            out.append(rec)
    return out
