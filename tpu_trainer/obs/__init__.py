"""Live telemetry plane (ISSUE 18).

``obs.metrics`` is the dependency-free registry core (Counter / Gauge /
Histogram with Prometheus text exposition); ``obs.http`` is the
stdlib-only scrape surface (``/metrics`` + ``/healthz`` + ``/statusz``
on a daemon thread). Everything is host-side Python: a run with no
registry installed pays a no-op attribute call per instrumentation
site, and output streams are bit-identical with metrics on or off.
"""

from tpu_trainer.obs.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    NULL_REGISTRY,
    MetricsRegistry,
    NullRegistry,
)
