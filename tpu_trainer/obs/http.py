"""Stdlib-only HTTP plane for the metrics registry.

``MetricsServer`` runs a ``ThreadingHTTPServer`` on a daemon thread and
serves three read-only endpoints:

- ``/metrics`` — the registry in Prometheus text exposition v0.0.4
  (``text/plain; version=0.0.4``), scrape-ready.
- ``/healthz`` — liveness + readiness. Liveness is the server itself
  (the process answers ⇒ alive; ``close()`` flips it for the final
  scrape race). Readiness is the AND of component-registered probes
  (``add_probe(name, fn)`` — e.g. the serving front-end's "at least
  one live replica"); status 200 when ready, 503 when not, body a JSON
  per-probe breakdown either way.
- ``/statusz`` — a human-readable JSON snapshot of fleet/run state,
  produced by the registered ``statusz_fn`` at request time.

Everything is pull: the hot path never blocks on the scrape side, and
the scrape side reads shared state under the registry lock only. No
endpoint touches the device.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class HealthState:
    """Liveness flag + named readiness probes.

    ``ready()`` is the AND of every probe (a probe that *raises* counts
    as not ready — a crashing health check must fail closed). Probes
    are cheap host-side closures over component state; components flip
    readiness by their own state changing, not by pushing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._live = True
        self._probes: Dict[str, Callable[[], bool]] = {}

    def add_probe(self, name: str, fn: Callable[[], bool]) -> None:
        with self._lock:
            self._probes[name] = fn

    def remove_probe(self, name: str) -> None:
        with self._lock:
            self._probes.pop(name, None)

    def set_live(self, live: bool) -> None:
        with self._lock:
            self._live = bool(live)

    def report(self) -> dict:
        with self._lock:
            probes = dict(self._probes)
            live = self._live
        results = {}
        for name, fn in sorted(probes.items()):
            try:
                results[name] = bool(fn())
            except Exception:
                results[name] = False
        return {
            "live": live,
            "ready": live and all(results.values()),
            "probes": results,
        }


class _Handler(BaseHTTPRequestHandler):
    # Set per-server via the factory in MetricsServer.
    server_version = "tpu-trainer-obs/1"

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        owner: "MetricsServer" = self.server._owner  # type: ignore
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = owner.registry.exposition().encode()
                self._send(200, body, PROM_CONTENT_TYPE)
            elif path == "/healthz":
                report = owner.health.report()
                code = 200 if report["ready"] else 503
                self._send(code, (json.dumps(report, indent=1) + "\n")
                           .encode(), "application/json")
            elif path == "/statusz":
                status = owner.statusz_fn() if owner.statusz_fn else {}
                self._send(200, (json.dumps(status, indent=1, default=str)
                                 + "\n").encode(), "application/json")
            elif path == "/":
                self._send(200, b"/metrics /healthz /statusz\n",
                           "text/plain; charset=utf-8")
            else:
                self._send(404, b"not found\n", "text/plain; charset=utf-8")
        except BrokenPipeError:
            pass  # scraper went away mid-response; nothing to salvage
        except Exception as e:
            try:
                self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                           "text/plain; charset=utf-8")
            except OSError:
                pass


class MetricsServer:
    """The daemon-thread scrape endpoint around one registry.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` —
    the bench and chaos lanes use this to avoid collisions). ``close()``
    flips liveness off and shuts the listener down; it is safe to call
    twice."""

    def __init__(self, registry, *, port: int = 0, host: str = "127.0.0.1",
                 statusz_fn: Optional[Callable[[], dict]] = None,
                 health: Optional[HealthState] = None):
        self.registry = registry
        self.statusz_fn = statusz_fn
        self.health = health if health is not None else HealthState()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._owner = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="obs-metrics-server", daemon=True)
        self._thread.start()
        self._closed = False

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.health.set_live(False)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
