"""Dependency-free metrics registry with Prometheus text exposition.

One ``MetricsRegistry`` per process half (serving front-end, training
loop, elastic supervisor, each serving worker). Components create
metric *families* — ``counter`` / ``gauge`` / ``histogram`` — and
either write them directly (``inc`` / ``set`` / ``observe``) or mirror
an existing cumulative stat via ``set_function`` (the value is read at
collection time, so the instrumented hot path pays nothing).

Design constraints, in order:

- **Zero cost when off.** Components take ``registry=None`` and fall
  back to ``NULL_REGISTRY``, whose metrics are shared no-op objects —
  an instrumentation site costs one attribute call and no allocation.
  Nothing here ever touches the device or the token streams.
- **Thread-safe.** One lock per registry guards every family/child
  mutation and the exposition walk; the HTTP scrape thread and the
  serve/train loop never see a torn histogram.
- **Mergeable.** ``snapshot()`` serializes a registry to a JSON-able
  dict (callbacks resolved to plain numbers) that crosses the worker
  RPC; ``merge(snapshot, extra_labels={"replica": "3"})`` folds it
  label-wise into an aggregating registry. Worker snapshots are
  cumulative, so a merge *overwrites* that labeled child — the newest
  snapshot is the truth for that source.

Exposition follows the Prometheus text format v0.0.4: ``# HELP`` /
``# TYPE`` headers, escaped label values, and per-histogram cumulative
``_bucket{le=...}`` series plus ``_sum`` / ``_count``.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Fixed log-spaced latency buckets (seconds): 100 us .. 60 s, roughly
# 1-2.5-5 per decade. Fixed so every histogram in the fleet is
# mergeable bucket-for-bucket and dashboards never re-bin.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integral floats render without the
    trailing ``.0`` (matches the reference client), infinities as
    ``+Inf``/``-Inf``."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(labels: Sequence[Tuple[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class _Child:
    """One (family, label-set) time series. Base for all three types."""

    def __init__(self, family: "_Family",
                 labels: Tuple[Tuple[str, str], ...]):
        self._family = family
        self._lock = family._lock
        self._labels = labels
        self._fn: Optional[Callable[[], float]] = None

    def set_function(self, fn: Callable[[], float]) -> "_Child":
        """Mirror an existing stat: ``fn`` is called at collection time
        and its value reported as this series' value. For counters the
        source must be monotone (mirror cumulative stats only)."""
        self._fn = fn
        return self


class Counter(_Child):
    """Monotonically non-decreasing cumulative count."""

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment {amount} < 0")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return float(self._fn()) if self._fn is not None else self._value

    def labels(self, **kv) -> "Counter":
        return self._family.labels(**kv)


class Gauge(_Child):
    """A value that can go up and down."""

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return float(self._fn()) if self._fn is not None else self._value

    def labels(self, **kv) -> "Gauge":
        return self._family.labels(**kv)


class Histogram(_Child):
    """Fixed-bucket histogram (counts kept per-bucket, rendered
    cumulative). Buckets come from the family and never change, so
    fleet-wide series merge bucket-for-bucket."""

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._counts = [0] * (len(family.buckets) + 1)  # +1: > last edge
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            i = 0
            edges = self._family.buckets
            while i < len(edges) and value > edges[i]:
                i += 1
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def labels(self, **kv) -> "Histogram":
        return self._family.labels(**kv)


_CHILD_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric: type, help text, and its labeled children."""

    def __init__(self, registry: "MetricsRegistry", name: str, typ: str,
                 help_text: str, labelnames: Tuple[str, ...],
                 buckets: Tuple[float, ...]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.type = typ
        self.help = help_text
        self.labelnames = labelnames
        self.buckets = buckets
        self._children: Dict[Tuple[Tuple[str, str], ...], _Child] = {}

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple((ln, str(kv[ln])) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _CHILD_TYPES[self.type](self, key)
                self._children[key] = child
            return child

    def _default_child(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; "
                f"use .labels(...)")
        return self.labels()


class MetricsRegistry:
    """Thread-safe home for a process's metric families."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    # -- family constructors (idempotent per name) -------------------------

    def _family(self, name, typ, help_text, labelnames, buckets=()):
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != typ or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name} re-registered as {typ}"
                        f"{labelnames} (was {fam.type}{fam.labelnames})")
                return fam
            fam = _Family(self, name, typ, help_text, labelnames,
                          tuple(buckets))
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "",
                labelnames: Sequence[str] = ()):
        fam = self._family(name, "counter", help_text, labelnames)
        return fam if fam.labelnames else fam._default_child()

    def gauge(self, name: str, help_text: str = "",
              labelnames: Sequence[str] = ()):
        fam = self._family(name, "gauge", help_text, labelnames)
        return fam if fam.labelnames else fam._default_child()

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError(f"histogram {name}: no buckets")
        fam = self._family(name, "histogram", help_text, labelnames,
                           buckets)
        return fam if fam.labelnames else fam._default_child()

    # -- exposition --------------------------------------------------------

    def exposition(self) -> str:
        """The full registry in Prometheus text format v0.0.4."""
        out: List[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                if not fam._children:
                    continue
                if fam.help:
                    out.append(f"# HELP {name} {_escape_help(fam.help)}")
                out.append(f"# TYPE {name} {fam.type}")
                for key in sorted(fam._children):
                    child = fam._children[key]
                    if fam.type == "histogram":
                        cum = 0
                        for edge, n in zip(fam.buckets, child._counts):
                            cum += n
                            ls = _label_str(key + (("le", _fmt_value(edge)),))
                            out.append(f"{name}_bucket{ls} {cum}")
                        cum += child._counts[-1]
                        ls = _label_str(key + (("le", "+Inf"),))
                        out.append(f"{name}_bucket{ls} {cum}")
                        out.append(
                            f"{name}_sum{_label_str(key)} "
                            f"{_fmt_value(child._sum)}")
                        out.append(f"{name}_count{_label_str(key)} {cum}")
                    else:
                        out.append(
                            f"{name}{_label_str(key)} "
                            f"{_fmt_value(child.value)}")
        return "\n".join(out) + ("\n" if out else "")

    # -- snapshot / merge (the worker -> front-end path) -------------------

    def snapshot(self) -> dict:
        """JSON-able dump of every family: callbacks resolved, histogram
        state as plain lists. The worker RPC payload."""
        snap: Dict[str, dict] = {}
        with self._lock:
            for name, fam in self._families.items():
                samples = []
                for key, child in fam._children.items():
                    entry: dict = {"labels": dict(key)}
                    if fam.type == "histogram":
                        entry["counts"] = list(child._counts)
                        entry["sum"] = child._sum
                        entry["count"] = child._count
                    else:
                        entry["value"] = float(child.value)
                    samples.append(entry)
                snap[name] = {
                    "type": fam.type,
                    "help": fam.help,
                    "labelnames": list(fam.labelnames),
                    "buckets": list(fam.buckets),
                    "samples": samples,
                }
        return snap

    def merge(self, snap: dict,
              extra_labels: Optional[Dict[str, str]] = None) -> None:
        """Fold a ``snapshot()`` into this registry label-wise. Each
        merged series gains ``extra_labels`` (e.g. ``replica="3"``) and
        is OVERWRITTEN with the snapshot's cumulative state — snapshots
        from one source supersede their predecessors."""
        extra = {str(k): str(v) for k, v in (extra_labels or {}).items()}
        for name, fam_snap in snap.items():
            labelnames = tuple(fam_snap.get("labelnames", ())) + tuple(
                k for k in sorted(extra) if k not in
                fam_snap.get("labelnames", ()))
            fam = self._family(
                name, fam_snap["type"], fam_snap.get("help", ""),
                labelnames, tuple(fam_snap.get("buckets", ())))
            for entry in fam_snap.get("samples", ()):
                labels = dict(entry.get("labels", {}))
                labels.update(extra)
                child = fam.labels(**labels)
                with self._lock:
                    if fam.type == "histogram":
                        counts = list(entry.get("counts", ()))
                        if len(counts) != len(fam.buckets) + 1:
                            raise ValueError(
                                f"{name}: snapshot bucket count "
                                f"{len(counts)} != {len(fam.buckets) + 1}")
                        child._counts = [int(c) for c in counts]
                        child._sum = float(entry.get("sum", 0.0))
                        child._count = int(entry.get("count", 0))
                    else:
                        child._fn = None
                        child._value = float(entry.get("value", 0.0))


class _NullMetric:
    """Shared no-op metric: every write is a pass, ``labels`` returns
    itself. The zero-cost path for ``registry=None`` components."""

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def set_function(self, fn) -> "_NullMetric":
        return self

    def labels(self, **kv) -> "_NullMetric":
        return self

    @property
    def value(self) -> float:
        return 0.0


_NULL_METRIC = _NullMetric()


class NullRegistry:
    """Registry stand-in when metrics are off: constructors hand back
    the shared no-op metric and exposition is empty."""

    def counter(self, name, help_text="", labelnames=()):
        return _NULL_METRIC

    def gauge(self, name, help_text="", labelnames=()):
        return _NULL_METRIC

    def histogram(self, name, help_text="", labelnames=(),
                  buckets=DEFAULT_LATENCY_BUCKETS):
        return _NULL_METRIC

    def exposition(self) -> str:
        return ""

    def snapshot(self) -> dict:
        return {}

    def merge(self, snap, extra_labels=None) -> None:
        pass


NULL_REGISTRY = NullRegistry()
