"""TPU-native GPT model in Flax.

Re-designs the reference model (``/root/reference/src/models/gpt.py``) as an
idiomatic JAX/Flax module. Capability parity, component by component:

- RMSNorm (reference ``gpt.py:22-67``) — float32 accumulation, bf16 out.
- RoPE (``gpt.py:70-147``) — tables recomputed on the fly, never stored in the
  checkpoint (the reference persists them as buffers — SURVEY.md §2.1 b8).
- Causal self-attention (``gpt.py:150-242``) — both the fused/"flash" path and
  the manual jnp path, selected by ``config.use_flash_attention``.
- SwiGLU MLP (``gpt.py:245-283``).
- Pre-norm transformer block (``gpt.py:286-316``) — the unit of rematerialization
  (gradient checkpointing) and of FSDP sharding granularity, mirrored here as
  the unit of ``nn.remat`` + ``nn.scan``.
- GPT with tied embeddings (``gpt.py:319-455``), normal(initializer_range) init
  (``gpt.py:350-386``), shifted cross-entropy loss (``gpt.py:450-453``).
- Autoregressive generation with temperature/top-k and context-window cropping
  (``gpt.py:457-484``) — here as a jit-compiled ``lax.fori_loop``.

Architectural choices that are TPU-first rather than translations:

- Layers are stacked via ``nn.scan`` (one traced block, parameters carry a
  leading ``[num_layers, ...]`` axis). XLA compiles the block once; the stacked
  layout is also what GSPMD shards best.
- Attention uses the BSHD layout ``[batch, seq, heads, head_dim]`` end to end;
  no transposes around the kernel.
- The model is parallelism-blind (the reference's single most load-bearing
  property — SURVEY.md §1): sharding is applied entirely outside via GSPMD.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.ops import ring
from tpu_trainer.ops.attention import flash_attention, reference_attention
from tpu_trainer.ops.dropout import hash_dropout
from tpu_trainer.ops.loss import (
    fused_shifted_cross_entropy,
    vocab_sharded_shifted_cross_entropy,
)
from tpu_trainer.utils import telemetry


class RMSNorm(nn.Module):
    """Root-mean-square layer norm (reference ``gpt.py:22-67``).

    ``x * rsqrt(mean(x^2) + eps) * weight`` with float32 accumulation.
    """

    eps: float = 1e-6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        weight = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        rms = jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + self.eps)
        return (x32 * rms * weight).astype(self.dtype)


# RoPE lives in ops/rope.py (shared with the attention dispatch and the
# fused kernel); re-exported here for API continuity.
from tpu_trainer.ops.rope import (  # noqa: E402,F401
    apply_rotary_pos_emb,
    rope_tables,
    rotate_half,
)


class _ProjKernel(nn.Module):
    """Bare projection weight with an ``nn.Dense``-identical parameter tree.

    Creates ``<name>/kernel`` with the same shape, init, and param dtype as
    the no-bias ``nn.Dense`` it stands in for, but returns the raw kernel so
    the caller can fuse several projections into one matmul
    (``GPTConfig.fused_projections``). Checkpoints, sharding rules
    (``parallel/sharding.py`` suffix matching), and param counting are
    unchanged either way.
    """

    features: int
    param_dtype: jnp.dtype
    kernel_init: nn.initializers.Initializer

    @nn.compact
    def __call__(self, in_features: int) -> jax.Array:
        return self.param(
            "kernel", self.kernel_init, (in_features, self.features),
            self.param_dtype,
        )


def _use_fused_projections(cfg: GPTConfig) -> bool:
    """Trace-time decision for ``cfg.fused_projections``.

    TP shards the q/k/v (and gate/up) kernels along their output dim — the
    axis the fusion concatenates — so fusing there would make GSPMD gather
    the kernel shards every step. When a mesh context is published
    (``parallel/context.py``), refuse to fuse over a >1 tensor axis even if
    the config asks for it — this covers every Trainer path (the context
    stays visible inside the pipeline's partial-manual stage body, whose
    manual axes are only {stage, sequence}). The Trainer *also* flips the
    config flag off under TP so the decision is visible in the stored
    config; entry points that never publish a mesh (``eval/infer.py``)
    rely on that config-level gate.
    """
    if not cfg.fused_projections:
        return False
    from tpu_trainer.parallel import context as ctx_lib

    mesh = ctx_lib.current_mesh()
    return mesh is None or mesh.shape.get("tensor", 1) <= 1


def _fused_projection(cfg: GPTConfig, x: jax.Array, specs) -> list:
    """Run several no-bias projections of ``x`` as ONE wide matmul.

    ``specs`` is ``[(name, features), ...]``; the per-projection kernels are
    created as separate parameters (``_ProjKernel``) and concatenated at
    trace time, so x is read from HBM once and the MXU sees a single dot.
    Returns the per-projection outputs (the split of the wide result).
    Module creation happens against the caller's compact context, so the
    parameter paths land under the calling module exactly as nn.Dense would.
    """
    kern = functools.partial(
        _ProjKernel, param_dtype=cfg.params_dtype,
        kernel_init=nn.initializers.normal(cfg.initializer_range),
    )
    in_f = x.shape[-1]
    ws = [kern(features, name=name)(in_f) for name, features in specs]
    w = jnp.concatenate(ws, axis=1)
    out = x.astype(cfg.compute_dtype) @ w.astype(cfg.compute_dtype)
    bounds = np.cumsum([features for _, features in specs])[:-1].tolist()
    return jnp.split(out, bounds, axis=-1)


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention (reference ``gpt.py:150-242``).

    ``decode=True`` switches to KV-cached autoregressive mode: new keys and
    values land in a ``cache`` collection at the running position, and
    queries attend over the cache — the fast decode path the reference lacks
    (its generate re-runs the full O(S^2) forward per token, ``infer.py``
    hot loop, SURVEY.md §3.5).
    """

    config: GPTConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, deterministic: bool = True, decode: bool = False,
        segment_ids: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        b, s, _ = x.shape
        dense = functools.partial(
            nn.Dense,
            use_bias=False,
            dtype=cfg.compute_dtype,
            param_dtype=cfg.params_dtype,
            kernel_init=nn.initializers.normal(cfg.initializer_range),
        )
        kv_features = cfg.kv_heads * cfg.head_dim
        if _use_fused_projections(cfg):
            # One [H, H + 2*kv] matmul instead of three: x is read from HBM
            # once and the MXU sees one wide dot; params stay separate
            # (checkpoint + sharding-rule invariance — see
            # _fused_projection / GPTConfig.fused_projections).
            q, k, v = _fused_projection(
                cfg, x,
                [("q_proj", cfg.hidden_size), ("k_proj", kv_features),
                 ("v_proj", kv_features)],
            )
        else:
            q = dense(features=cfg.hidden_size, name="q_proj")(x)
            k = dense(features=kv_features, name="k_proj")(x)
            v = dense(features=kv_features, name="v_proj")(x)

        # [b, s, h*d] -> [b, s, heads, head_dim] (BSHD; no BHSD transpose on
        # TPU). Under GQA the k/v head dim is num_kv_heads (< num_heads).
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.kv_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.kv_heads, cfg.head_dim)

        if decode:
            out = self._decode_attention(q, k, v)
        else:
            cos, sin = rope_tables(s, cfg.head_dim, cfg.rope_theta)
            needs_rng = cfg.attention_dropout > 0.0 and not deterministic
            dropout_rng = self.make_rng("dropout") if needs_rng else None
            manual_ctx = ring.current_manual_context()
            sp_ctx = ring.current_context()
            if segment_ids is not None and (
                (manual_ctx is not None
                 and manual_ctx.mesh.shape[manual_ctx.axis_name] > 1)
                or (sp_ctx is not None
                    and sp_ctx.mesh.shape[sp_ctx.axis_name] > 1)
            ):
                # The ring paths rotate K/V chunks across the sequence axis;
                # segment isolation there needs per-chunk segment slices the
                # ring body does not yet carry.
                raise NotImplementedError(
                    "segment_ids are not supported under sequence parallelism"
                )
            if (manual_ctx is not None
                    and manual_ctx.mesh.shape[manual_ctx.axis_name] > 1):
                # Already inside a manual region bound to the sequence axis
                # (the SP x PP jointly-manual pipeline): x is the LOCAL
                # sequence shard here. RoPE at global positions (this
                # device's chunk offset), then the ring body directly —
                # no nested shard_map.
                sp = manual_ctx.mesh.shape[manual_ctx.axis_name]
                cos_g, sin_g = rope_tables(s * sp, cfg.head_dim,
                                           cfg.rope_theta)
                off = jax.lax.axis_index(manual_ctx.axis_name) * s
                cos_l = jax.lax.dynamic_slice(
                    cos_g, (off, 0), (s, cfg.head_dim))
                sin_l = jax.lax.dynamic_slice(
                    sin_g, (off, 0), (s, cfg.head_dim))
                q, k = apply_rotary_pos_emb(q, k, cos_l, sin_l)
                out = ring.ring_attention_manual(
                    q, k, v, sp, manual_ctx.axis_name,
                    dropout_rate=cfg.attention_dropout if needs_rng else 0.0,
                    dropout_rng=dropout_rng,
                )
            elif sp_ctx is not None and sp_ctx.mesh.shape[sp_ctx.axis_name] > 1:
                # Sequence parallelism: K/V ring over the mesh's sequence
                # axis, each chunk through the flash kernel where available
                # (ops/ring.py). Attention dropout runs per chunk.
                q, k = apply_rotary_pos_emb(q, k, cos, sin)
                out = ring.ring_attention(
                    q, k, v, sp_ctx.mesh, sp_ctx.axis_name,
                    dropout_rate=cfg.attention_dropout if needs_rng else 0.0,
                    dropout_rng=dropout_rng,
                )
            elif cfg.use_flash_attention:
                # RoPE rides into the kernel (rotation happens in VMEM on
                # TPU; external otherwise — ops/attention.py decides).
                out = flash_attention(
                    q, k, v,
                    dropout_rate=cfg.attention_dropout,
                    deterministic=deterministic,
                    dropout_rng=dropout_rng,
                    rope=(cos, sin),
                    segment_ids=segment_ids,
                )
            else:
                q, k = apply_rotary_pos_emb(q, k, cos, sin)
                out = reference_attention(
                    q, k, v,
                    dropout_rate=cfg.attention_dropout,
                    deterministic=deterministic,
                    dropout_rng=dropout_rng,
                    segment_ids=segment_ids,
                )

        out = out.reshape(b, s, cfg.hidden_size)
        out = dense(features=cfg.hidden_size, name="o_proj")(out)
        out = _residual_dropout(cfg, self, out, deterministic)
        return out

    def _decode_attention(self, q, k, v) -> jax.Array:
        """KV-cached attention over ``cache`` variables.

        The cache holds ``[b, max_seq_len, heads, head_dim]`` per layer plus
        the running length ``idx``; a call with ``s`` tokens appends at
        ``idx`` (prefill: s = prompt length; decode: s = 1) and every query
        attends to positions ``<= its own``. RoPE is applied at the *global*
        positions ``idx..idx+s-1``.
        """
        cfg = self.config
        if cfg.decode_paged:
            return self._paged_decode_attention(q, k, v)
        b, s, h, d = q.shape
        kvh = k.shape[2]  # num_kv_heads: the GQA cache is group-fold smaller
        # Cache length: the static decode window when set (generate_kv
        # sizes it to prompt+new rounded to 128) — the buffer, the DUS
        # writes, and every attention read scale with it instead of the
        # full context limit.
        max_len = cfg.max_seq_len
        if 0 < cfg.decode_window < max_len:
            max_len = cfg.decode_window
        ck = self.variable(
            "cache", "k", jnp.zeros, (b, max_len, kvh, d), cfg.compute_dtype
        )
        cv = self.variable(
            "cache", "v", jnp.zeros, (b, max_len, kvh, d), cfg.compute_dtype
        )
        ci = self.variable(
            "cache", "idx", lambda: jnp.zeros((), jnp.int32)
        )
        # Per-row left-pad sizes for ragged batches (generate_kv left-pads
        # mixed-length prompts to a shared frontier): row r's positions
        # < pad[r] are padding — excluded from attention windows and from
        # RoPE position counting. The variable only exists (and the
        # per-row machinery only traces) when the caller statically asked
        # for ragged decode — uniform batches keep the cheaper shared-
        # position path.
        ragged = cfg.decode_ragged
        if ragged:
            cp = self.variable(
                "cache", "pad", lambda: jnp.zeros((b,), jnp.int32)
            )
            pad = cp.value
        idx = ci.value

        cos, sin = rope_tables(max_len, d, cfg.rope_theta)
        if ragged:
            # Logical (post-pad) positions per row; clamped at 0 for the
            # pad region itself (whose outputs are never read).
            gpos = idx + jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
            lpos = jnp.maximum(gpos - pad[:, None], 0)          # [b, s]
            q, k = apply_rotary_pos_emb(q, k, cos[lpos], sin[lpos])
        else:
            cos_s = jax.lax.dynamic_slice(cos, (idx, 0), (s, d))
            sin_s = jax.lax.dynamic_slice(sin, (idx, 0), (s, d))
            q, k = apply_rotary_pos_emb(q, k, cos_s, sin_s)

        k_all = jax.lax.dynamic_update_slice(ck.value, k, (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cv.value, v, (0, idx, 0, 0))
        if not self.is_initializing():
            ck.value = k_all
            cv.value = v_all
            ci.value = idx + s

        if kvh != h:
            # Expand K/V heads to the query heads' groups for the einsum
            # (decode batches are small; the cache itself stays compact).
            from tpu_trainer.ops.attention import repeat_kv

            k_all, v_all = repeat_kv(k_all, v_all, h)
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_all) * scale
        q_pos = idx + jax.lax.broadcasted_iota(jnp.int32, (s, max_len), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, max_len), 1)
        if ragged:
            # Causal, excluding each row's left padding. Pad-region queries
            # keep their self position so their (never-read) softmax rows
            # stay finite — an empty window would put NaN into this
            # position's residual stream and poison later layers' cached
            # K/V.
            allowed = (k_pos[None] <= q_pos[None]) & (
                (k_pos[None] >= pad[:, None, None])
                | (k_pos[None] == q_pos[None])
            )
            scores = jnp.where(
                allowed[:, None], scores, jnp.finfo(scores.dtype).min
            )
        else:
            allowed = k_pos <= q_pos
            scores = jnp.where(
                allowed[None, None], scores, jnp.finfo(scores.dtype).min
            )
        weights = jax.nn.softmax(
            scores.astype(jnp.float32), axis=-1
        ).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, v_all)

    def _paged_decode_attention(self, q, k, v) -> jax.Array:
        """KV-cached attention over a PAGED cache (``cfg.decode_paged``).

        Instead of one contiguous ``[b, max_len, ...]`` buffer per row, KV
        history lives in fixed-size blocks inside a shared pool
        (``[num_blocks, block_size, kvh, d]``), addressed through per-row
        block tables — the serving engine allocates/frees blocks from a
        free list so memory scales with tokens actually cached, not with
        slots * context limit (tpu_trainer/serving/paged_cache.py).

        Cache-variable contract (the engine writes ``tables``/
        ``lengths``/``offsets`` from its host-side state before every
        call):

        - prefill (``s > 1``): row r's tokens are a CHUNK starting at
          global position ``offsets[r]`` (0 = classic whole-prompt
          prefill); ``lengths[r]`` is the row's total cached tokens
          AFTER this chunk, so the chunk's true width is ``lengths[r] -
          offsets[r]`` within the right-padded ``s`` (attention masks
          beyond it; padded positions scatter into the null block 0).
          Attention runs over this call's in-flight k/v plus — when
          ``cfg.paged_hist_blocks > 0`` — the first ``paged_hist_blocks``
          pooled blocks of each row, masked to positions strictly below
          ``offsets[r]`` (the history deposited by earlier chunks or a
          shared prefix). History k/v precede the in-flight k/v in the
          softmax's key order, i.e. in ascending global position — the
          same order the monolithic pass reduces in, which is what keeps
          chunked greedy streams bit-identical. ``lengths`` is left
          as-is (it already counts the tokens deposited so far).
          The speculative-decode verifier (serving/spec.py) rides this
          exact branch: it feeds [last accepted token + K drafts] as a
          chunk at ``offsets[r] = cached_tokens`` and consumes the
          model's per-position logits for the whole window (the model
          always returns ``[b, s, vocab]``; slicing to the last position
          is the caller's choice), scoring all K+1 candidates in one
          forward. Rejected positions' pool writes are harmless: the
          host rewinds ``lengths`` and every read masks by it.
        - decode (``s == 1``): the new token writes at position
          ``lengths[r]`` of row r's table and attends over ``lengths[r]
          + 1`` pooled positions (flash_decode kernel or the jnp
          reference, ``cfg.paged_attention``); ``lengths`` increments.
          ``offsets`` is ignored (broadcast as zeros).

        Tensor parallel (``cfg.paged_tp > 1``): both branches run the
        identical per-head math under a head-sharded ``shard_map`` over
        the replica's mesh (serving/sharding.py) — decode via
        ``ops.flash.paged_attention_sharded``, prefill via the local
        ``attend`` closure — closing with an exact disjoint-slice
        all-reduce, so sharded greedy streams stay token-identical to
        the single-device engine. Tables/lengths/offsets remain
        replicated host mirrors; only the pools (when ``kvh % tp == 0``)
        and the heads axis of activations split.
        """
        cfg = self.config
        b, s, h, d = q.shape
        kvh = k.shape[2]
        bsz = cfg.paged_block_size
        nblk = cfg.paged_num_blocks
        mb = cfg.paged_max_blocks
        int8 = cfg.paged_kv_int8
        from tpu_trainer.utils.quant import quant_block_len, quantize_kv_int8

        nbq = d // quant_block_len(d)
        kv_dtype = jnp.int8 if int8 else cfg.compute_dtype
        pk = self.variable(
            "cache", "pool_k", jnp.zeros, (nblk, bsz, kvh, d), kv_dtype)
        pv = self.variable(
            "cache", "pool_v", jnp.zeros, (nblk, bsz, kvh, d), kv_dtype)
        if int8:
            sk = self.variable(
                "cache", "scale_k", jnp.zeros, (nblk, bsz, kvh, nbq),
                jnp.float32)
            sv = self.variable(
                "cache", "scale_v", jnp.zeros, (nblk, bsz, kvh, nbq),
                jnp.float32)
        tb = self.variable("cache", "tables", jnp.zeros, (b, mb), jnp.int32)
        ln = self.variable("cache", "lengths", jnp.zeros, (b,), jnp.int32)
        of = self.variable("cache", "offsets", jnp.zeros, (b,), jnp.int32)
        tables, lengths, offsets = tb.value, ln.value, of.value

        cos, sin = rope_tables(mb * bsz, d, cfg.rope_theta)
        if s == 1:
            pos = lengths[:, None]                               # [b, 1]
        else:
            # Chunked prefill: row r's local position i sits at global
            # position offsets[r] + i (offsets is all-zero for the
            # classic whole-prompt pass).
            pos = offsets[:, None] + jax.lax.broadcasted_iota(
                jnp.int32, (b, s), 1)
        rope_pos = jnp.minimum(pos, mb * bsz - 1)  # pad rows may overrun
        q, k = apply_rotary_pos_emb(q, k, cos[rope_pos], sin[rope_pos])

        # Scatter this call's k/v into the pool: position p of row r lands
        # at (tables[r, p // bsz], p % bsz). Prefill padding (p >= the
        # row's true length) redirects to the reserved null block 0 —
        # written garbage there is never read (every read masks by
        # lengths), so a [b*s] flat scatter needs no predication.
        write_pos = pos
        valid = (write_pos < lengths[:, None]) if s > 1 else (
            jnp.ones((b, 1), bool))
        blk_ids = jnp.take_along_axis(
            tables, jnp.minimum(write_pos // bsz, mb - 1), axis=1)
        blk_ids = jnp.where(valid, blk_ids, 0).reshape(-1)
        offs = jnp.where(valid, write_pos % bsz, 0).reshape(-1)
        if int8:
            k_q, k_s = quantize_kv_int8(k)
            v_q, v_s = quantize_kv_int8(v)
            pool_k = pk.value.at[blk_ids, offs].set(
                k_q.reshape(b * s, kvh, d))
            pool_v = pv.value.at[blk_ids, offs].set(
                v_q.reshape(b * s, kvh, d))
            scale_k = sk.value.at[blk_ids, offs].set(
                k_s.reshape(b * s, kvh, nbq))
            scale_v = sv.value.at[blk_ids, offs].set(
                v_s.reshape(b * s, kvh, nbq))
        else:
            pool_k = pk.value.at[blk_ids, offs].set(
                k.astype(kv_dtype).reshape(b * s, kvh, d))
            pool_v = pv.value.at[blk_ids, offs].set(
                v.astype(kv_dtype).reshape(b * s, kvh, d))
            scale_k = scale_v = None

        if s > 1:
            # Prefill attention runs over the in-flight k/v (everything
            # from this chunk was just computed): ragged causal in LOCAL
            # coordinates — the chunk holds lengths - offsets true tokens
            # — keeping each pad query's self position so its (never-read)
            # softmax row stays finite, same rationale as the contiguous
            # ragged path above. With offsets == 0 this is exactly the
            # original whole-prompt mask.
            kf, vf = k, v
            if int8:
                # Attend the quantization the pool will actually hold:
                # a later decode step reads these positions back through
                # the int8 round-trip, so a multi-token window (chunked
                # prefill, speculative verify) must see the same values
                # now — otherwise a token scored here and a token scored
                # by the one-at-a-time path diverge under int8.
                from tpu_trainer.utils.quant import dequantize_kv_int8

                kf = dequantize_kv_int8(k_q, k_s, q.dtype)
                vf = dequantize_kv_int8(v_q, v_s, q.dtype)
            if kvh != h:
                from tpu_trainer.ops.attention import repeat_kv

                kf, vf = repeat_kv(kf, vf, h)
            scale = 1.0 / (d ** 0.5)
            hb = cfg.paged_hist_blocks
            hk = hv = None
            if hb > 0:
                # Non-zero-offset chunk: also attend the pooled history
                # (earlier chunks / shared prefix) — the first hb table
                # entries of each row, masked to global positions below
                # offsets[r]. Reading the post-scatter pool is safe: the
                # positions this chunk just wrote are >= offsets and
                # masked out here (the in-flight path covers them).
                from tpu_trainer.utils.quant import dequantize_kv_int8

                htab = tables[:, :hb]                       # [b, hb]
                hk = pool_k[htab].reshape(b, hb * bsz, kvh, d)
                hv = pool_v[htab].reshape(b, hb * bsz, kvh, d)
                if int8:
                    hks = scale_k[htab].reshape(b, hb * bsz, kvh, nbq)
                    hvs = scale_v[htab].reshape(b, hb * bsz, kvh, nbq)
                    hk = dequantize_kv_int8(hk, hks, q.dtype)
                    hv = dequantize_kv_int8(hv, hvs, q.dtype)
                else:
                    hk = hk.astype(q.dtype)
                    hv = hv.astype(q.dtype)
                if kvh != h:
                    from tpu_trainer.ops.attention import repeat_kv

                    hk, hv = repeat_kv(hk, hv, h)

            # In-flight (+ optional pooled-history) attention over FULL
            # q-head inputs. Extracted as a closure so the tensor-parallel
            # path can run the identical math per head shard under
            # shard_map: softmax reduces over keys only, so splitting the
            # heads axis changes no arithmetic, and kf/vf/hk/hv are
            # repeated to q heads BEFORE sharding so GQA needs no special
            # casing here (repeat-then-shard).
            def attend(q_a, kf_a, vf_a, ln_a, of_a, *hist):
                scores = jnp.einsum("bqhd,bkhd->bhqk", q_a, kf_a) * scale
                q_pos = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
                k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
                chunk_len = (ln_a - of_a)[:, None, None]
                allowed = (k_pos[None] <= q_pos[None]) & (
                    (k_pos[None] < chunk_len)
                    | (k_pos[None] == q_pos[None])
                )
                scores = jnp.where(
                    allowed[:, None], scores, jnp.finfo(scores.dtype).min)
                v_cat = vf_a
                if hist:
                    hk_a, hv_a = hist
                    h_scores = jnp.einsum(
                        "bqhd,bkhd->bhqk", q_a, hk_a) * scale
                    h_pos = jax.lax.broadcasted_iota(
                        jnp.int32, (b, hb * bsz), 1)
                    h_allowed = h_pos < of_a[:, None]       # [b, hb*bsz]
                    h_scores = jnp.where(
                        h_allowed[:, None, None], h_scores,
                        jnp.finfo(h_scores.dtype).min)
                    # History keys come FIRST: ascending global position,
                    # the same reduce order as the monolithic pass — the
                    # bit-exactness contract of chunked prefill.
                    scores = jnp.concatenate([h_scores, scores], axis=-1)
                    v_cat = jnp.concatenate([hv_a, vf_a], axis=1)
                weights = jax.nn.softmax(
                    scores.astype(jnp.float32), axis=-1).astype(q_a.dtype)
                return jnp.einsum("bhqk,bkhd->bqhd", weights, v_cat)

            hist = () if hk is None else (hk, hv)
            tp = cfg.paged_tp
            if tp > 1:
                from jax.sharding import PartitionSpec as P

                from tpu_trainer.serving import sharding as tp_lib
                from tpu_trainer.utils.jax_compat import shard_map

                mesh = tp_lib.tp_mesh(tp, cfg.paged_tp_devices)
                hl = h // tp
                head = P(None, None, tp_lib.TP_AXIS, None)
                in_specs = [head, head, head, P(), P()]
                in_specs += [head] * len(hist)

                def body(q_l, kf_l, vf_l, ln_l, of_l, *hist_l):
                    i = jax.lax.axis_index(tp_lib.TP_AXIS)
                    out_l = attend(q_l, kf_l, vf_l, ln_l, of_l, *hist_l)
                    # Disjoint head slices: the psum is an exact concat
                    # (one non-zero contributor per element).
                    full = jnp.zeros((b, s, h, d), out_l.dtype)
                    full = jax.lax.dynamic_update_slice(
                        full, out_l, (0, 0, i * hl, 0))
                    return jax.lax.psum(full, tp_lib.TP_AXIS)

                out = shard_map(
                    body, mesh=mesh, in_specs=tuple(in_specs),
                    out_specs=P(), check_vma=False,
                )(q, kf, vf, lengths, offsets, *hist)
            else:
                out = attend(q, kf, vf, lengths, offsets, *hist)
            new_len = lengths
        else:
            from tpu_trainer.ops import flash as flash_lib

            new_len = lengths + 1
            impl = cfg.paged_attention
            if impl == "auto":
                impl = ("kernel" if jax.default_backend() == "tpu"
                        else "reference")
            if cfg.paged_tp > 1:
                from tpu_trainer.serving import sharding as tp_lib

                out = flash_lib.paged_attention_sharded(
                    q[:, 0], pool_k, pool_v, tables, new_len,
                    mesh=tp_lib.tp_mesh(cfg.paged_tp, cfg.paged_tp_devices),
                    k_scale=scale_k, v_scale=scale_v, impl=impl,
                ).astype(q.dtype)[:, None]                # [b, 1, h, d]
            else:
                fn = (flash_lib.flash_decode if impl == "kernel"
                      else flash_lib.paged_attention_reference)
                out = fn(
                    q[:, 0], pool_k, pool_v, tables, new_len,
                    k_scale=scale_k, v_scale=scale_v,
                ).astype(q.dtype)[:, None]                # [b, 1, h, d]

        if not self.is_initializing():
            pk.value = pool_k
            pv.value = pool_v
            if int8:
                sk.value = scale_k
                sv.value = scale_v
            ln.value = new_len
        return out


def _residual_dropout(cfg, module, x, deterministic):
    """Residual-stream dropout (reference ``gpt.py:241,282``): counter-based
    masks when ``cfg.fast_dropout`` (see ops/dropout.py), threefry otherwise."""
    if deterministic or cfg.dropout <= 0.0:
        return x
    if cfg.fast_dropout:
        return hash_dropout(x, cfg.dropout, module.make_rng("dropout"))
    return nn.Dropout(rate=cfg.dropout)(x, deterministic=False)


class MLP(nn.Module):
    """SwiGLU feed-forward (reference ``gpt.py:245-283``):
    ``down(silu(gate(x)) * up(x))`` + dropout."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        cfg = self.config
        dense = functools.partial(
            nn.Dense,
            use_bias=False,
            dtype=cfg.compute_dtype,
            param_dtype=cfg.params_dtype,
            kernel_init=nn.initializers.normal(cfg.initializer_range),
        )
        if _use_fused_projections(cfg):
            # gate+up as one [H, 2I] matmul (see _fused_projection).
            gate, up = _fused_projection(
                cfg, x,
                [("gate_proj", cfg.intermediate_size),
                 ("up_proj", cfg.intermediate_size)],
            )
        else:
            gate = dense(cfg.intermediate_size, name="gate_proj")(x)
            up = dense(cfg.intermediate_size, name="up_proj")(x)
        act = {"silu": nn.silu, "gelu": nn.gelu}[cfg.activation]
        x = act(gate) * up
        x = dense(cfg.hidden_size, name="down_proj")(x)
        return _residual_dropout(cfg, self, x, deterministic)


class TransformerBlock(nn.Module):
    """Pre-norm block with two residuals (reference ``gpt.py:286-316``).

    Written in scan form: ``__call__(carry, seg) -> (carry, ys)`` so a single
    traced block is iterated ``num_layers`` times by ``nn.scan``. The carry
    is ``(x, aux)`` — ``aux`` accumulates the MoE load-balance loss across
    layers (zero for the dense model). The second argument is the packed
    batch's ``segment_ids`` (or None), broadcast to every layer
    (``in_axes=nn.broadcast`` on the scan). ``ys`` is normally None; under
    an active telemetry capture (utils/telemetry) it is a dict of per-layer
    activation/router stats, which the scan stacks into ``[num_layers]``
    vectors (the unrolled path stacks them by hand).
    """

    config: GPTConfig
    deterministic: bool = True
    decode: bool = False

    @nn.compact
    def __call__(self, carry, segment_ids=None):
        cfg = self.config
        x, aux = carry
        residual = x
        h = RMSNorm(dtype=cfg.compute_dtype, name="input_layernorm")(x)
        h = CausalSelfAttention(cfg, name="attention")(
            h, self.deterministic, self.decode, segment_ids
        )
        attn_out = h
        x = residual + h

        residual = x
        h = RMSNorm(dtype=cfg.compute_dtype, name="post_attention_layernorm")(x)
        if cfg.num_experts > 0:
            from tpu_trainer.models.moe import MoEMLP

            h, layer_aux = MoEMLP(cfg, name="moe_mlp")(h, self.deterministic)
            aux = aux + layer_aux
        else:
            h = MLP(cfg, name="mlp")(h, self.deterministic)
        x = residual + h

        telem = None
        if telemetry.capturing():
            telem = {
                "attn_rms": telemetry.rms(attn_out),
                "attn_absmax": telemetry.absmax(attn_out),
                "ffn_rms": telemetry.rms(h),
                "ffn_absmax": telemetry.absmax(h),
                "block_rms": telemetry.rms(x),
                "block_absmax": telemetry.absmax(x),
            }
            router = telemetry.pop("router")
            if router is not None:
                telem.update(
                    {f"router_{k}": v for k, v in router.items()}
                )
        return (x, aux), telem


@jax.custom_vjp
def _unstack_layers(stacked):
    """Slice a stacked ``[num_layers, ...]`` param tree into per-layer trees.

    Exists for its backward: plain AD of per-layer slicing rebuilds the
    stacked cotangent through a chain of dynamic-update-slices that XLA
    materializes as one full-buffer copy per layer (measured ~0.3 ms * 12
    layers * per-matrix at headline geometry — ~12% of the step). The custom
    backward stacks the per-layer gradients with a single concatenate write
    instead.
    """
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return tuple(
        jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
        for i in range(num_layers)
    )


def _unstack_fwd(stacked):
    return _unstack_layers(stacked), None


def _unstack_bwd(_, grads):
    return (jax.tree_util.tree_map(lambda *gs: jnp.stack(gs), *grads),)


_unstack_layers.defvjp(_unstack_fwd, _unstack_bwd)


class GPT(nn.Module):
    """GPT for causal language modeling (reference ``gpt.py:319-484``)."""

    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        labels: Optional[jax.Array] = None,
        train: bool = False,
        decode: bool = False,
        segment_ids: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, Optional[jax.Array]]:
        """Forward pass.

        ``attention_mask`` is accepted for API parity but — exactly like the
        reference (``gpt.py:203`` passes ``attn_mask=None``; SURVEY.md §2.1 b3)
        — semantics are causal-only.

        ``segment_ids`` ([b, s] int, 0 = padding, documents 1..K) isolates
        attention within packed documents and masks loss targets that would
        cross a document boundary. Unsupported under pipeline parallelism
        and sequence parallelism (NotImplementedError).

        Returns ``(logits [b, s, vocab] float32, loss | None)``.
        """
        cfg = self.config
        embed = nn.Embed(
            num_embeddings=cfg.vocab_size,
            features=cfg.hidden_size,
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            dtype=cfg.compute_dtype,
            param_dtype=cfg.params_dtype,
            name="embed_tokens",
        )
        x = embed(input_ids)
        if telemetry.capturing():
            telemetry.record("embed_out", {
                "rms": telemetry.rms(x), "absmax": telemetry.absmax(x),
            })

        policies = {
            "full": None,
            "dots": jax.checkpoint_policies.dots_saveable,
        }
        carry0 = (x, jnp.zeros((), jnp.float32))
        from tpu_trainer.parallel import context as ctx_lib

        ctx_mesh = ctx_lib.current_mesh()
        stage_n = ctx_mesh.shape.get("stage", 1) if ctx_mesh is not None else 1
        manual_apply = not decode and not self.is_initializing()
        if manual_apply and (stage_n > 1 or cfg.scan_unroll):
            # Shared setup for the two manual apply paths (pipeline and
            # unrolled): one detached block module, dropout-rng gating, and
            # optional remat wrapping.
            block_mod = TransformerBlock(cfg, deterministic=not train)
            needs_rng = train and (
                cfg.dropout > 0.0 or cfg.attention_dropout > 0.0
            )

            def run_block(p, carry, rng):
                rngs = {} if rng is None else {"dropout": rng}
                return block_mod.apply(
                    {"params": p}, carry, segment_ids, rngs=rngs
                )

            if cfg.gradient_checkpointing:
                run_block = jax.checkpoint(
                    run_block, prevent_cse=False,
                    policy=policies[cfg.remat_policy],
                )
        if segment_ids is not None and stage_n > 1:
            # The GPipe schedule slices microbatches itself and its 1f1b
            # variants bypass normal AD; segment plumbing there is a
            # separate project.
            raise NotImplementedError(
                "segment_ids are not supported under pipeline parallelism"
            )
        if manual_apply and stage_n > 1:
            # Pipeline parallelism: the stacked layers (sharded over `stage`
            # by parallel/sharding.py) run through the GPipe schedule
            # (parallel/pipeline.py). Embedding / final norm / loss stay
            # outside, replicated over the stage axis. The MoE aux rides the
            # schedule (summed over layers, per-microbatch estimator). The
            # flash dispatch still shard_maps the kernel inside the stage
            # body — its manual region covers only batch/head axes, disjoint
            # from `stage` (ops/attention.py).
            from tpu_trainer.parallel.pipeline import pipeline_forward

            def block_fn(p, xm, rng=None):
                # [0]: the pipeline schedule carries only (x, aux) between
                # stages — per-layer telemetry ys are not collected here
                # (the 1f1b variants bypass normal AD entirely).
                return run_block(p, (xm, jnp.zeros((), jnp.float32)), rng)[0]

            rng = self.make_rng("dropout") if needs_rng else None
            # SP x PP: go jointly manual over {stage, sequence} so the
            # ring's collectives bind to this one manual region (Shardy
            # rejects a nested manual region with loop-carried ppermute).
            import contextlib as _cl

            sp_n = ctx_mesh.shape.get(ring.SEQ_AXIS, 1)
            if sp_n > 1:
                seq_cm = ring.sequence_parallel_manual(ctx_mesh)
                manual_seq = ring.SEQ_AXIS
            else:
                seq_cm = _cl.nullcontext()
                manual_seq = None
            with seq_cm:
                x, moe_aux = pipeline_forward(
                    self.variables["params"]["layers"], x, block_fn,
                    ctx_mesh, cfg.pipeline_microbatches or stage_n, rng=rng,
                    with_aux=True, manual_seq_axis=manual_seq,
                )
        elif manual_apply and cfg.scan_unroll:
            # Unrolled apply path: parameters keep the nn.scan layout
            # ([num_layers, ...] stacked leaves, created by the scan branch
            # at init — checkpoint/sharding layout unchanged), but each layer
            # runs as straight-line code on a static slice. This removes the
            # scan's stacking machinery: per-layer saved activations are
            # plain fusion outputs instead of dynamic-update-slices into
            # [num_layers, ...] buffers, and _unstack_layers turns the
            # stacked param gradient into one concatenate (see its
            # docstring). Measured ~20% faster than the rolled scan at
            # headline geometry; the rolled path remains for decode (cache
            # collection) and very deep models (compile time).
            per_layer = _unstack_layers(self.variables["params"]["layers"])
            carry = carry0
            telems = []
            for p in per_layer:
                rng = self.make_rng("dropout") if needs_rng else None
                carry, telem = run_block(p, carry, rng)
                if telem is not None:
                    telems.append(telem)
            x, moe_aux = carry
            if telems:
                # Same [num_layers, ...] stacking nn.scan's ys would give.
                telemetry.record("layers", jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *telems
                ))
        else:
            block = TransformerBlock
            if cfg.gradient_checkpointing and not decode:
                # Remat per block — the reference's activation-checkpointing
                # unit (gpt.py:440-444, fsdp_trainer.py:312-328). Policy
                # selects what survives to the backward (config.remat_policy).
                block = nn.remat(
                    block, prevent_cse=False, policy=policies[cfg.remat_policy]
                )
            layers = nn.scan(
                block,
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                in_axes=nn.broadcast,  # segment_ids: same array every layer
            )
            (x, moe_aux), layer_telem = layers(
                cfg, deterministic=not train, decode=decode, name="layers"
            )(carry0, segment_ids)
            if layer_telem is not None:
                telemetry.record("layers", layer_telem)

        x = RMSNorm(dtype=cfg.compute_dtype, name="norm")(x)
        if telemetry.capturing():
            telemetry.record("final_norm", {
                "rms": telemetry.rms(x), "absmax": telemetry.absmax(x),
            })
        # Weight tying (reference gpt.py:342): logits via the embedding matrix.
        logits = embed.attend(x).astype(jnp.float32)
        if telemetry.capturing(deep=True):
            # Between final_norm and the loss, nan-scan only: making the
            # logits live here would defeat the fused/remat loss heads'
            # memory savings on periodic telemetry steps, but without this
            # site a NaN entering in the head matmul is indistinguishable
            # from one entering in the loss math (the seq x tensor repro,
            # ROADMAP open items).
            telemetry.record("logits", {
                "rms": telemetry.rms(logits), "absmax": telemetry.absmax(logits),
            })

        loss = None
        if labels is not None:
            # Shifted next-token cross entropy (reference gpt.py:450-453), mean
            # over batch * (seq - 1) positions, computed in float32.
            if cfg.fused_loss:
                # Blockwise fused head+CE: full logits never materialize in
                # either pass (ops/loss.py; the `logits` above are dead code
                # in the training graph, which only consumes the loss).
                loss = fused_shifted_cross_entropy(
                    embed.embedding, x, labels,
                    chunk_size=cfg.loss_chunk_size,
                    allow_pallas=cfg.fused_loss_pallas,
                    segment_ids=segment_ids,
                )
            elif cfg.remat_lm_head:
                # Nothing of the [b, s, vocab] softmax survives forward; the
                # backward recomputes one vocab matmul instead of re-reading
                # a ~bytes(b*s*V*4) buffer. (The unused `logits` above is
                # dead-code-eliminated in the training graph, which only
                # consumes the loss.)
                def head_loss(xf):
                    lg = embed.attend(xf).astype(jnp.float32)
                    return _masked_shifted_mean(
                        optax_softmax_cross_entropy(
                            lg[:, :-1, :], labels[:, 1:]
                        ),
                        segment_ids,
                    )

                loss = jax.checkpoint(
                    head_loss,
                    policy=jax.checkpoint_policies.nothing_saveable,
                )(x)
            else:
                loss = _masked_shifted_mean(
                    optax_softmax_cross_entropy(logits[:, :-1, :], labels[:, 1:]),
                    segment_ids,
                )
            if cfg.num_experts > 0:
                # MoE auxiliaries (mean over layers). The layer returns them
                # pre-weighted: moe_aux_weight * load-balance +
                # router_z_weight * z-loss (models/moe.py).
                loss = loss + moe_aux / cfg.num_layers
        return logits, loss


def _masked_shifted_mean(ce: jax.Array, segment_ids) -> jax.Array:
    """Mean of per-position shifted CE ``[b, s-1]``, dropping positions whose
    next-token target crosses a packed-document boundary (or is padding).
    With ``segment_ids=None`` this is a plain mean — the unpacked path."""
    if segment_ids is None:
        return jnp.mean(ce)
    from tpu_trainer.ops.loss import segment_target_mask

    m = segment_target_mask(segment_ids)[:, :-1]
    return jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)


def optax_softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Integer-label softmax cross entropy without the optax import cycle."""
    logits = logits.astype(jnp.float32)
    log_z = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return log_z - label_logits


def count_parameters(params) -> int:
    """Total parameter count (reference ``gpt.py:487-489``)."""
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


@functools.partial(
    jax.jit, static_argnames=("config", "max_new_tokens", "temperature", "top_k")
)
def generate(
    params,
    rng: jax.Array,
    input_ids: jax.Array,
    *,
    config: GPTConfig,
    max_new_tokens: int = 100,
    temperature: float = 1.0,
    top_k: int = 50,
    prompt_len: Optional[jax.Array] = None,
    num_new: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive sampling (reference ``gpt.py:457-484``), fully jitted.

    Same semantics as the reference: crop context to ``max_seq_len``, divide
    logits by ``temperature``, keep the top-k logits (when ``top_k > 0``),
    sample from the resulting distribution, append. The reference's Python
    loop with a growing tensor becomes a fixed-size buffer + ``lax.fori_loop``
    (static shapes; one compile per (input width, max_new_tokens)).

    ``prompt_len`` (a traced int scalar, <= the input width) makes the input
    width a *bucket* rather than the semantic prompt length: generation
    starts at ``prompt_len`` and padding beyond it is never attended (causal
    masking makes positions >= the current index invisible). ``num_new``
    (traced, <= ``max_new_tokens``) likewise makes the new-token count a
    bucket: the loop runs only the requested steps. Together these let
    ``generate_bucketed`` reuse one compile across prompt lengths and
    new-token counts without executing padded decode steps.

    The reference recomputes the full forward each step with no KV cache
    (``infer.py`` hot loop, SURVEY.md §3.5); a windowed full forward matches
    that exactly. ``generate_kv`` is the cached fast path.
    """
    model = GPT(config)
    b, width = input_ids.shape
    total = width + max_new_tokens
    window = min(total, config.max_seq_len)
    start_i = width if prompt_len is None else prompt_len

    buf = jnp.zeros((b, total), dtype=input_ids.dtype)
    buf = jax.lax.dynamic_update_slice(buf, input_ids, (0, 0))

    def body(i, carry):
        buf, rng = carry
        # Window of the last `window` tokens ending just before position i.
        start = jnp.clip(i - window, 0, total - window)
        ids = jax.lax.dynamic_slice(buf, (0, start), (b, window))
        logits, _ = model.apply({"params": params}, ids)
        pos = i - 1 - start  # index of the newest real token inside the window
        last = jax.lax.dynamic_slice(logits, (0, pos, 0), (b, 1, logits.shape[-1]))[:, 0]
        rng, sub = jax.random.split(rng)
        nxt = _sample(last, sub, temperature, top_k).astype(buf.dtype)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, i))
        return buf, rng

    n_new = max_new_tokens if num_new is None else num_new
    buf, _ = jax.lax.fori_loop(start_i, start_i + n_new, body, (buf, rng))
    return buf


def _bucket(n: int, floor: int = 16) -> int:
    """Next power of two >= n (>= floor)."""
    b = floor
    while b < n:
        b *= 2
    return b


def generate_bucketed(
    params,
    rng: jax.Array,
    input_ids: jax.Array,
    *,
    config: GPTConfig,
    max_new_tokens: int = 100,
    temperature: float = 1.0,
    top_k: int = 50,
) -> jax.Array:
    """``generate`` with bucketed compile shapes (VERDICT r1 weak #7).

    The jitted ``generate`` compiles once per (input width, max_new_tokens)
    pair — every new prompt length used to pay a full XLA compile. Here the
    prompt pads up to a power-of-two bucket and the new-token count rounds
    up likewise, with the true ``prompt_len`` passed as a *traced* scalar:
    any prompt in the same bucket reuses the compile, and the result is
    sliced back to exactly ``prompt + max_new_tokens``. Sampling semantics
    are identical (padding is never attended; the sampling loop runs the
    same positions with the same key folds).
    """
    b, true_len = input_ids.shape
    width = _bucket(true_len)
    new_bucket = _bucket(max_new_tokens)
    if (width + new_bucket > config.max_seq_len
            >= true_len + max_new_tokens) or width > config.max_seq_len:
        # Bucket rounding would engage the context-window crop earlier than
        # the exact shapes do (window = min(total, max_seq_len)); keep exact
        # reference semantics and pay the compile.
        return generate(
            params, rng, input_ids,
            config=config, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k,
        )
    padded = jnp.zeros((b, width), input_ids.dtype)
    padded = jax.lax.dynamic_update_slice(padded, input_ids, (0, 0))
    buf = generate(
        params, rng, padded,
        config=config, max_new_tokens=new_bucket, temperature=temperature,
        top_k=top_k, prompt_len=jnp.asarray(true_len, jnp.int32),
        num_new=jnp.asarray(max_new_tokens, jnp.int32),
    )
    return jax.lax.dynamic_slice(
        buf, (0, 0), (b, true_len + max_new_tokens)
    )


def init_cache(config: GPTConfig, batch_size: int):
    """Zero-initialized KV cache pytree for ``generate_kv``."""
    model = GPT(config)
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((batch_size, 1), jnp.int32),
            decode=True,
        )["cache"]
    )
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def init_paged_cache(config: GPTConfig, batch_size: int):
    """Zero-initialized PAGED cache pytree (``config.decode_paged``): the
    block pools, per-row block tables, and lengths every layer's
    ``_paged_decode_attention`` reads. The serving engine overwrites the
    ``tables``/``lengths`` leaves from its host-side scheduler state
    before each jitted step (serving/engine.py)."""
    if not config.decode_paged:
        raise ValueError("init_paged_cache needs config.decode_paged=True")
    return init_cache(config, batch_size)


def _sample(logits, rng, temperature: float, top_k: int):
    """Temperature + top-k categorical sampling (reference gpt.py:473-482).

    ``temperature == 0`` is exact greedy argmax (temperature is static
    under jit, so this is a trace-time branch) — it used to divide by
    zero and sample NaN logits."""
    if temperature == 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits)


def generate_kv(
    params,
    rng: jax.Array,
    input_ids: jax.Array,
    *,
    config: GPTConfig,
    max_new_tokens: int = 100,
    temperature: float = 1.0,
    top_k: int = 50,
    prompt_lens: Optional[jax.Array] = None,
) -> jax.Array:
    """KV-cached autoregressive sampling: one prefill pass over the prompt,
    then one single-token forward per generated token.

    Same sampling semantics as ``generate`` (temperature, top-k,
    ``max_seq_len`` context limit) but O(S) per token instead of the
    reference's O(S^2) full re-forward (``infer.py`` hot loop, SURVEY.md
    §3.5). Requires ``prompt_len + max_new_tokens <= config.max_seq_len``
    (the cache size); ``generate`` handles the windowed overflow case.

    Ragged batches: pass ``prompt_lens`` ([b] int32, true lengths of
    right-padded rows). Rows are re-packed LEFT-padded internally so every
    row shares one cache frontier; per-row pad offsets ride the cache
    collection and shift both the RoPE positions and the attention window,
    so padding is never attended and each row's positions start at its own
    first real token. Output rows come back right-padded (row r holds
    ``prompt_lens[r] + max_new_tokens`` real tokens, zero-filled beyond) —
    a mixed-length batch decodes in ONE call, where the reference's
    generator is batch-of-one (``infer.py:60-66``).

    This eager wrapper validates ``prompt_lens`` host-side (the jitted body
    only ever sees tracers, so it cannot); callers who jit *around*
    ``generate_kv`` skip this check and get the clamped-lengths behavior
    documented in the body.
    """
    if prompt_lens is not None and not isinstance(
        jnp.asarray(prompt_lens), jax.core.Tracer
    ):
        # Concrete lengths: fail loudly on impossible values — a length
        # beyond the padded width would silently repack garbage (negative
        # left-pad duplicates tokens and the attention window degenerates).
        b, width = input_ids.shape
        vals = np.asarray(prompt_lens)
        if vals.shape != (b,) or (vals <= 0).any() or (vals > width).any():
            raise ValueError(
                f"prompt_lens must be [batch]={b} values in "
                f"[1, {width}] (the padded width); got {vals}"
            )
    return _generate_kv_jit(
        params, rng, input_ids, config=config,
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, prompt_lens=prompt_lens,
    )


@functools.partial(
    jax.jit, static_argnames=("config", "max_new_tokens", "temperature", "top_k")
)
def _generate_kv_jit(
    params,
    rng: jax.Array,
    input_ids: jax.Array,
    *,
    config: GPTConfig,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    prompt_lens: Optional[jax.Array],
) -> jax.Array:
    import dataclasses as _dc

    if prompt_lens is not None:
        # Static switch: the per-row pad machinery only traces when asked
        # for (uniform decode keeps the cheaper shared-position path).
        config = _dc.replace(config, decode_ragged=True)
    b, prompt_len = input_ids.shape
    total = prompt_len + max_new_tokens
    if total > config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds the cache size (max_seq_len={config.max_seq_len}); "
            f"use generate() for windowed generation"
        )
    # Size the KV cache to what this call can actually fill (128-bucketed
    # so nearby shapes share a compile), not the model's context limit:
    # the decode attention's HBM reads are proportional to the cache
    # view, so a 384-token request against a 1024-token cache was paying
    # 2.7x the necessary read volume every step (VERDICT r4 #5).
    config = _dc.replace(
        config, decode_window=min(-(-total // 128) * 128, config.max_seq_len)
    )
    model = GPT(config)
    if max_new_tokens == 0:
        return input_ids
    cache = init_cache(config, b)

    pad = None
    if prompt_lens is not None:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
        # In here lengths are always tracers; out-of-range values (only
        # possible when the caller jitted over the eager wrapper's
        # validation) clamp to [1, padded width] rather than repacking
        # garbage.
        prompt_lens = jnp.clip(prompt_lens, 1, prompt_len)
        pad = (prompt_len - prompt_lens).astype(jnp.int32)     # [b]
        # Right-padded -> left-padded rows (shared decode frontier).
        cols = jax.lax.broadcasted_iota(jnp.int32, (b, prompt_len), 1)
        src = jnp.clip(cols - pad[:, None], 0, prompt_len - 1)
        input_ids = jnp.where(
            cols >= pad[:, None],
            jnp.take_along_axis(input_ids, src, axis=1),
            jnp.zeros((), input_ids.dtype),
        )
        # Per-row pad offsets enter every layer's decode attention through
        # its cache variable (models/gpt.py _decode_attention).
        cache = jax.tree_util.tree_map_with_path(
            lambda p, x: jnp.broadcast_to(pad, x.shape)
            if getattr(p[-1], "key", None) == "pad" else x,
            cache,
        )

    # Prefill: one pass over the whole prompt populates every layer's cache.
    (logits, _), vars_out = model.apply(
        {"params": params, "cache": cache},
        input_ids,
        decode=True,
        mutable=["cache"],
    )
    cache = vars_out["cache"]
    rng, sub = jax.random.split(rng)
    nxt = _sample(logits[:, -1], sub, temperature, top_k).astype(input_ids.dtype)

    buf = jnp.zeros((b, total), input_ids.dtype)
    buf = jax.lax.dynamic_update_slice(buf, input_ids, (0, 0))
    buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, prompt_len))

    def body(i, carry):
        buf, cache, rng = carry
        tok = jax.lax.dynamic_slice(buf, (0, i - 1), (b, 1))
        (logits, _), vars_out = model.apply(
            {"params": params, "cache": cache},
            tok,
            decode=True,
            mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits[:, -1], sub, temperature, top_k).astype(buf.dtype)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, i))
        return buf, vars_out["cache"], rng

    buf, _, _ = jax.lax.fori_loop(
        prompt_len + 1, total, body, (buf, cache, rng)
    )
    if pad is not None:
        # Left-padded -> right-padded output rows.
        cols = jax.lax.broadcasted_iota(jnp.int32, (b, total), 1)
        src = jnp.clip(cols + pad[:, None], 0, total - 1)
        real = cols < (total - pad)[:, None]
        buf = jnp.where(
            real, jnp.take_along_axis(buf, src, axis=1),
            jnp.zeros((), buf.dtype),
        )
    return buf


if __name__ == "__main__":
    # Smoke test mirroring the reference __main__ block (gpt.py:492-508).
    config = GPTConfig.gpt2_small(dropout=0.0, attention_dropout=0.0)
    model = GPT(config)
    rng = jax.random.PRNGKey(0)
    input_ids = jax.random.randint(rng, (2, 128), 0, config.vocab_size)
    params = model.init(rng, input_ids)["params"]

    print(f"Model config: {config}")
    print(f"Estimated parameters: {config.num_parameters():,}")
    print(f"Actual parameters: {count_parameters(params):,}")

    logits, loss = model.apply({"params": params}, input_ids, labels=input_ids)
    print(f"Logits shape: {logits.shape}")
    print(f"Loss: {float(loss):.4f}")


def pipeline_1f1b_value_and_grad(model: "GPT", mesh, num_microbatches: int):
    """Build a grad_fn with ``jax.value_and_grad``'s interface for the
    1F1B pipeline schedule (``GPTConfig.pipeline_schedule == "1f1b"``).

    The GPipe path differentiates the schedule scan by AD, which keeps all
    M microbatch activations alive at the bubble point; 1F1B needs the
    backward manually interleaved with the forward, so the loss and every
    gradient come out of ONE scheduled scan (``parallel/pipeline.py
    pipeline_1f1b``) and the usual ``value_and_grad`` around ``GPT.apply``
    is bypassed. This function replicates the model's embedding, stage
    block, and head-loss computations (same modules; the head+CE always
    runs blockwise AND vocab-sharded over the stage axis here — the same
    math as either ``fused_loss`` setting, computed as 1/S slices with
    explicit collectives), assembling the full parameter-gradient pytree:
    stacked layer grads from the schedule, the tied embedding's gradient
    as head + lookup contributions, and the final norm's from the head
    VJP.

    Dropout streams are folded per (global layer, microbatch) from the
    step rng directly — self-consistent and decorrelated, but a different
    (equally valid) stream than the GPipe path's ``make_rng`` derivation;
    loss-equivalence against GPipe holds exactly with dropout off.

    Composes with sequence parallelism (a non-trivial ``sequence`` mesh
    axis: the pipeline goes jointly manual over {stage, sequence}, the
    blocks route through the in-region ring attention, and the head's CE
    reads its next-token shift from the replicated global labels) and with
    MoE (``stage_fwd`` returns the stage's aux sum; its gradient rides the
    same stage vjp via a pre-scaled cotangent seed).

    Returns ``grad_fn(params, micro_ids, rng, loss_scale) ->
    ((loss * scale, loss), grads)``.
    """
    from tpu_trainer.parallel.pipeline import pipeline_1f1b

    cfg = model.config
    S = mesh.shape["stage"]
    v = (cfg.pipeline_virtual_stages
         if cfg.pipeline_schedule == "interleaved" else 1)
    lpc = cfg.num_layers // (S * v)  # layers per chunk
    M = num_microbatches
    sq = mesh.shape.get(ring.SEQ_AXIS, 1)
    manual_seq = ring.SEQ_AXIS if sq > 1 else None
    with_aux = cfg.num_experts > 0
    needs_rng = cfg.dropout > 0.0 or cfg.attention_dropout > 0.0
    block_mod = TransformerBlock(cfg, deterministic=False)
    norm_mod = RMSNorm(dtype=cfg.compute_dtype)
    policies = {
        "full": None,
        "dots": jax.checkpoint_policies.dots_saveable,
    }

    def grad_fn(params, ids, rng, loss_scale):
        emb = params["embed_tokens"]["embedding"]
        vocab, hidden = emb.shape

        def stage_fwd(chunk_params, xm, micro_idx, chunk_idx):
            def one_layer(carry, scanned):
                li, p = scanned
                rngs = {}
                if needs_rng:
                    # Global layer index: chunk `chunk_idx` of this device
                    # is global stage chunk_idx*S + stage (v=1: == stage).
                    g_stage = chunk_idx * S + jax.lax.axis_index("stage")
                    g_layer = g_stage * lpc + li
                    key = jax.random.fold_in(rng, g_layer * M + micro_idx)
                    if manual_seq is not None:
                        # Sequence shards see local slices and hash_dropout
                        # keys by LOCAL positions: fold the shard index so
                        # chunks don't repeat one mask (same rule as
                        # pipeline_forward).
                        key = jax.random.fold_in(
                            key, jax.lax.axis_index(manual_seq))
                    rngs = {"dropout": key}
                (xc, aux), _ = block_mod.apply(
                    {"params": p}, carry, rngs=rngs)
                return (xc, aux), None

            run = one_layer
            if cfg.gradient_checkpointing:
                run = jax.checkpoint(run, prevent_cse=False,
                                     policy=policies[cfg.remat_policy])
            (y, aux), _ = jax.lax.scan(
                run, (xm, jnp.zeros((), jnp.float32)),
                (jnp.arange(lpc), chunk_params),
            )
            return (y, aux) if with_aux else y

        # --- vocab-sharded head (VERDICT r3 #1) --------------------------
        # Each stage evaluates 1/S of the LM head + CE on the last stage's
        # broadcast output; explicit pmax/psum over the stage axis stitch
        # the softmax (ops/loss.py vocab_sharded_shifted_cross_entropy —
        # custom_vjp, so AD never transposes a collective). Head FLOPs per
        # microbatch total ONE full evaluation, split S ways.
        v_s = -(-vocab // S)  # ceil: the last slice may overhang
        emb_padded = jnp.pad(emb, ((0, S * v_s - vocab), (0, 0)))

        def head_vjp(y_bc, labels_mb, micro_idx):
            off = jax.lax.axis_index("stage") * v_s
            e_slice = jax.lax.dynamic_slice(
                emb_padded, (off, 0), (v_s, hidden)
            )

            def f(yy, e_, nw_):
                xn = norm_mod.apply({"params": nw_}, yy)
                return vocab_sharded_shifted_cross_entropy(
                    e_, xn, labels_mb, vocab=vocab, axis_name="stage",
                    chunk_size=cfg.loss_chunk_size, seq_axis=manual_seq,
                )

            loss_m, pull = jax.vjp(f, y_bc, e_slice, params["norm"])
            dy_part, de_slice, dnorm = pull(
                jnp.asarray(loss_scale / M, jnp.float32))
            # dy: the pullback's x-cotangent is this stage's vocab-slice
            # partial — one psum (in the activation dtype, what AD of the
            # bf16 forward would move) makes it the full cotangent.
            dy = jax.lax.psum(
                dy_part.astype(cfg.compute_dtype), "stage"
            )
            # Parameter-grad accumulators stay f32; the norm grad is also
            # a per-stage partial (linearity: psummed with the rest at the
            # end of the schedule).
            return (loss_m / M,
                    dy,
                    {"embedding_slice": de_slice.astype(jnp.float32),
                     "norm": jax.tree_util.tree_map(
                         lambda g: g.astype(jnp.float32), dnorm)})

        def head_finalize(acc):
            # Scatter this stage's [v_s, hidden] slice gradient into its
            # rows of the full [vocab, hidden] table (other rows zero; the
            # pipeline's final psum assembles the table from all stages).
            off = jax.lax.axis_index("stage") * v_s
            full = jax.lax.dynamic_update_slice(
                jnp.zeros((S * v_s, hidden), jnp.float32),
                acc["embedding_slice"], (off, 0),
            )[:vocab]
            return {"embedding": full, "norm": acc["norm"]}

        def emb_accum(acc, dx, ids_mb):
            # d(embedding lookup): scatter-add each token's cotangent row.
            flat = ids_mb.reshape(-1)
            return acc.at[flat].add(dx.reshape(-1, hidden))

        head_zeros = {
            "embedding_slice": jnp.zeros((v_s, hidden), jnp.float32),
            "norm": jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params["norm"]),
        }
        emb_zeros = jnp.zeros((vocab, hidden), jnp.float32)

        x = jnp.take(
            emb.astype(cfg.compute_dtype), ids, axis=0
        )  # nn.Embed semantics: cast table, then gather
        import contextlib as _cl

        seq_cm = (ring.sequence_parallel_manual(mesh) if manual_seq
                  else _cl.nullcontext())
        # aux cotangent per microbatch backward: d total_loss / d aux_layer
        # = loss_scale / (M * num_layers * sq) — matching the GPipe
        # estimator (mean over micros and seq shards, /num_layers in the
        # model's loss assembly).
        aux_args = {}
        if with_aux:
            aux_args = dict(
                with_aux=True,
                aux_seed=jnp.asarray(
                    loss_scale / (M * cfg.num_layers * sq), jnp.float32),
            )
        with seq_cm:
            out = pipeline_1f1b(
                params["layers"], x, ids, ids, stage_fwd, head_vjp,
                head_zeros, emb_accum, emb_zeros, mesh, M,
                head_finalize=head_finalize, manual_seq_axis=manual_seq,
                virtual_stages=v,
                **aux_args,
            )
        if with_aux:
            loss_mean, aux_raw, dlayers, dhead, de_lookup = out
            loss_mean = loss_mean + aux_raw / (M * cfg.num_layers * sq)
        else:
            loss_mean, dlayers, dhead, de_lookup = out
        # The lookup's cotangent arrives unscaled by loss_scale/M? No — dx
        # flowed from head_vjp's scaled seed through the stage backwards,
        # so every gradient here already carries loss_scale / M per micro,
        # summed over micros.
        grads = {
            "embed_tokens": {"embedding": dhead["embedding"] + de_lookup},
            "layers": dlayers,
            "norm": dhead["norm"],
        }
        return (loss_mean * loss_scale, loss_mean), grads

    return grad_fn
