"""Model configuration.

TPU-native re-design of the reference model config
(``/root/reference/src/models/config.py:6-102``). Differences from the reference,
by design:

- Frozen (hashable) dataclass so it can be a static argument to ``jax.jit``.
- ``num_parameters()`` is exact for the *actual* architecture (RoPE + RMSNorm +
  SwiGLU + tied embeddings). The reference's estimate counts a learned positional
  embedding the model does not have and 4 LayerNorm params/layer where RMSNorm has
  one weight vector (reference ``config.py:81-102`` — SURVEY.md §2.1 b7).
- ``activation`` defaults to ``"silu"`` and is honored; the reference declares
  ``"gelu"`` but hardcodes SiLU in the MLP (``gpt.py:280`` — SURVEY.md §2.1 b9).
- Adds the compute/parameter dtype policy (TPU bf16-compute / fp32-params recipe),
  replacing torch autocast (reference ``ddp_trainer.py:115-156``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def dtype_of(name: str):
    """Map a dtype name ('float32' | 'bfloat16' | 'float16') to a jnp dtype."""
    return _DTYPES[name]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Configuration for the GPT model (defaults = GPT-2 124M / "small").

    Architecturally LLaMA-style — RMSNorm, RoPE, SwiGLU, no biases, pre-norm,
    tied embeddings — with GPT-2's vocabulary, mirroring the reference
    (``/root/reference/src/models/gpt.py``; SURVEY.md §2.1 b9).
    """

    # Model architecture (reference config.py:13-19)
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    # Grouped-query attention (beyond-reference, LLaMA-2/3-style): number of
    # K/V heads; None = num_heads (classic multi-head). Each group of
    # num_heads // num_kv_heads query heads shares one K/V head — the KV
    # cache, the k/v projections, and the ring-attention K/V traffic all
    # shrink by the group factor.
    num_kv_heads: Optional[int] = None
    intermediate_size: Optional[int] = None  # defaults to 4 * hidden_size
    max_seq_len: int = 1024

    # Regularization (reference config.py:21-23)
    dropout: float = 0.1
    attention_dropout: float = 0.1

    # Initialization (reference config.py:25-26)
    initializer_range: float = 0.02

    # Activation — honored here (SiLU), unlike the reference's dead field.
    activation: str = "silu"

    # RoPE base frequency (reference gpt.py:76 hardcodes 10000)
    rope_theta: float = 10000.0

    # Mixture-of-Experts (0 = dense; beyond-reference model family). When
    # num_experts > 0 every block's feed-forward becomes a routed expert
    # SwiGLU (models/moe.py): Switch-style top-1 by default, GShard-style
    # top-2 (renormalized gates, first-choice priority at capacity) with
    # moe_top_k=2. Experts shard over the mesh's 'expert' axis AND their
    # FFN dims over 'tensor' (EP x TP composes). router_z_weight adds the
    # ST-MoE router z-loss (mean logsumexp^2 of router logits — keeps
    # logits from drifting to magnitudes where softmax saturates).
    num_experts: int = 0
    moe_top_k: int = 1
    expert_capacity_factor: float = 1.25
    # Token routing implementation (models/moe.py): "gather" fills each
    # expert slot by index gather (O(T*k) integer bookkeeping + two
    # [E*C, H] gathers; measured 30% of the MoE step back at E=8);
    # "einsum" uses one-hot dispatch/combine matmuls (2*T*E*C*H FLOPs
    # each — the MXU does the routing, and GSPMD lowers EP to a clean
    # token all-to-all, which gathers do NOT give it); "auto" (default)
    # picks gather on meshes without an expert axis and einsum under
    # expert parallelism. Same semantics every way, pinned by oracle
    # tests.
    moe_dispatch: str = "auto"
    # Routing discipline (models/moe.py): "capacity" (default) is the
    # Switch/GShard scheme above — fixed per-expert slots C, tokens past
    # capacity dropped, dense [E, C, H] expert matmuls (moe_dispatch picks
    # how tokens reach the slots). "dropless" is MegaBlocks-style
    # (arXiv:2211.15841): token-choices are argsorted into expert order
    # and all three SwiGLU projections run as grouped matmuls
    # (ops/grouped_matmul.gmm) sized by the true per-expert counts — no
    # capacity_factor, drop_frac == 0 by construction, and expert compute
    # scales with the tokens actually routed instead of E*C.
    moe_impl: str = "capacity"
    moe_aux_weight: float = 0.01
    router_z_weight: float = 0.0

    # Optimization flags (reference config.py:30-32)
    use_flash_attention: bool = False
    gradient_checkpointing: bool = False
    # Rematerialization policy when gradient_checkpointing is on:
    # "full"  — save only block inputs, recompute everything (the reference's
    #           activation-checkpointing semantics; max memory savings);
    # "dots"  — save matmul outputs, recompute elementwise chains (dropout
    #           masks, norms, activations). Cheaper in compute than "full"
    #           and cuts the per-layer activation stores that dominate HBM
    #           write traffic in the unremated step.
    remat_policy: str = "full"
    # Rematerialize the LM head + cross entropy in the backward pass:
    # nothing of the [batch, seq, vocab] softmax survives the forward (the
    # single biggest activation — 1.6 GB f32 at bs=8/seq=1024/V=50257);
    # backward recomputes one vocab matmul instead. Independent of
    # gradient_checkpointing. Off by default (a memory knob: costs ~4.5%
    # step time on v5e, measured). Subsumed by fused_loss (below), which is
    # both faster *and* lighter; this flag only matters with fused_loss off.
    remat_lm_head: bool = False
    # Compute the training loss via the blockwise fused LM-head + cross
    # entropy (ops/loss.py): full [batch, seq, vocab] logits never
    # materialize in either pass. Identical math to the reference's
    # F.cross_entropy over materialized logits (gpt.py:450-453); measured
    # 4.4x faster at small/bs=8/seq=1024 on v5e, where the logits buffer's
    # HBM traffic was 28% of the step. Affects the loss only — the logits
    # output of __call__ is unchanged.
    fused_loss: bool = True
    # Sequence-chunk length for fused_loss; 0 = auto (~8k tokens per chunk).
    loss_chunk_size: int = 0
    # On compiled TPU, compute the fused loss with the Pallas head kernel
    # (ops/head_ce.py): the softmax statistics ride through the head matmul
    # online (flash-attention-style), deleting the separate logsumexp HBM
    # pass over the [tokens, vocab] block, and the backward reads saved
    # compute-dtype logits instead of re-using the f32 block. Loss stays
    # exact f32; backward probabilities carry bf16 rounding (same order as
    # the flash kernel's backward). The kernel shard_maps over batch
    # (data x fsdp) AND sequence axes, and an expert axis (which shards
    # only expert params) does not block it. Falls back off-TPU; under a
    # stage axis the pipeline owns the head, and under single-stage TP
    # the loss routes to the vocab-sharded XLA head (ops/loss._tp_loss).
    fused_loss_pallas: bool = True
    # GPipe microbatch count when the mesh has a `stage` axis > 1
    # (parallel/pipeline.py); 0 = auto (one microbatch per stage). More
    # microbatches -> smaller pipeline bubble, smaller per-step matmuls.
    pipeline_microbatches: int = 0
    # Pipeline schedule: "gpipe" (AD of the forward scan — all M
    # microbatch activations live at the bubble point), "1f1b"
    # (manually scheduled interleaved backward — at most min(M, 2S-1)
    # stage inputs in flight, M-independent; stage blocks rematerialize
    # in the backward), or "interleaved" (virtual-stage 1F1B: each device
    # holds `pipeline_virtual_stages` non-contiguous layer chunks, cutting
    # the bubble from (S-1)/(M+S-1) to ~(S-1)/(vM+S-1) at the cost of a
    # ~v x larger saved-input window). All three compose with SP and MoE.
    pipeline_schedule: str = "gpipe"
    # Layer chunks per device under pipeline_schedule="interleaved"
    # (Megatron's virtual pipeline stages); ignored by other schedules.
    # Requires num_layers % (stages * v) == 0 and microbatches % stages
    # == 0.
    pipeline_virtual_stages: int = 2
    # Counter-based dropout masks (ops/dropout.py) instead of threefry
    # bernoulli: same Bernoulli semantics, ~5x cheaper mask generation
    # (threefry masks measured ~9% of the headline step). Applies to the
    # residual/MLP dropout; attention-weight dropout inside the flash kernel
    # is always counter-based.
    fast_dropout: bool = True
    # Run the layer stack as an unrolled per-layer loop at apply time.
    # Parameters stay stacked [num_layers, ...] (checkpoint/sharding layout
    # unchanged — nn.scan still creates them), but each layer executes as
    # straight-line code on a static slice, with the stacked parameter
    # gradient rebuilt by one concatenate (models/gpt.py:_unstack_layers)
    # instead of the scan's per-layer dynamic-update-slice copies (~25% of
    # the headline step's device time, measured). Costs compile time (body
    # traced num_layers times); the rolled scan remains for decode and is
    # the right choice for very deep models or fast iteration.
    scan_unroll: bool = True

    # Fuse the q/k/v projections into one [H, H+2*kv] matmul and gate/up
    # into one [H, 2I] matmul (models/gpt.py): the input activations are
    # read from HBM once per fused group and the MXU sees one wide dot
    # instead of two or three narrow ones. Parameters stay separate
    # (checkpoint layout and name-based sharding rules unchanged — the
    # concatenate is a compute-graph detail). The Trainer and the decode
    # CLI force this off when the mesh's tensor axis > 1: TP shards those
    # kernels along exactly the axis the fusion concatenates.
    fused_projections: bool = True

    # --- Paged decode (the serving engine's cache layout) ---------------
    # Static switch: route _decode_attention through the PAGED KV cache —
    # fixed-size blocks in a preallocated pool addressed by per-row block
    # tables (vLLM-style PagedAttention; tpu_trainer/serving/). Set by
    # ServingEngine via dataclasses.replace; mutually exclusive with
    # decode_ragged (the contiguous ragged path). Not a training knob.
    decode_paged: bool = False
    # Pool geometry, static so the cache variables (and jit) specialize:
    # tokens per block / total blocks in the pool (block 0 reserved as the
    # null block writes of masked rows land in) / block-table width =
    # per-request capacity ceiling in blocks.
    paged_block_size: int = 16
    paged_num_blocks: int = 0
    paged_max_blocks: int = 0
    # Store the paged pools as blockwise-absmax int8 (utils/quant.py —
    # the optimizer-state scheme pointed at the KV cache): halves-to-
    # quarters pool HBM, ~1e-2 relative error on the attention output
    # (documented tolerance; greedy streams may diverge where logits are
    # near-tied).
    paged_kv_int8: bool = False
    # Decode-attention implementation over the pool: "reference" (pure
    # jnp gather — the CPU path), "kernel" (Pallas flash-decode,
    # interpret off-TPU), "auto" = kernel on TPU, reference elsewhere.
    paged_attention: str = "auto"
    # Chunked-prefill history width for THIS dispatch, in blocks: when a
    # prefill chunk starts past position 0 (the request's earlier chunks
    # or a shared prefix already sit in the pool), the chunk's queries
    # must also attend to the first ``paged_hist_blocks`` table entries
    # of pooled history. Static so the gather shape specializes with the
    # width bucket; 0 = no history read — offset-0 prefill, the original
    # monolithic path. Set per dispatch by the serving engine via
    # dataclasses.replace; never a user knob.
    paged_hist_blocks: int = 0

    # Tensor-parallel decode: shard the paged engine's dispatch over a
    # single-axis ("tp",) device mesh — Q heads split paged_tp ways, KV
    # pools shard on their kv-heads axis when divisible (else replicate:
    # the GQA kv_heads < tp mode), params commit sharded and gather to
    # replicated inside the step (serving/sharding.py). Set by
    # ServingEngine via dataclasses.replace from its mesh_tensor /
    # mesh_devices kwargs — never a user-facing model knob. Because the
    # jitted-step memos key on the (hashable) config, carrying the tp
    # degree AND the device-id tuple here is what keeps two engines with
    # otherwise-equal configs but different meshes from sharing one jit
    # (the latent wrong-device-dispatch bug at tp=1 too: an explicit
    # device set at tp=1 still changes the key).
    paged_tp: int = 1
    paged_tp_devices: Optional[Tuple[int, ...]] = None

    # Static switch for the ragged (per-row prompt length) KV-decode path:
    # set internally by generate_kv(prompt_lens=...); uniform decode keeps
    # the cheaper shared-position attention. Not a training knob.
    decode_ragged: bool = False
    # KV-cache view length for decode (0 = max_seq_len). generate_kv sets
    # this per call to prompt+new rounded up to 128: the cache allocates
    # and the decode attention reads only this prefix instead of the full
    # max_seq_len buffer — the attention's HBM reads scale with what can
    # actually be filled, not the model's context limit (VERDICT r4 #5).
    # Static, so it participates in jit specialization like the prompt
    # shape already does.
    decode_window: int = 0

    # REPRODUCIBILITY NOTE: fused_loss, fast_dropout, and scan_unroll
    # default on as of v0.2, and the dropout-hash gained a second mix round
    # in v0.3. Each changes the dropout RNG stream and/or loss reduction
    # numerics relative to v0.1 — the same seed no longer reproduces a
    # v0.1 run bit-for-bit (checkpoint/param layout is unchanged). To
    # compare training curves against old runs, pin fused_loss=False,
    # fast_dropout=False, scan_unroll=False deliberately.

    # TPU dtype policy: compute dtype for activations/matmuls; params and the
    # softmax/loss accumulations stay float32.
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            object.__setattr__(self, "intermediate_size", 4 * self.hidden_size)
        assert self.hidden_size % self.num_heads == 0, (
            f"hidden_size ({self.hidden_size}) must be divisible by "
            f"num_heads ({self.num_heads})"
        )
        # num_kv_heads stays None (= num_heads) rather than being
        # materialized: dataclasses.replace(cfg, num_heads=...) must keep
        # working on configs that never asked for GQA. Resolved via the
        # kv_heads property.
        if self.num_kv_heads is not None:
            assert self.num_heads % self.num_kv_heads == 0, (
                f"num_heads ({self.num_heads}) must be divisible by "
                f"num_kv_heads ({self.num_kv_heads})"
            )
        if self.num_experts > 0 and not (
            1 <= self.moe_top_k <= self.num_experts
        ):
            raise ValueError(
                f"moe_top_k ({self.moe_top_k}) must be in "
                f"[1, num_experts={self.num_experts}]"
            )
        if self.moe_dispatch not in ("auto", "gather", "einsum"):
            raise ValueError(
                f"unknown moe_dispatch {self.moe_dispatch!r}; "
                f"choose auto, gather, or einsum"
            )
        if self.moe_impl not in ("capacity", "dropless"):
            raise ValueError(
                f"unknown moe_impl {self.moe_impl!r}; "
                f"choose capacity or dropless"
            )
        if self.pipeline_schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"unknown pipeline_schedule {self.pipeline_schedule!r}; "
                f"choose gpipe, 1f1b, or interleaved"
            )
        if (self.pipeline_schedule == "interleaved"
                and self.pipeline_virtual_stages < 2):
            raise ValueError(
                f"pipeline_schedule='interleaved' needs "
                f"pipeline_virtual_stages >= 2 "
                f"(got {self.pipeline_virtual_stages}); v=1 is plain 1f1b"
            )
        if self.paged_attention not in ("auto", "reference", "kernel"):
            raise ValueError(
                f"unknown paged_attention {self.paged_attention!r}; "
                f"choose auto, reference, or kernel"
            )
        if self.decode_paged:
            if self.decode_ragged:
                raise ValueError(
                    "decode_paged and decode_ragged are mutually exclusive"
                )
            if self.paged_num_blocks < 2 or self.paged_max_blocks < 1:
                raise ValueError(
                    "decode_paged needs paged_num_blocks >= 2 (block 0 is "
                    "the reserved null block) and paged_max_blocks >= 1"
                )
            if not 0 <= self.paged_hist_blocks <= self.paged_max_blocks:
                raise ValueError(
                    f"paged_hist_blocks ({self.paged_hist_blocks}) must be "
                    f"in [0, paged_max_blocks={self.paged_max_blocks}]"
                )
        # TP decode feasibility + hashability: the devices tuple may
        # arrive as a JSON list (worker specs round-trip the config dict);
        # coerce so the frozen config stays a valid static jit argument.
        if self.paged_tp_devices is not None and not isinstance(
                self.paged_tp_devices, tuple):
            object.__setattr__(
                self, "paged_tp_devices",
                tuple(int(d) for d in self.paged_tp_devices))
        if self.paged_tp != 1:
            from tpu_trainer.serving.sharding import validate_tp

            validate_tp(self.num_heads, self.kv_heads, self.paged_tp)
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; "
                f"choose from ['dots', 'full']"
            )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        """Resolved K/V head count (num_kv_heads, defaulting to num_heads)."""
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)

    @property
    def compute_dtype(self):
        return dtype_of(self.dtype)

    @property
    def params_dtype(self):
        return dtype_of(self.param_dtype)

    # --- Size presets (reference config.py:41-79) ------------------------------

    @classmethod
    def gpt2_small(cls, **overrides) -> "GPTConfig":
        """GPT-2 124M-class configuration."""
        return cls(vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12,
                   **overrides)

    @classmethod
    def gpt2_medium(cls, **overrides) -> "GPTConfig":
        """GPT-2 355M-class configuration."""
        return cls(vocab_size=50257, hidden_size=1024, num_layers=24, num_heads=16,
                   **overrides)

    @classmethod
    def gpt2_large(cls, **overrides) -> "GPTConfig":
        """GPT-2 774M-class configuration."""
        return cls(vocab_size=50257, hidden_size=1280, num_layers=36, num_heads=20,
                   **overrides)

    @classmethod
    def gpt2_xl(cls, **overrides) -> "GPTConfig":
        """GPT-2 1.5B-class configuration."""
        return cls(vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25,
                   **overrides)

    @classmethod
    def preset(cls, name: str, **overrides) -> "GPTConfig":
        presets = {
            "small": cls.gpt2_small,
            "medium": cls.gpt2_medium,
            "large": cls.gpt2_large,
            "xl": cls.gpt2_xl,
        }
        if name not in presets:
            raise ValueError(f"unknown model size {name!r}; choose from {sorted(presets)}")
        return presets[name](**overrides)

    def num_parameters(self) -> int:
        """Exact parameter count of the actual model.

        embed (tied with lm_head): V*H
        per layer: attention 2*H^2 (q/o) + 2*H*(kv_heads*head_dim) (k/v —
                   equals 4*H^2 total without GQA), no bias
                   + FFN: SwiGLU 3*H*I (dense) or E*3*H*I + H*E router (MoE)
                   + 2 RMSNorm weight vectors (2*H)
        final RMSNorm: H
        """
        h, i = self.hidden_size, self.intermediate_size
        kv = self.kv_heads * self.head_dim
        embed = self.vocab_size * h
        if self.num_experts > 0:
            ffn = self.num_experts * 3 * h * i + h * self.num_experts
        else:
            ffn = 3 * h * i
        attn = 2 * h * h + 2 * h * kv  # q/o full, k/v grouped
        per_layer = attn + ffn + 2 * h
        return embed + self.num_layers * per_layer + h

    def num_active_parameters(self) -> int:
        """Parameters a single token actually flows through: for MoE, only
        the ``moe_top_k`` routed experts' FFNs count (plus the router);
        dense models: == ``num_parameters()``. This is the N that belongs
        in the 6N FLOPs/token estimate — total-parameter MFU overstates
        MoE utilization by ~E/top_k on the FFN share."""
        if self.num_experts <= 0:
            return self.num_parameters()
        h, i = self.hidden_size, self.intermediate_size
        inactive_ffn = (self.num_experts - self.moe_top_k) * 3 * h * i
        return self.num_parameters() - self.num_layers * inactive_ffn
