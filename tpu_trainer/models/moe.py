"""Mixture-of-Experts feed-forward with expert parallelism.

Beyond-reference capability (the reference has a single dense model family;
SURVEY.md §2 lists EP as absent): a Switch-Transformer-style top-1 routed
MoE that drops into the TransformerBlock in place of the dense SwiGLU when
``GPTConfig.num_experts > 0``.

TPU-native shape: experts are *stacked* (``[E, ...]`` parameter leaves, like
the layer stack). Token routing has two interchangeable implementations
(``GPTConfig.moe_dispatch``; identical semantics pinned by oracle tests):

- **gather** (default off expert-parallel meshes, round 4): each expert
  slot gathers its token's row and each token gathers its k expert
  outputs back — O(T*k) integer bookkeeping plus two [E*C, H]-volume
  gathers (AD transposes to the matching scatter-adds). Measured: the
  one-hot alternative burned 30% of the MoE step multiplying by zeros.
- **einsum** (default under expert parallelism): dispatch/combine as
  one-hot matmuls — the MXU does the routing, and sharding the expert
  leaves over the ``expert`` mesh axis makes GSPMD emit the token
  all-to-all between data-sharded tokens and expert-sharded FFNs
  automatically (a lowering the gather formulation does not offer it).

No collective appears in this file either way.

Mechanics (Switch Transformer, arXiv:2101.03961; top-2 per GShard/ST-MoE):

- router: ``logits [T, E]`` in f32; top-k experts per token
  (``moe_top_k``: 1 = Switch, gate = router prob; 2 = GShard, gates
  renormalized over the chosen pair).
- capacity ``C = ceil(k*T/E * capacity_factor)``; per-expert positions
  come from a cumsum over the one-hot assignments in *choice-major* order
  (every token's first choice queues before any second choice — at
  capacity, second choices drop first); tokens beyond capacity are
  dropped (contribute zero, like the papers).
- aux losses, returned PRE-WEIGHTED as one scalar the model adds
  directly: ``moe_aux_weight * (E * sum_e f_e * p_e)`` (load balance,
  over first-choice assignment fractions) plus ``router_z_weight *
  mean(logsumexp(logits)^2)`` (ST-MoE z-loss, arXiv:2202.08906 — keeps
  router logits from drifting into softmax saturation).

``GPTConfig.moe_impl="dropless"`` replaces the capacity machinery above
with MegaBlocks-style token-dropless routing (arXiv:2211.15841): the
``T*k`` token-choice rows are permuted into expert order (one stable
argsort), per-expert group sizes come from a bincount of the routing
(no capacity ``C`` exists, so ``drop_frac`` is 0 by construction), and
all three SwiGLU projections run as grouped matmuls
(``ops/grouped_matmul.gmm``) whose compute scales with the tokens each
expert actually received. The inverse permutation gathers rows back and
the top-k gates weight the combine. Router, gates, and aux/z losses are
shared with the capacity path; telemetry reports the TRUE post-routing
load (the bincount) rather than pre-capacity first-choice fractions,
plus a ``max_group_frac`` collapse indicator.
"""

from __future__ import annotations

import math
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.ops.grouped_matmul import gmm
from tpu_trainer.utils import telemetry


# --- all-gather dispatch/combine (custom_vjp) ------------------------------
#
# The dispatch map is a BIJECTION between kept token-choices (t, j) and
# expert slots s (dropped choices hit a trailing trash slot / trash row on
# either side). AD's transpose of a row gather is a row scatter-add, and on
# v5e those scatter-adds measured ~45 GB/s (row-serial read-modify-write at
# sub-sublane granularity) against ~420 GB/s for the matching gathers —
# 14.1 ms of the 179 ms top-2 step (round-5 xplane). The bijection lets
# every transpose be re-expressed as a gather through the INVERSE map, so
# both passes of both movements run at gather speed:
#
#   dispatch fwd:  expert_in[s] = x[token(s)]                 (gather)
#   dispatch bwd:  dx[t]        = sum_j d_ein[slot(t, j)]     (k gathers)
#   combine  fwd:  out[t]       = sum_j g[t,j] * eo[slot(t,j)] (k gathers)
#   combine  bwd:  d_eo[s]      = g[tc(s)] * dout[token(s)]    (one gather)
#                  d_g[t,j]     = <dout[t], eo[slot(t,j)]>     (k gathers)
#
# ``slot_token`` maps slot -> source token (trash slots -> T, the zero pad
# row); ``flat_ids`` maps (t, j) -> slot (dropped -> S, the zero pad row);
# ``slot_tc`` maps slot -> flat token-choice in CHOICE-MAJOR order
# (j*T + t; trash -> k*T). Choice-major is load-bearing twice: the k
# per-choice gathers address clean [T, H] panels (token-major produced a
# [T, k, H] intermediate whose T(2,128) tile layout cost ~2 ms/step of
# relayout), and the combine backward's gate-scaled rows concatenate as
# ``[dout * g_0; dout * g_1; ...]`` — a [k*T, H] buffer in natural layout,
# so d_eo is ONE row gather instead of a row gather times a 1-D gate
# gather (1-D gathers run element-serial on TPU; measured ~1 ms/step).


@jax.custom_vjp
def _dispatch_rows(x, slot_token, flat_ids):
    """Gather token rows into expert slots: ``x [T, H] -> [S, H]``."""
    x_pad = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)], axis=0)
    return x_pad[slot_token]


def _dispatch_rows_fwd(x, slot_token, flat_ids):
    return _dispatch_rows(x, slot_token, flat_ids), flat_ids


def _dispatch_rows_bwd(flat_ids, d_ein):
    d_pad = jnp.concatenate(
        [d_ein, jnp.zeros((1, d_ein.shape[1]), d_ein.dtype)], axis=0
    )
    dx = d_pad[flat_ids[:, 0]]
    for j in range(1, flat_ids.shape[1]):
        dx = dx + d_pad[flat_ids[:, j]]
    return dx, None, None


_dispatch_rows.defvjp(_dispatch_rows_fwd, _dispatch_rows_bwd)


@jax.custom_vjp
def _combine_rows(eo, gates, flat_ids, slot_tc):
    """Weighted gather-back: ``out[t] = sum_j gates[t, j] * eo[slot(t, j)]``.

    ``eo [S, H]`` expert outputs, ``gates [T, k]`` f32, ``slot_tc [S]`` the
    inverse map in CHOICE-MAJOR order (slot -> j*T + t; trash -> k*T) used
    only by the backward.
    """
    eo_pad = jnp.concatenate(
        [eo, jnp.zeros((1, eo.shape[1]), eo.dtype)], axis=0
    )
    out = None
    for j in range(flat_ids.shape[1]):
        contrib = eo_pad[flat_ids[:, j]] * gates[:, j:j + 1].astype(eo.dtype)
        out = contrib if out is None else out + contrib
    return out


def _combine_rows_fwd(eo, gates, flat_ids, slot_tc):
    return _combine_rows(eo, gates, flat_ids, slot_tc), (
        eo, gates, flat_ids, slot_tc
    )


def _combine_rows_bwd(res, dout):
    eo, gates, flat_ids, slot_tc = res
    T, k = flat_ids.shape
    H = eo.shape[1]
    # Pre-scale dout by each choice's gate and stack choice-major: row
    # j*T + t = dout[t] * gates[t, j]. One clean-layout buffer, one row
    # gather through the inverse map; the trailing zero row absorbs trash
    # slots (slot_tc = k*T).
    dout_scaled = jnp.concatenate(
        [dout * gates[:, j:j + 1].astype(dout.dtype) for j in range(k)]
        + [jnp.zeros((1, H), dout.dtype)],
        axis=0,
    )
    d_eo = dout_scaled[slot_tc]
    eo_pad = jnp.concatenate(
        [eo, jnp.zeros((1, H), eo.dtype)], axis=0
    )
    d_gates = jnp.stack(
        [jnp.sum((eo_pad[flat_ids[:, j]] * dout).astype(jnp.float32), axis=-1)
         for j in range(k)],
        axis=1,
    ).astype(gates.dtype)
    return d_eo, d_gates, None, None


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


class MoEMLP(nn.Module):
    """Top-k routed expert SwiGLU (replaces ``MLP`` when experts are on)."""

    config: GPTConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, deterministic: bool = True
    ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        E = cfg.num_experts
        k = cfg.moe_top_k
        b, s, H = x.shape
        T = b * s
        I = cfg.intermediate_size

        xt = x.reshape(T, H)

        # Router in f32 (standard for stability).
        router_logits = nn.Dense(
            E, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name="router",
        )(xt.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]
        gate_vals, gate_idx = jax.lax.top_k(probs, k)           # [T, k]
        assign_k = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)  # [T,k,E]

        # Gates: Switch keeps the raw router prob at k=1; at k>1 the chosen
        # probs renormalize to sum 1 (GShard/Mixtral semantics).
        gates = gate_vals if k == 1 else (
            gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)
        )

        # Aux load-balance loss uses pre-capacity FIRST-choice fractions.
        frac = jnp.mean(assign_k[:, 0], axis=0)                 # [E]
        mean_prob = jnp.mean(probs, axis=0)                     # [E]
        aux = cfg.moe_aux_weight * E * jnp.sum(frac * mean_prob)
        if cfg.router_z_weight > 0.0:
            z = jax.nn.logsumexp(router_logits, axis=-1)        # [T]
            aux = aux + cfg.router_z_weight * jnp.mean(z * z)

        dtype = cfg.compute_dtype
        entropy = -jnp.sum(mean_prob * jnp.log(mean_prob + 1e-9))

        def ffn_param(name, shape):
            return self.param(
                name, nn.initializers.normal(cfg.initializer_range), shape,
                cfg.params_dtype,
            ).astype(dtype)

        w_gate = ffn_param("experts_gate", (E, H, I))
        w_up = ffn_param("experts_up", (E, H, I))
        w_down = ffn_param("experts_down", (E, I, H))
        act = {"silu": nn.silu, "gelu": nn.gelu}[cfg.activation]

        if cfg.moe_impl == "dropless":
            out = self._dropless_ffn(
                xt, gate_idx, gates, entropy, w_gate, w_up, w_down, act,
            ).reshape(b, s, H)
            from tpu_trainer.models.gpt import _residual_dropout

            out = _residual_dropout(cfg, self, out, deterministic)
            return out, aux.astype(jnp.float32)

        if T <= 2 * E:
            # Tiny-token regime (single-token KV decode: T = batch): the
            # statistical capacity rule degenerates (C~1 would zero out any
            # token colliding on an expert). Give every token a slot.
            C = T
        else:
            C = max(1, math.ceil(k * T / E * cfg.expert_capacity_factor))

        # Position of each token-choice in its expert's queue, counted in
        # choice-major order (all first choices precede any second choice,
        # so capacity overflow drops second choices first); drop past C.
        assign_flat = assign_k.transpose(1, 0, 2).reshape(k * T, E)
        pos_flat = jnp.cumsum(assign_flat, axis=0) - assign_flat
        pos_k = pos_flat.reshape(k, T, E).transpose(1, 0, 2)    # [T, k, E]
        keep_k = (pos_k < C).astype(jnp.float32) * assign_k
        pos_idx = jnp.sum(pos_k * assign_k, axis=-1).astype(jnp.int32)

        kept = jnp.sum(keep_k, axis=-1) > 0                     # [T, k]
        if telemetry.capturing():
            # Router health (Switch-Transformer diagnostics), popped by the
            # enclosing TransformerBlock into its per-layer telemetry:
            # first-choice load fractions (sum to 1 by construction),
            # entropy of the mean routing distribution (log E when the
            # router is uniform, 0 when it collapses onto one expert), the
            # fraction of token-choices dropped at capacity, and the
            # heaviest expert's share of KEPT token-choices (collapse
            # shows up here before the drops do). ``dropless`` marks the
            # impl so the analyzer can gate drop_frac > 0 as a bug on
            # dropless runs but expected behavior here.
            kept_counts = jnp.sum(keep_k, axis=(0, 1))          # [E]
            telemetry.record("router", {
                "load": frac,
                "entropy": entropy,
                "drop_frac": 1.0 - jnp.mean(kept.astype(jnp.float32)),
                "max_group_frac": (jnp.max(kept_counts)
                                   / jnp.maximum(jnp.sum(kept_counts), 1.0)),
                "dropless": jnp.zeros((), jnp.float32),
            })
        mode = cfg.moe_dispatch
        if mode == "auto":
            # Trace-time mesh introspection: gathers are far cheaper on a
            # chip, but only the einsum form hands GSPMD a one-hot matmul
            # it can lower to the EP token all-to-all.
            from tpu_trainer.parallel import context as ctx_lib

            mesh = ctx_lib.current_mesh()
            ep = mesh.shape.get("expert", 1) if mesh is not None else 1
            mode = "einsum" if ep > 1 else "gather"
        if mode == "gather":
            # Gather dispatch (round 4, re-formulated round 5): the one-hot
            # dispatch/combine einsums cost 2*T*E*C*H FLOPs EACH — at
            # E=8/capacity 1.25 that is ~129 GF per einsum per layer vs
            # ~145 GF for all three expert FFN einsums combined, and the
            # [T, k, E, C] slot tensor is a ~335 MB f32 buffer. Measured
            # on v5e (xplane): dispatch/combine = 47.4 ms of a 156 ms
            # step (30%). Round 4 replaced them with gathers whose AD
            # transposes were scatter-adds (still 14.1 ms of the 179 ms
            # top-2 step); round 5's custom_vjp pair above re-expresses
            # those transposes as gathers through the inverse slot map.
            # Dropped token-choices route to a trailing trash slot that
            # reads as a zero row — identical semantics to the einsum
            # path (pinned by tests/test_moe.py oracles either way).
            flat_ids = jnp.where(kept, gate_idx * C + pos_idx, E * C)
            # One scatter builds the inverse map: slot -> flat token-choice
            # in choice-major order (j*T + t, trash -> k*T; see the
            # custom_vjp comment for why choice-major).
            tc_vals = (jnp.arange(T, dtype=jnp.int32)[:, None]
                       + T * jnp.arange(k, dtype=jnp.int32)[None, :])
            slot_tc = jnp.full((E * C + 1,), k * T, jnp.int32)
            slot_tc = slot_tc.at[flat_ids.reshape(-1)].set(
                tc_vals.reshape(-1)
            )[:E * C]
            slot_token = jnp.where(slot_tc == k * T, T, slot_tc % T)
            expert_in = _dispatch_rows(
                xt.astype(dtype), slot_token, flat_ids
            ).reshape(E, C, H)
        else:
            # One-hot einsum dispatch (rounds 2-3): the routing rides the
            # MXU, and under expert parallelism GSPMD lowers the einsums
            # to a clean token all-to-all — which is why "auto" selects
            # this form whenever the mesh has a non-trivial expert axis.
            slot = (keep_k[..., None]
                    * jax.nn.one_hot(pos_idx, C,
                                     dtype=jnp.float32)[:, :, None, :])
            dispatch = jnp.sum(slot, axis=1)                    # [T, E, C]
            expert_in = jnp.einsum(
                "tec,th->ech", dispatch.astype(dtype), xt.astype(dtype)
            )  # [E, C, H]

        hmid = jnp.einsum("ech,ehi->eci", expert_in, w_gate)
        hmid = act(hmid) * jnp.einsum("ech,ehi->eci", expert_in, w_up)
        expert_out = jnp.einsum("eci,eih->ech", hmid, w_down)   # [E, C, H]

        if mode == "gather":
            out = _combine_rows(
                expert_out.reshape(E * C, H), gates, flat_ids, slot_tc
            ).reshape(b, s, H)
        else:
            combine = jnp.sum(
                slot * gates[:, :, None, None], axis=1
            )                                                   # [T, E, C]
            out = jnp.einsum(
                "tec,ech->th", combine.astype(dtype), expert_out
            ).reshape(b, s, H)
        from tpu_trainer.models.gpt import _residual_dropout

        out = _residual_dropout(cfg, self, out, deterministic)
        return out, aux.astype(jnp.float32)

    def _dropless_ffn(self, xt, gate_idx, gates, entropy,
                      w_gate, w_up, w_down, act):
        """Token-dropless expert FFN over grouped matmuls.

        One stable argsort of the ``T*k`` token-choice rows by expert id
        builds the grouped layout (stability makes the permutation a pure
        function of the routing — exact-resume replays it bit-identically);
        ``bincount`` gives the true per-expert group sizes. Each SwiGLU
        projection is one ``gmm`` whose compute is exactly
        ``sum(counts) = k*T`` rows — no capacity padding, no drops. The
        inverse permutation is a second argsort (of the first), and the
        gates weight the per-choice rows back into token order.

        Mesh composition: on a multi-device mesh the jnp twin runs
        (``use_kernel=False``) so GSPMD partitions the ragged dot like any
        other op; the Pallas kernel drives the single-device TPU path. A
        shard_mapped gmm with an explicit EP all-to-all is the planned
        follow-up (ROADMAP item 4).
        """
        cfg = self.config
        E = cfg.num_experts
        k = cfg.moe_top_k
        T = xt.shape[0]
        dtype = cfg.compute_dtype

        flat_expert = gate_idx.astype(jnp.int32).reshape(-1)    # [T*k]
        counts = jnp.bincount(flat_expert, length=E)            # [E]
        perm = jnp.argsort(flat_expert)                         # stable
        inv_perm = jnp.argsort(perm)

        from tpu_trainer.parallel import context as ctx_lib

        mesh = ctx_lib.current_mesh()
        use_kernel = False if (mesh is not None and mesh.size > 1) else None

        def grouped(lhs, w):
            return gmm(lhs, w, counts, use_kernel=use_kernel)

        grouped_in = xt.astype(dtype)[perm // k]                # [T*k, H]
        mid = act(grouped(grouped_in, w_gate)) * grouped(grouped_in, w_up)
        grouped_out = grouped(mid, w_down)                      # [T*k, H]
        rows = grouped_out[inv_perm].reshape(T, k, -1)
        out = jnp.sum(rows * gates[..., None].astype(dtype), axis=1)

        if telemetry.capturing():
            # True post-routing load (the bincount — what each expert
            # actually computed), not pre-capacity first-choice fractions;
            # max_group_frac is the collapse indicator (1/E when balanced,
            # -> 1.0 as the router collapses onto one expert). drop_frac
            # is structurally zero — the analyzer FAILs a dropless run
            # that ever reports otherwise.
            load = counts.astype(jnp.float32) / float(k * T)
            telemetry.record("router", {
                "load": load,
                "entropy": entropy,
                "drop_frac": jnp.zeros((), jnp.float32),
                "max_group_frac": jnp.max(load),
                "dropless": jnp.ones((), jnp.float32),
            })
        return out
