"""Mixture-of-Experts feed-forward with expert parallelism.

Beyond-reference capability (the reference has a single dense model family;
SURVEY.md §2 lists EP as absent): a Switch-Transformer-style top-1 routed
MoE that drops into the TransformerBlock in place of the dense SwiGLU when
``GPTConfig.num_experts > 0``.

TPU-native shape: experts are *stacked* (``[E, ...]`` parameter leaves, like
the layer stack) and the whole layer is einsums — dispatch/combine are
one-hot matmuls, so the MXU does the routing and GSPMD does the expert
parallelism: sharding the expert leaves over the ``expert`` mesh axis makes
XLA emit the all-to-all between data-sharded tokens and expert-sharded FFNs
automatically. No collective appears in this file.

Mechanics (Switch Transformer, arXiv:2101.03961):

- router: ``logits [T, E]`` in f32; top-1 expert per token.
- capacity ``C = ceil(T/E * capacity_factor)``; per-expert positions come
  from a cumsum over the one-hot assignment; tokens beyond capacity are
  dropped (contribute zero, like the paper).
- combine weight = router probability of the chosen expert.
- aux load-balance loss ``E * sum_e f_e * p_e`` (fraction of tokens routed
  to e times mean router prob of e), returned for the model to add with
  ``moe_aux_weight``.
"""

from __future__ import annotations

import math
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_trainer.models.config import GPTConfig


class MoEMLP(nn.Module):
    """Top-1 routed expert SwiGLU (replaces ``MLP`` when experts are on)."""

    config: GPTConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, deterministic: bool = True
    ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        E = cfg.num_experts
        b, s, H = x.shape
        T = b * s
        I = cfg.intermediate_size
        if T <= 2 * E:
            # Tiny-token regime (single-token KV decode: T = batch): the
            # statistical capacity rule degenerates (C~1 would zero out any
            # token colliding on an expert). Give every token a slot.
            C = T
        else:
            C = max(1, math.ceil(T / E * cfg.expert_capacity_factor))

        xt = x.reshape(T, H)

        # Router in f32 (standard for stability).
        router_logits = nn.Dense(
            E, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name="router",
        )(xt.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]
        expert_idx = jnp.argmax(probs, axis=-1)                 # [T]
        assign = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)

        # Aux load-balance loss uses pre-capacity assignment fractions.
        frac = jnp.mean(assign, axis=0)                         # [E]
        mean_prob = jnp.mean(probs, axis=0)                     # [E]
        aux = E * jnp.sum(frac * mean_prob)

        # Position of each token within its expert's queue; drop past C.
        pos = jnp.cumsum(assign, axis=0) - assign               # [T, E]
        keep = (pos < C).astype(jnp.float32) * assign
        gate = jnp.sum(probs * keep, axis=-1)                   # [T]
        pos_idx = jnp.sum(pos * assign, axis=-1).astype(jnp.int32)

        # dispatch [T, E, C]: 1 at (t, expert(t), pos(t)) for kept tokens.
        dispatch = (
            keep[:, :, None] * jax.nn.one_hot(pos_idx, C, dtype=jnp.float32)[:, None, :]
        )

        dtype = cfg.compute_dtype
        expert_in = jnp.einsum(
            "tec,th->ech", dispatch.astype(dtype), xt.astype(dtype)
        )  # [E, C, H]

        def ffn_param(name, shape):
            return self.param(
                name, nn.initializers.normal(cfg.initializer_range), shape,
                cfg.params_dtype,
            ).astype(dtype)

        w_gate = ffn_param("experts_gate", (E, H, I))
        w_up = ffn_param("experts_up", (E, H, I))
        w_down = ffn_param("experts_down", (E, I, H))

        hmid = jnp.einsum("ech,ehi->eci", expert_in, w_gate)
        act = {"silu": nn.silu, "gelu": nn.gelu}[cfg.activation]
        hmid = act(hmid) * jnp.einsum("ech,ehi->eci", expert_in, w_up)
        expert_out = jnp.einsum("eci,eih->ech", hmid, w_down)   # [E, C, H]

        combine = dispatch * gate[:, None, None]                # [T, E, C]
        out = jnp.einsum(
            "tec,ech->th", combine.astype(dtype), expert_out
        ).reshape(b, s, H)
        from tpu_trainer.models.gpt import _residual_dropout

        out = _residual_dropout(cfg, self, out, deterministic)
        return out, aux.astype(jnp.float32)
