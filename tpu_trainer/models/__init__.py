from tpu_trainer.models.config import GPTConfig, dtype_of
from tpu_trainer.models.gpt import (
    GPT,
    MLP,
    CausalSelfAttention,
    RMSNorm,
    TransformerBlock,
    apply_rotary_pos_emb,
    count_parameters,
    generate,
    generate_bucketed,
    generate_kv,
    rope_tables,
    rotate_half,
)

__all__ = [
    "GPTConfig",
    "dtype_of",
    "GPT",
    "MLP",
    "CausalSelfAttention",
    "RMSNorm",
    "TransformerBlock",
    "apply_rotary_pos_emb",
    "count_parameters",
    "generate",
    "generate_bucketed",
    "generate_kv",
    "rope_tables",
    "rotate_half",
]
