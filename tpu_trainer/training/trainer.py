"""The trainer: one jitted train_step for every parallelism strategy.

TPU-native re-design of the reference's two trainer classes
(``DistributedTrainer``, ``ddp_trainer.py:66-456``; ``FSDPTrainer``,
``fsdp_trainer.py:53-505``). The load-bearing property of the reference —
*the model is parallelism-blind; the runtime layer decides placement*
(SURVEY.md §1) — becomes literal here: DDP and FSDP are the **same**
``train_step``, differing only in the NamedShardings handed to ``jax.jit``.

Key mappings (SURVEY.md C9/C10/C15/C16):

- DDP's ``no_sync`` + final-micro-step all-reduce (``ddp_trainer.py:329-342``)
  → ``lax.scan`` over micro-batches accumulating local grads, one reduction
  at the end (the no_sync equivalent is free — XLA reduces once, after the
  scan, because that's where the grads are first consumed).
- FSDP's per-module all-gather / reduce-scatter (``fsdp_trainer.py:369-384``)
  → GSPMD-inserted collectives from the param/grad shardings; overlap comes
  from XLA's latency-hiding scheduler (↔ ``backward_prefetch``).
- ``clip_grad_norm_`` (``ddp_trainer.py:347-350``, ``fsdp_trainer.py:386-388``)
  → ``optax.clip_by_global_norm`` inside the chain (global norm over sharded
  trees = partial norms + psum, emitted automatically).
- fp16 ``GradScaler`` (``ddp_trainer.py:152``) → dynamic loss scaling done
  functionally in-step (scale up on a run of finite steps, halve + skip the
  update on overflow). bf16 needs none of this (TPU-native recipe:
  fp32 params, bf16 compute).
- LR is applied per-step as a pure function of ``state.step`` inside the
  optimizer — fixing the reference's set-after-step off-by-one (§2.1 b1).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Optional, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.models.gpt import GPT
from tpu_trainer.ops import ring
from tpu_trainer.parallel import context as ctx_lib
from tpu_trainer.parallel import mesh as mesh_lib
from tpu_trainer.parallel import sharding as shard_lib
from tpu_trainer.training.config import TrainingConfig
from tpu_trainer.training.optimizer import make_optimizer
from tpu_trainer.utils import telemetry

_MP_TO_DTYPE = {"fp32": "float32", "bf16": "bfloat16", "fp16": "float16"}


@jax.custom_vjp
def _linked_cast(master, casted):
    """Use a precomputed compute-dtype param copy, gradients to the master.

    Forward returns ``casted`` (== the compute-dtype cast of ``master``,
    produced inside the PREVIOUS step's optimizer-update fusion — see
    ``TrainState.params_c``); the backward converts the cotangents to the
    master's f32, which is exactly the transpose of the cast this replaces,
    so XLA fuses it into the dW producers the same way it fused the
    original cast-transpose. Numerics are identical to casting ``master``
    in-place.
    """
    return casted


def _linked_cast_fwd(master, casted):
    return casted, None


def _linked_cast_bwd(_, g):
    # master's cotangent: the cast-transpose (convert to f32). casted is a
    # derived constant at every call site; its zero cotangent is dead code
    # the compiler drops.
    return (
        jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), g),
        jax.tree_util.tree_map(jnp.zeros_like, g),
    )


_linked_cast.defvjp(_linked_cast_fwd, _linked_cast_bwd)


# Blockwise int8 quantization now lives in utils/quant.py (shared with the
# on-device quantized Adam state); re-exported here for its established
# import path (tests/test_offload.py, validate.py).
from tpu_trainer.utils.quant import (  # noqa: E402,F401
    QuantPack,
    dequantize_blockwise_int8,
    quantize_blockwise_int8,
)


def _split_packed(batch: jax.Array):
    """``[rows, seq]`` → ``(tokens, None)``; packed ``[rows, seq, 2]`` →
    ``(tokens, segment_ids)``. The channel-last convention: ``[..., 0]`` is
    token ids, ``[..., 1]`` is segment ids (0 = padding, docs 1..K)."""
    if batch.ndim >= 3 and batch.shape[-1] == 2:
        return batch[..., 0], batch[..., 1]
    return batch, None


def _path_keys(path) -> tuple:
    """Pytree path -> hashable tuple of key strings."""
    return tuple(
        str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", ""))))
        for p in path
    )


def select_resident_moments(opt_shapes, budget_bytes: int,
                            shard_count: int = 1):
    """Partial-offload selection: which optimizer-state leaves stay on
    device under a byte budget (VERDICT r4 #3).

    Greedy largest-first over the float ndim>=1 leaves (the stream is
    volume-bound, so the biggest leaves buy the most link traffic per
    selection; Adam's mu/nu for one param are equal-sized and selected
    together or not at all only by budget coincidence — fine, each leaf
    streams independently). Scalars never stream anyway. Returns
    ``(frozenset of path-key tuples, bytes kept)``.

    ``shard_count`` is the fsdp axis size under zero2/zero3, where the
    moments are fsdp-sharded: a kept leaf then costs ``size /
    shard_count`` bytes of *per-device* HBM, which is what the
    ``--opt_resident_gb`` budget and the startup line describe. Leaves no
    dim of which divides the axis stay replicated and cost full size
    (same shape-only rule as ``shard_lib.fsdp_spec``; a leaf that is
    *additionally* tensor-sharded is counted conservatively at its
    fsdp-only shard size).
    """
    cands = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(opt_shapes)[0]:
        if (getattr(leaf, "ndim", 0) >= 1
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            size = leaf.size * jnp.dtype(leaf.dtype).itemsize
            if (shard_count > 1 and shard_lib.FSDP_AXIS
                    in tuple(shard_lib.fsdp_spec(leaf.shape, shard_count))):
                size = -(-size // shard_count)  # ceil: per-device bytes
            cands.append((_path_keys(path), size))
    cands.sort(key=lambda kv: (-kv[1], kv[0]))
    keep, used = set(), 0
    for pk, sz in cands:
        if used + sz <= budget_bytes:
            keep.add(pk)
            used += sz
    return frozenset(keep), used

_SCALE_GROWTH_INTERVAL = 2000  # steps of finite grads before doubling
_MAX_LOSS_SCALE = 2.0**16
_INIT_LOSS_SCALE = 2.0**15


class TrainState(struct.PyTreeNode):
    """Everything that evolves across steps (the checkpointable unit —
    reference checkpoint dict, ``ddp_trainer.py:408-415``)."""

    step: jax.Array            # int32 scalar
    params: Any
    opt_state: Any
    rng: jax.Array             # dropout PRNG key chain
    loss_scale: jax.Array      # float32 scalar (fp16 dynamic scaling; 1.0 else)
    good_steps: jax.Array      # int32: consecutive finite-grad steps (fp16)
    # Compute-dtype copy of the >=2-D params (None when inactive). The
    # f32->bf16 cast of the full parameter tree used to run as separate
    # convert passes at the top of every step (~1.7 ms at headline
    # geometry: the cast lives in the NEXT step's executable, so XLA
    # cannot fuse it into the optimizer-update fusions that produced the
    # params). Carrying the cast in the state moves it into the update
    # fusion's epilogue. Derived data: excluded from checkpoints
    # (utils/checkpoint.py strips it on save and rebuilds on restore), so
    # the checkpoint format is unchanged and pre-round-4 checkpoints
    # restore cleanly.
    params_c: Any = None


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """How to parallelize: mesh shape + ZeRO mode.

    - DDP (reference ddp_trainer): ``MeshConfig(data=-1)`` + ``"replicated"``.
    - FSDP (reference fsdp_trainer): ``MeshConfig(fsdp=-1)`` + one of
      ``zero3`` (FULL_SHARD) / ``zero2`` (SHARD_GRAD_OP) /
      ``replicated`` (NO_SHARD); reference spellings accepted.
    - HYBRID_SHARD: both axes > 1.
    - ``cpu_offload`` (reference ``FSDPConfig.cpu_offload``,
      ``fsdp_trainer.py:62-63,299-301``): optimizer state lives in host
      memory (``pinned_host``) and is streamed to the device inside the
      jitted step only for the update — the TPU analogue of torch FSDP's
      ``CPUOffload``, trading step time for 2x param-bytes of HBM.
    - ``offload_dtype``: storage dtype for the host-resident optimizer
      state. The offloaded step is host-link *volume* bound (measured:
      the f32 Adam m/v round trip, 16 bytes/param/step, runs at ~7 GB/s
      effective through this host link — over a second per step at 1B
      params — while the update compute is ~0.1 s; overlap alone cannot
      help when the stream is 10x the compute). ``"bfloat16"`` halves the
      stream: m/v are cast once after each update and reconstructed to
      f32 on device before the next (one rounding per step — the same
      tradeoff as 8-bit optimizer states, milder). ``"int8"`` quarters
      it: ndim>=2 moment leaves quantize to blockwise-absmax int8 along
      their last dim (block 256; ~0.4% relative error per block), with
      Adam's nonnegative second moment quantized in sqrt-space — v only
      enters the update through sqrt(v), so the 8 bits cover half the
      log-range (the bitsandbytes dynamic-quantization motivation).
      Default f32 keeps the offloaded step bitwise-identical to the
      on-device one.
    - ``offload_budget_gb`` (round 5, VERDICT r4 #3 — partial offload):
      GB of optimizer-moment leaves allowed to REMAIN device-resident,
      largest-first; only the overflow streams over the host link. The
      stream is volume-bound, so every resident GB is ~2 GB/step less
      link traffic at f32 (read + write) — resident leaves skip the
      storage transform entirely and keep the bitwise-f32 contract.
      0 = classic full offload.
    """

    mesh: mesh_lib.MeshConfig = mesh_lib.MeshConfig()
    sharding_strategy: str = "replicated"
    cpu_offload: bool = False
    offload_dtype: str = "float32"
    offload_budget_gb: float = 0.0


class Trainer:
    """Owns the mesh, the jitted step, and state initialization.

    Public interface mirrors the reference trainer (SURVEY.md §1 L4):
    ``init_state()``, ``train_step(state, batch) -> (state, metrics)``,
    ``put_batch``, plus ``process_index/process_count`` for rank discovery.
    """

    def __init__(
        self,
        model_config: GPTConfig,
        training_config: TrainingConfig = TrainingConfig(),
        parallel_config: ParallelConfig = ParallelConfig(),
        mesh: Optional[Mesh] = None,
    ):
        # Mixed-precision policy → model compute dtype (reference
        # ddp_trainer.py:115-156 autocast selection).
        dtype = _MP_TO_DTYPE[training_config.mixed_precision]
        self.model_config = dataclasses.replace(model_config, dtype=dtype)
        self.training_config = training_config
        self.parallel_config = parallel_config
        self.strategy = shard_lib.canonical_strategy(parallel_config.sharding_strategy)
        self.use_loss_scaling = training_config.mixed_precision == "fp16"

        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(parallel_config.mesh)
        self.sp_size = self.mesh.shape[mesh_lib.SEQUENCE_AXIS]
        if self.sp_size > 1 and training_config.max_seq_len % self.sp_size != 0:
            raise ValueError(
                f"max_seq_len {training_config.max_seq_len} not divisible by "
                f"sequence axis size {self.sp_size}"
            )
        # Data feeding works on ANY mesh/host layout: each host's feed rank
        # is derived from which global batch rows its devices address
        # (mesh_lib.host_feed_info). Hosts under a sequence/tensor axis that
        # spans hosts share a feed rank and load identical rows; hosts under
        # data/fsdp axes get disjoint ranks — the round-2
        # dp_size-must-partition-hosts restriction is gone.
        self.ep_size = self.mesh.shape.get(mesh_lib.EXPERT_AXIS, 1)
        if self.ep_size > 1:
            if self.model_config.num_experts <= 0:
                raise ValueError(
                    "expert mesh axis > 1 requires a MoE model "
                    "(GPTConfig.num_experts > 0)"
                )
            if self.model_config.num_experts % self.ep_size != 0:
                raise ValueError(
                    f"num_experts {self.model_config.num_experts} not "
                    f"divisible by expert axis size {self.ep_size}"
                )
        self.tp_size = self.mesh.shape[mesh_lib.TENSOR_AXIS]
        if self.tp_size > 1:
            if self.model_config.num_heads % self.tp_size != 0:
                raise ValueError(
                    f"num_heads {self.model_config.num_heads} not divisible "
                    f"by tensor axis size {self.tp_size}"
                )
            if self.model_config.kv_heads % self.tp_size != 0:
                raise ValueError(
                    f"num_kv_heads {self.model_config.kv_heads} not "
                    f"divisible by tensor axis size {self.tp_size} (each "
                    f"tensor shard must own whole K/V-head groups)"
                )
            # TP shards the q/k/v (and gate/up) kernels along their output
            # dim — the axis fused_projections concatenates. Fusing there
            # would force GSPMD to gather the shards; keep the narrow
            # per-projection matmuls, which shard cleanly.
            if self.model_config.fused_projections:
                self.model_config = dataclasses.replace(
                    self.model_config, fused_projections=False
                )
        # jax-0.4.37 workaround (ROADMAP open item): composed sequence x
        # tensor meshes NaN inside the blockwise fused head+CE even though
        # every activation (including the full-vocab logits) is finite.
        # Fall back to the unfused head + CE there; numerics are identical,
        # only the logits materialization differs.
        from tpu_trainer.utils import jax_compat

        if (self.sp_size > 1 and self.tp_size > 1
                and self.model_config.fused_loss
                and not jax_compat.FUSED_LOSS_SEQ_TP_OK):
            import warnings

            warnings.warn(
                "sequence x tensor mesh on old jax: disabling fused_loss "
                "(known fused head+CE NaN on this API generation; see "
                "ROADMAP open items)",
                stacklevel=2,
            )
            self.model_config = dataclasses.replace(
                self.model_config, fused_loss=False
            )
        self.stage_size = self.mesh.shape.get(mesh_lib.STAGE_AXIS, 1)
        if self.stage_size > 1:
            # Pipeline parallelism (parallel/pipeline.py): contiguous layer
            # blocks per stage, GPipe microbatches within each step.
            if self.model_config.num_layers % self.stage_size != 0:
                raise ValueError(
                    f"num_layers {self.model_config.num_layers} not divisible "
                    f"by stage axis size {self.stage_size}"
                )
            # SP x PP composes for BOTH schedules: the pipeline's shard_map
            # goes jointly manual over {stage, sequence} and the ring runs
            # unrolled inside it (models/gpt.py pipeline branch /
            # pipeline_1f1b_value_and_grad, ring.ring_attention_manual) —
            # the round-2 guard against Shardy's nested manual-region
            # binding and round-3's 1f1b-specific guards are gone. MoE
            # rides either schedule (the aux loss is threaded through the
            # manual backward under 1f1b).
            microbatches = (self.model_config.pipeline_microbatches
                            or self.stage_size)
            if self.model_config.pipeline_schedule == "interleaved":
                vst = self.model_config.pipeline_virtual_stages
                if self.model_config.num_layers % (self.stage_size * vst):
                    raise ValueError(
                        f"num_layers {self.model_config.num_layers} not "
                        f"divisible by stages*virtual "
                        f"({self.stage_size}*{vst})"
                    )
                if microbatches % self.stage_size:
                    raise ValueError(
                        f"interleaved schedule needs pipeline_microbatches "
                        f"({microbatches}) divisible by the stage count "
                        f"({self.stage_size})"
                    )
            global_rows = (training_config.batch_size
                           * mesh_lib.dp_size(self.mesh))
            if global_rows % microbatches != 0:
                raise ValueError(
                    f"global batch {global_rows} rows (batch_size "
                    f"{training_config.batch_size} x {mesh_lib.dp_size(self.mesh)} "
                    f"data shards) not divisible by pipeline_microbatches "
                    f"{microbatches}"
                )
        self.model = GPT(self.model_config)
        self.optimizer = make_optimizer(training_config)

        # Carry the compute-dtype param copy in the state (see
        # TrainState.params_c / TrainingConfig.carry_cast_params): only
        # meaningful when compute and param dtypes differ, and skipped
        # under cpu_offload — those configs run at the HBM edge and the
        # extra copy is the marginal GB while the stream dwarfs the cast.
        self._carry_cast = (
            training_config.carry_cast_params
            and self.model_config.compute_dtype
            != self.model_config.params_dtype
            and not parallel_config.cpu_offload
            # The pipeline's manual schedules take the f32 master and
            # manage their own stage-local casts; keep their param flow
            # unchanged.
            and self.stage_size == 1
        )

        # cpu_offload viability + host storage dtype must be known before
        # state shapes are traced (_make_state casts the stored state).
        self.cpu_offload = parallel_config.cpu_offload
        if (self.cpu_offload
                and training_config.optimizer_state_dtype != "float32"):
            raise ValueError(
                "cpu_offload streams the optimizer state from host storage "
                "(--offload_dtype controls its width there); combine it "
                "with optimizer_state_dtype=float32 — the on-device "
                "quantized state targets HBM traffic, which offloaded "
                "state does not generate"
            )
        if self.cpu_offload:
            kinds = {
                m.kind for d in self.mesh.devices.flat
                for m in d.addressable_memories()
            }
            platform = next(iter(self.mesh.devices.flat)).platform
            multi = self.mesh.size > 1
            if "pinned_host" not in kinds or (platform == "cpu" and multi):
                import warnings

                warnings.warn(
                    "cpu_offload requested but this backend cannot host-"
                    "offload here (no pinned_host memory space, or the CPU "
                    "SPMD partitioner's UNIMPLEMENTED multi-device "
                    "placement); keeping optimizer state on device",
                    stacklevel=2,
                )
                self.cpu_offload = False
        # Host-side storage for offloaded optimizer state: "bfloat16" halves
        # the host-link stream; "int8" quarters it via blockwise-absmax
        # quantization (mu symmetric, nu in sqrt-space) — see
        # ParallelConfig docstring.
        self._offload_quant = (
            self.cpu_offload and parallel_config.offload_dtype == "int8"
        )
        self._offload_cast = (
            jnp.dtype(parallel_config.offload_dtype)
            if self.cpu_offload and not self._offload_quant
            and parallel_config.offload_dtype != "float32" else None
        )
        # Partial offload (offload_budget_gb): leaves in _offload_keep stay
        # device-resident in exact f32. Selection needs the optimizer-state
        # shapes BEFORE _make_state is traced (its _offload_store consults
        # the keep set), hence this separate abstract trace.
        self._offload_keep = frozenset()
        self.offload_resident_bytes = 0  # surfaced in the CLI startup line
        if self.cpu_offload and parallel_config.offload_budget_gb > 0:
            p_shapes = jax.eval_shape(
                lambda rng: self.model.init(
                    rng, jnp.zeros((1, 8), jnp.int32))["params"],
                jax.random.PRNGKey(0),
            )
            opt_shapes = jax.eval_shape(self.optimizer.init, p_shapes)
            # Under zero2/zero3 the moments are fsdp-sharded: budget the
            # PER-DEVICE shard bytes, not the global leaf bytes, so
            # --opt_resident_gb and the startup line match actual HBM.
            fsdp_shards = (
                self.mesh.shape[shard_lib.FSDP_AXIS]
                if self.strategy in ("zero2", "zero3") else 1
            )
            self._offload_keep, self.offload_resident_bytes = (
                select_resident_moments(
                    opt_shapes,
                    int(parallel_config.offload_budget_gb * 2**30),
                    shard_count=fsdp_shards,
                )
            )

        # --- shardings, from shapes only (no allocation) -------------------
        state_shapes = jax.eval_shape(self._make_state, jax.random.PRNGKey(0))
        # Compute-side dtypes of the optimizer state (pre-storage-cast), for
        # reconstructing f32 state on device each step.
        self._opt_compute_dtypes = jax.tree_util.tree_map(
            lambda s: s.dtype,
            jax.eval_shape(self.optimizer.init, state_shapes.params),
        )
        replicated = P()
        param_specs = shard_lib.params_specs(
            state_shapes.params, self.mesh, self.strategy
        )
        self._state_specs = TrainState(
            step=replicated,
            params=param_specs,
            opt_state=shard_lib.opt_state_specs(
                state_shapes.opt_state, self.mesh, self.strategy
            ),
            rng=replicated,
            loss_scale=replicated,
            good_steps=replicated,
            # params_c mirrors the params' placement leaf for leaf (same
            # tree, same shapes, compute dtype).
            params_c=param_specs if self._carry_cast else None,
        )
        self.state_shardings = shard_lib.to_shardings(self._state_specs, self.mesh)
        self._grad_shardings = shard_lib.to_shardings(
            shard_lib.grads_specs(state_shapes.params, self.mesh, self.strategy),
            self.mesh,
        )
        self.batch_sharding = mesh_lib.batch_sharding(self.mesh)

        if self.cpu_offload:
            # Optimizer state is host-resident; the step streams it through
            # the device around the update (jax.device_put inside jit).
            # Scalar leaves (Adam's step count) stay on device — the SPMD
            # partitioner rejects placement annotations on scalars, and
            # they're bytes anyway.
            self._opt_device_shardings = self.state_shardings.opt_state
            # Partial offload: leaves in _offload_keep keep their device
            # sharding (their pre-pack paths survive because kept leaves
            # skip the storage transform, so pack-extended paths — 'q'/
            # 'scale' — are never in the keep set).
            self._opt_host_shardings = jax.tree_util.tree_map_with_path(
                lambda path, ns, shape: (
                    NamedSharding(self.mesh, ns.spec, memory_kind="pinned_host")
                    if getattr(shape, "ndim", 0) >= 1
                    and _path_keys(path) not in self._offload_keep else ns
                ),
                self.state_shardings.opt_state,
                state_shapes.opt_state,
            )
            self.state_shardings = self.state_shardings.replace(
                opt_state=self._opt_host_shardings
            )

        self._init_jit = jax.jit(self._make_state, out_shardings=self.state_shardings)
        self._step_jit = jax.jit(
            self._train_step,
            donate_argnums=(0,),
            in_shardings=(self.state_shardings, self.batch_sharding),
            out_shardings=(self.state_shardings, None),
        )
        # Telemetry step: a SECOND executable of the same math with the
        # per-layer stats as extra outputs (utils/telemetry). The training
        # loop calls it every --telemetry_interval steps; the steady-state
        # step above keeps its original graph and pays nothing. jax.jit is
        # lazy, so runs that never ask for telemetry never compile this.
        self._step_tel_jit = jax.jit(
            functools.partial(self._train_step, telemetry_on=True),
            donate_argnums=(0,),
            in_shardings=(self.state_shardings, self.batch_sharding),
            out_shardings=(self.state_shardings, None),
        )
        eval_batch_sharding = NamedSharding(
            self.mesh, mesh_lib.batch_spec_2d()
        )
        self._eval_jit = jax.jit(
            self._eval_step,
            in_shardings=(self.state_shardings, eval_batch_sharding),
            out_shardings=None,
        )
        self._eval_batch_sharding = eval_batch_sharding

    # --- rank discovery (↔ reference rank/world_size, ddp_trainer.py:101-103)
    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def process_count(self) -> int:
        return jax.process_count()

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def dp_size(self) -> int:
        return mesh_lib.dp_size(self.mesh)

    @functools.cached_property
    def _feed_info(self):
        """(feed_rank, feed_world) for this host's data loading — see
        mesh_lib.host_feed_info. Computed from the actual batch sharding, so
        sequence/tensor axes spanning hosts get replicated-row feeding."""
        c = self.training_config
        shape = (c.gradient_accumulation_steps,
                 c.batch_size * self.dp_size, c.max_seq_len)
        return mesh_lib.host_feed_info(self.batch_sharding, shape, row_dim=1)

    @property
    def data_feed_rank(self) -> int:
        return self._feed_info[0]

    @property
    def data_feed_world(self) -> int:
        return self._feed_info[1]

    @property
    def global_batch_size(self) -> int:
        """Sequences consumed per optimizer step, across all devices."""
        c = self.training_config
        return c.batch_size * c.gradient_accumulation_steps * self.dp_size

    @property
    def feed_signature(self) -> dict:
        """The quantities a persisted loader cursor's units depend on.
        Stamped into every checkpoint's ``data_state`` so an elastic
        restart on a differently-factored mesh can remap the cursor
        (``utils/checkpoint.remap_data_state``) instead of replaying the
        dataset from the start."""
        return {
            "global_batch_size": self.global_batch_size,
            "feed_world": self.data_feed_world,
        }

    @property
    def tokens_per_step(self) -> int:
        return self.global_batch_size * self.training_config.max_seq_len

    # --- state ------------------------------------------------------------

    @staticmethod
    def _is_packed(x) -> bool:
        # Type check, not a dict-key heuristic: QuantPack is a registered
        # pytree node, so a params subtree using the same keys can never
        # be misread as a quantized moment.
        return isinstance(x, QuantPack)

    @staticmethod
    def _path_nonneg(path) -> bool:
        """Adam's second moment (``nu`` in optax's ScaleByAdamState) is
        nonnegative and only consumed through sqrt — quantize it in
        sqrt-space."""
        return any(
            str(getattr(p, "name", getattr(p, "key", ""))) == "nu"
            for p in path
        )

    def _offload_store(self, opt_state):
        """Compute-dtype optimizer state -> host storage form (no-op unless
        ``offload_dtype`` narrows it; "int8" packs ndim>=2 float leaves
        into blockwise {q, scale}). Device-resident leaves under a partial
        offload budget (``self._offload_keep``) skip the transform — they
        never cross the link, so they stay exact f32."""
        keep = self._offload_keep
        if self._offload_quant:
            return jax.tree_util.tree_map_with_path(
                lambda path, x: x if self._is_packed(x)
                or _path_keys(path) in keep
                else quantize_blockwise_int8(
                    x, nonneg=self._path_nonneg(path))
                if getattr(x, "ndim", 0) >= 2
                and jnp.issubdtype(x.dtype, jnp.floating) else x,
                opt_state,
                is_leaf=self._is_packed,
            )
        if self._offload_cast is None:
            return opt_state
        return jax.tree_util.tree_map_with_path(
            lambda path, x: x.astype(self._offload_cast)
            if getattr(x, "ndim", 0) >= 1
            and jnp.issubdtype(x.dtype, jnp.floating)
            and _path_keys(path) not in keep else x,
            opt_state,
        )

    def _offload_load(self, opt_state):
        """Host storage form -> the optimizer's compute dtypes (on device,
        after the h2d stream — the dequant/cast costs HBM ops, the narrow
        storage saved host-link bytes)."""
        if self._offload_quant:
            return jax.tree_util.tree_map_with_path(
                lambda path, x, dt: dequantize_blockwise_int8(
                    x,
                    x["q"].shape[:-2]
                    + (x["q"].shape[-2] * x["q"].shape[-1],),
                    dt,
                    nonneg=self._path_nonneg(path),
                ) if self._is_packed(x) else x,
                opt_state, self._opt_compute_dtypes,
                is_leaf=self._is_packed,
            )
        if self._offload_cast is None:
            return opt_state
        return jax.tree_util.tree_map(
            lambda x, dt: x.astype(dt) if getattr(x, "ndim", 0) >= 1 else x,
            opt_state, self._opt_compute_dtypes,
        )

    def _cast_params(self, params):
        """Compute-dtype copy of the >=2-D param leaves (exactly the cast
        the modules apply: Dense/Embed promote their matrices to the
        module dtype; 1-D leaves — RMSNorm weights — stay f32)."""
        cd = self.model_config.compute_dtype
        return jax.tree_util.tree_map(
            lambda p: p.astype(cd) if p.ndim >= 2 else p, params
        )

    def _apply_params(self, state: TrainState):
        """The param tree the model forward should consume: the carried
        compute-dtype copy (gradients linked to the f32 master via
        ``_linked_cast``), or the master itself when the carry is off."""
        if state.params_c is None:
            return state.params
        return _linked_cast(state.params, state.params_c)

    def _make_state(self, rng: jax.Array) -> TrainState:
        param_rng, dropout_rng = jax.random.split(rng)
        dummy = jnp.zeros((1, 8), jnp.int32)
        params = self.model.init(param_rng, dummy)["params"]
        opt_state = self._offload_store(self.optimizer.init(params))
        init_scale = _INIT_LOSS_SCALE if self.use_loss_scaling else 1.0
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=dropout_rng,
            loss_scale=jnp.asarray(init_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            params_c=self._cast_params(params) if self._carry_cast else None,
        )

    def with_params_c(self, state: TrainState) -> TrainState:
        """Attach the derived compute-dtype param copy to a state that lacks
        it (checkpoint restore: ``params_c`` is stripped on save)."""
        if not self._carry_cast or state.params_c is not None:
            return state
        cast = jax.jit(
            self._cast_params,
            out_shardings=shard_lib.to_shardings(
                self._state_specs.params_c, self.mesh
            ),
        )
        return state.replace(params_c=cast(state.params))

    def init_state(self, seed: Optional[int] = None) -> TrainState:
        """Initialize (sharded directly on the mesh — params never exist
        unsharded, unlike the reference's build-on-CPU-then-wrap)."""
        seed = self.training_config.seed if seed is None else seed
        return self._init_jit(jax.random.PRNGKey(seed))

    # --- data placement -----------------------------------------------------

    def put_batch(self, local_batch: np.ndarray) -> jax.Array:
        """Host numpy ``[accum * local_bs, seq]`` → global sharded device array
        ``[accum, global_bs, seq]`` (↔ reference micro-batch slicing,
        ``ddp_trainer.py:320-326``, done once here instead of per micro-step).

        Packed batches arrive as ``[accum * local_bs, seq, 2]`` — channel 0
        tokens, channel 1 segment ids — and come out ``[accum, global_bs,
        seq, 2]``. The batch PartitionSpec is 3-D, so the trailing channel
        dim stays replicated without a second sharding.
        """
        accum = self.training_config.gradient_accumulation_steps
        packed = local_batch.ndim == 3
        n, seq = local_batch.shape[:2]
        if n % accum != 0:
            raise ValueError(f"batch rows {n} not divisible by accum {accum}")
        # Out-of-vocab ids make the embedding gather silently produce garbage
        # (NaN loss a few steps later); a host-side max over the batch is
        # ~free next to the device step. Typical trigger: byte tokenizer ids
        # (<= 50256) against a shrunken vocab_size.
        vocab = self.model_config.vocab_size
        tokens = local_batch[..., 0] if packed else local_batch
        top = int(tokens.max()) if tokens.size else 0
        if top >= vocab or int(tokens.min() if tokens.size else 0) < 0:
            raise ValueError(
                f"batch contains token id {top} outside [0, {vocab}) — "
                f"tokenizer/vocab_size mismatch (e.g. byte-tokenizer ids "
                f"with a reduced model vocab)"
            )
        tail = local_batch.shape[2:]
        local = local_batch.reshape(accum, n // accum, seq, *tail)
        # feed_world, not process_count: hosts sharing a data shard (a
        # sequence/tensor axis spanning hosts) each pass the same rows, and
        # the global row count scales with the number of DISTINCT slices.
        global_shape = (accum, (n // accum) * self.data_feed_world, seq, *tail)
        return jax.make_array_from_process_local_data(
            self.batch_sharding, local, global_shape
        )

    # --- the step -----------------------------------------------------------

    def place_batch(self, batch) -> jax.Array:
        """Host array ``[accum * local_bs, seq]`` (or ``[accum, local_bs,
        seq]``) → the sharded ``[accum, global_bs, seq]`` device array the
        jitted step expects; device arrays pass through. Packed batches
        carry a trailing ``2`` channel dim (tokens, segment ids) and are
        recognized by ``shape[-1] == 2`` (a real seq dim is never 2).
        Public: the device-prefetch feed (``data/device_prefetch.py``) uses
        this to enqueue H2D copies ahead of the step."""
        if not isinstance(batch, jax.Array):
            batch = np.asarray(batch)
            packed = batch.shape[-1] == 2
            flat_ndim = 3 if packed else 2
            if batch.ndim == flat_ndim + 1:
                batch = batch.reshape(-1, *batch.shape[2:])
            batch = self.put_batch(batch)
        return batch

    def train_step(self, state: TrainState, batch,
                   telemetry: bool = False) -> Tuple[TrainState, dict]:
        """One optimizer step over ``accum`` micro-batches.

        ``batch``: the sharded ``[accum, global_bs, seq]`` device array from
        ``put_batch``, or a **process-local** host array, which is placed
        automatically (``place_batch``).

        ``telemetry=True`` runs the telemetry variant of the step (separate
        executable, compiled on first use): the metrics dict gains a
        ``"telemetry"`` subtree of per-layer grad/param/update norms,
        activation RMS/absmax, and MoE router stats.
        """
        batch = self.place_batch(batch)
        if telemetry:
            return self._step_tel_jit(state, batch)
        return self._step_jit(state, batch)

    def step_memory_analysis(self, state: TrainState, batch) -> Optional[dict]:
        """Compiler-reported per-device HBM footprint of the compiled train
        step, in bytes.

        Fallback memory accounting for runtimes that hide
        ``device.memory_stats()`` (e.g. the axon TPU tunnel returns None):
        the XLA executable's own ``memory_analysis`` works regardless of
        runtime introspection. ``peak_bytes`` ≈ arguments + outputs +
        temporaries − aliased (the donated train state aliases its output, so
        it is counted once). Returns None when the backend doesn't expose the
        analysis.
        """
        batch = self.place_batch(batch)
        # Same jit object + same shapes as the running step: this hits the
        # existing executable cache rather than recompiling.
        compiled = self._step_jit.lower(state, batch).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        try:
            arg = ma.argument_size_in_bytes
            out = ma.output_size_in_bytes
            tmp = ma.temp_size_in_bytes
            alias = ma.alias_size_in_bytes
        except AttributeError:
            return None
        return {
            "argument_bytes": arg,
            "output_bytes": out,
            "temp_bytes": tmp,
            "alias_bytes": alias,
            "peak_bytes": arg + out + tmp - alias,
        }

    def step_cost_analysis(self, state: TrainState, batch) -> Optional[dict]:
        """Compiler-predicted cost of one train step: FLOPs and HBM bytes
        accessed per the XLA cost model, plus the memory_analysis peak.

        This is the *computed ceiling* next to the observed rate: predicted
        FLOPs/step over device peak FLOPs gives the step time the chip
        cannot beat, and achieved/predicted FLOP throughput is an MFU that
        charges the model for padding and recompute the 6N estimate misses.
        Returns None when the backend hides the analysis.
        """
        batch = self.place_batch(batch)
        # Same jit object + shapes as the running step: hits the executable
        # cache (or warms it — this doubles as an explicit compile point the
        # goodput ledger can attribute to "compile").
        compiled = self._step_jit.lower(state, batch).compile()
        try:
            ca = compiled.cost_analysis()
        except Exception:
            return None
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else None
        if not ca:
            return None
        out = {}
        if ca.get("flops"):
            out["flops_per_step"] = float(ca["flops"])
        if ca.get("bytes accessed"):
            out["bytes_accessed"] = float(ca["bytes accessed"])
        mem = self.step_memory_analysis(state, batch)
        if mem is not None:
            out["peak_bytes"] = mem["peak_bytes"]
        return out or None

    def compiled_step_text(self, state: TrainState, batch) -> Optional[str]:
        """Post-optimization HLO of the compiled train step (or None).

        Used by parallel/comms_model.crosscheck to count the collective ops
        GSPMD actually inserted against the analytic traffic model. Same jit
        object + shapes as the running step, so this hits the executable
        cache rather than recompiling.
        """
        batch = self.place_batch(batch)
        try:
            return self._step_jit.lower(state, batch).compile().as_text()
        except Exception:
            return None

    def executable_cache_size(self) -> Optional[int]:
        """Number of executables cached across the train-step jit variants.

        Growth after warmup means XLA recompiled the step — on TPU a
        multi-second stall per occurrence, usually shape churn from the
        loader. Returns None when this jax doesn't expose the private
        cache-size hook (the watchdog then disarms rather than guessing).
        """
        total = 0
        for fn in (self._step_jit, self._step_tel_jit):
            try:
                total += fn._cache_size()
            except Exception:
                return None
        return total

    def nan_scan(self, state: TrainState, batch) -> dict:
        """Forward-only activation scan: where does the first NaN/Inf appear?

        Runs one deterministic forward (micro-batch 0) with the telemetry
        capture active and bisects the per-layer absmax series host-side.
        Returns ``{"first_nan": {"layer", "site"} | None, "sites": [...],
        "stats": {flattened telemetry scalars}}`` — see
        utils/telemetry.nan_report. Debug tool (``--nan_scan``); the
        activation hooks don't run under pipeline schedules (stage > 1).
        """
        batch = self.place_batch(batch)

        def scan_fn(st, micro):
            tokens, segs = _split_packed(micro)
            with telemetry.capture(deep=True) as cap:
                with self._sp_context():
                    _, loss = self.model.apply(
                        {"params": st.params}, tokens, labels=tokens,
                        segment_ids=segs,
                    )
            stats = telemetry.assemble(cap.stats)
            stats["loss"] = loss
            return stats

        stats = jax.jit(scan_fn)(state, batch[0])
        stats = jax.device_get(stats)
        report = telemetry.nan_report(stats)
        report["stats"] = telemetry.flatten_scalars(
            {k: v for k, v in stats.items() if isinstance(v, dict)},
            prefix="nan_scan",
        )
        report["stats"]["nan_scan/loss"] = float(np.asarray(stats["loss"]))
        return report

    def eval_step(self, state: TrainState, batch) -> jax.Array:
        """Forward-only mean loss on one ``[rows, seq]`` batch (deterministic,
        no dropout) — the eval loop the reference's dead ``eval_interval``
        promised (``ddp_trainer.py:52``, SURVEY.md §0.1)."""
        if not isinstance(batch, jax.Array):
            local = np.asarray(batch)
            n, seq = local.shape[:2]
            batch = jax.make_array_from_process_local_data(
                self._eval_batch_sharding, local,
                (n * self.data_feed_world, seq) + local.shape[2:]
            )
        return self._eval_jit(state, batch)

    def _eval_step(self, state: TrainState, batch: jax.Array):
        tokens, segs = _split_packed(batch)
        with self._sp_context():
            _, loss = self.model.apply(
                {"params": state.params}, tokens, labels=tokens,
                segment_ids=segs,
            )
        return loss

    def _sp_context(self):
        """Trace context for the model body: publishes the mesh so mesh-aware
        ops (the Pallas flash kernel) shard_map themselves over it
        (``parallel/context.py``), plus the ring-attention context when the
        mesh has a non-trivial ``sequence`` axis."""
        import contextlib

        stack = contextlib.ExitStack()
        stack.enter_context(ctx_lib.mesh_scope(self.mesh))
        if self.sp_size > 1:
            stack.enter_context(ring.sequence_parallel(self.mesh))
        return stack

    def _train_step(self, state: TrainState, batch: jax.Array,
                    telemetry_on: bool = False):
        cfg = self.training_config
        accum = cfg.gradient_accumulation_steps
        assert batch.ndim in (3, 4) and batch.shape[0] == accum

        def loss_fn(params, micro, rng, scale):
            tokens, segs = _split_packed(micro)
            # With the carried cast, the forward consumes the state's
            # compute-dtype copy; gradients still land on the f32 master
            # (_linked_cast routes the cotangents through the
            # cast-transpose). Identical numerics to casting here.
            if state.params_c is not None:
                params = _linked_cast(params, state.params_c)
            # Telemetry variant only: activate the trace-time capture so the
            # model routes per-layer activation/router stats out of the
            # forward; they ride the value_and_grad aux. The steady-state
            # trace (telemetry_on=False) is byte-identical to before.
            cap_cm = (telemetry.capture() if telemetry_on
                      else contextlib.nullcontext())
            with cap_cm as cap:
                with self._sp_context():
                    _, loss = self.model.apply(
                        {"params": params},
                        tokens,
                        labels=tokens,
                        train=True,
                        rngs={"dropout": rng},
                        segment_ids=segs,
                    )
            if telemetry_on:
                return loss * scale, (loss, telemetry.assemble(cap.stats))
            return loss * scale, loss

        if (self.stage_size > 1
                and self.model_config.pipeline_schedule in (
                    "1f1b", "interleaved")):
            # Manual interleaved-backward schedule: the loss and gradients
            # come from one scheduled scan instead of AD over the GPipe
            # forward — the activation-memory cap 1F1B exists for
            # (models/gpt.py pipeline_1f1b_value_and_grad).
            from tpu_trainer.models.gpt import pipeline_1f1b_value_and_grad

            _raw_1f1b = pipeline_1f1b_value_and_grad(
                self.model, self.mesh,
                self.model_config.pipeline_microbatches or self.stage_size,
            )

            def grad_fn(p, micro, rng_, scale_):
                # Same trace context as loss_fn: publishes the mesh so the
                # flash dispatch shard_maps its batch/head axes — without
                # it the Pallas call inside the stage body would force
                # batch replication, the memory cliff 1F1B exists to avoid.
                with self._sp_context():
                    (scaled, loss_v), g = _raw_1f1b(p, micro, rng_, scale_)
                if telemetry_on:
                    # 1f1b bypasses normal AD — no forward capture here;
                    # grad/param/update norms below still apply.
                    return (scaled, (loss_v, {})), g
                return (scaled, loss_v), g
        else:
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        fwd_stats = None
        if accum == 1:
            # No accumulation buffer — one backward, grads consumed in place.
            new_rng, sub = jax.random.split(state.rng)
            (_, aux), grads = grad_fn(
                state.params, batch[0], sub, state.loss_scale
            )
            if telemetry_on:
                loss_sum, fwd_stats = aux
            else:
                loss_sum = aux
        else:
            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )

            def micro_step(carry, micro):
                grads_acc, loss_acc, rng = carry
                rng, sub = jax.random.split(rng)
                (_, aux), grads = grad_fn(state.params, micro, sub, state.loss_scale)
                loss = aux[0] if telemetry_on else aux
                grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
                ys = aux[1] if telemetry_on else None
                return (grads_acc, loss_acc + loss, rng), ys

            (grads, loss_sum, new_rng), fwd_stack = jax.lax.scan(
                micro_step, (zero_grads, jnp.zeros((), jnp.float32), state.rng), batch
            )
            if telemetry_on:
                # [accum, ...]-stacked forward stats → mean (max for absmax).
                fwd_stats = telemetry.reduce_micro(fwd_stack)
        # Mean over micro-steps and undo the loss scale; then pin the grads to
        # their ZeRO sharding (the reduce-scatter point under zero2/zero3).
        denom = accum * state.loss_scale
        grads = jax.tree_util.tree_map(lambda g: g / denom, grads)
        grads = shard_lib.constrain(grads, self._grad_shardings)
        loss = loss_sum / accum

        grad_norm = optax.global_norm(grads)

        # Schedule applied here, as a pure function of state.step (fixes b1;
        # also keeps logged LR == applied LR across fp16 overflow skips, where
        # the optimizer chain's internal count freezes but the schedule ticks).
        lr = cfg.lr_at(state.step)

        def apply_update(_):
            opt_in = state.opt_state
            if self.cpu_offload:
                opt_in = jax.device_put(opt_in, self._opt_device_shardings)
            updates, new_opt = self.optimizer.update(
                grads, self._offload_load(opt_in), state.params
            )
            new_opt = self._offload_store(new_opt)
            if self.cpu_offload:
                new_opt = jax.device_put(new_opt, self._opt_host_shardings)
            updates = jax.tree_util.tree_map(lambda u: u * lr, updates)
            new_p = optax.apply_updates(state.params, updates)
            # The compute-dtype copy is produced HERE, in the same
            # executable as the update — XLA fuses the cast into the
            # update fusions' epilogues (the point of params_c).
            new_c = self._cast_params(new_p) if self._carry_cast else None
            return new_p, new_opt, new_c

        if self.use_loss_scaling:
            finite = jnp.isfinite(grad_norm)
            new_params, new_opt, new_params_c = jax.lax.cond(
                finite, apply_update,
                lambda _: (state.params, state.opt_state, state.params_c),
                None,
            )
            grew = state.good_steps + 1 >= _SCALE_GROWTH_INTERVAL
            new_scale = jnp.where(
                finite,
                jnp.where(grew, jnp.minimum(state.loss_scale * 2.0, _MAX_LOSS_SCALE),
                          state.loss_scale),
                jnp.maximum(state.loss_scale * 0.5, 1.0),
            )
            new_good = jnp.where(finite, jnp.where(grew, 0, state.good_steps + 1), 0)
        else:
            new_params, new_opt, new_params_c = apply_update(None)
            new_scale, new_good = state.loss_scale, state.good_steps

        metrics = {
            "loss": loss,
            "lr": lr,
            "grad_norm": grad_norm,
            "loss_scale": state.loss_scale,
        }
        if telemetry_on:
            telem = dict(fwd_stats or {})
            # Per-group norms from the trees the step already has in hand:
            # the stacked [num_layers, ...] leaves reduce to a per-layer
            # vector, embed/norm to scalars (telemetry.group_norms; the
            # recombination to optax.global_norm is pinned by tests).
            grad_norms = telemetry.group_norms(grads)
            param_norms = telemetry.group_norms(state.params)
            update_norms = telemetry.group_norms(jax.tree_util.tree_map(
                lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
                new_params, state.params,
            ))
            telem["grad_norm"] = grad_norms
            telem["param_norm"] = param_norms
            telem["update_ratio"] = {
                k: update_norms[k] / (param_norms[k] + 1e-20)
                for k in update_norms
            }
            metrics["telemetry"] = telem
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt,
            rng=new_rng,
            params_c=new_params_c,
        )
        if self.use_loss_scaling:
            new_state = new_state.replace(loss_scale=new_scale, good_steps=new_good)
        return new_state, metrics


class RecompileWatchdog:
    """Detect steady-state recompilation of the jitted train step.

    ``jax.jit`` silently compiles a fresh executable for every new abstract
    input signature; a loader that churns shapes (ragged tails, bucketing
    bugs) turns each "cache miss" into a multi-second compile stall that
    telemetry otherwise books as ordinary step time. The watchdog samples
    ``Trainer.executable_cache_size()`` after every step: growth that the
    training loop did not expect (first use of the plain or telemetry
    variant is expected) produces a ``kind:"recompile"`` record carrying
    the offending batch's abstract shape; ``warn_after`` unexpected events
    flips ``storm`` on, the loop's cue to warn loudly.

    Disarms (observe returns None forever) when the cache-size hook is
    unavailable on this jax.
    """

    def __init__(self, trainer: Trainer, warn_after: int = 3):
        self.trainer = trainer
        self.warn_after = warn_after
        self.events: list = []
        self._watermark: Optional[int] = None
        self._armed = trainer.executable_cache_size() is not None

    def observe(self, step: int, batch=None,
                expected: bool = False) -> Optional[dict]:
        """Sample the executable cache after ``step`` ran on ``batch``.

        ``expected=True`` raises the watermark silently (warmup compiles:
        the first use of each step variant). Returns the recompile record
        to log, or None when nothing unexpected happened.
        """
        if not self._armed:
            return None
        size = self.trainer.executable_cache_size()
        if size is None:
            self._armed = False
            return None
        if self._watermark is None or expected:
            self._watermark = max(self._watermark or 0, size)
            return None
        if size <= self._watermark:
            return None
        grew = size - self._watermark
        self._watermark = size
        shape = tuple(getattr(batch, "shape", ()) or ())
        dtype = getattr(batch, "dtype", None)
        record = {
            "kind": "recompile",
            "step": int(step),
            "executables": int(size),
            "new_executables": int(grew),
            "batch_abstract": "{}[{}]".format(
                dtype if dtype is not None else "?",
                ",".join(str(d) for d in shape)),
        }
        self.events.append(record)
        record["recompiles_total"] = len(self.events)
        record["storm"] = len(self.events) >= self.warn_after
        return record
