"""FSDP/ZeRO training entry point (↔ reference ``src/training/fsdp_trainer.py``).

Fully-sharded data parallelism the TPU way: params/grads/optimizer state
sharded over the ``fsdp`` mesh axis via NamedSharding (GSPMD emits the
all-gather/reduce-scatter that torch FSDP performs per wrapped module —
SURVEY.md C10). Sharding modes accept the reference spellings::

    python -m tpu_trainer.training.train_fsdp --sharding FULL_SHARD     # ZeRO-3
    python -m tpu_trainer.training.train_fsdp --sharding SHARD_GRAD_OP  # ZeRO-2
    python -m tpu_trainer.training.train_fsdp --sharding NO_SHARD       # DDP-like
    python -m tpu_trainer.training.train_fsdp --sharding HYBRID_SHARD \
        --mesh_data 2 --mesh_fsdp 4   # working here; docstring-only upstream

or via ``scripts/train_fsdp.sh``. Activation checkpointing defaults ON
(reference ``fsdp_trainer.py:312-328``); disable with
``--no_activation_checkpointing``.
"""

import sys

from tpu_trainer.training.cli import run_training

def main(argv=None) -> int:
    return run_training(argv, mode="fsdp")


if __name__ == "__main__":
    sys.exit(main())
