"""DDP training entry point (↔ reference ``src/training/ddp_trainer.py``).

Data parallelism the TPU way: params replicated, batch sharded over the
``data`` mesh axis, gradient all-reduce inserted by the XLA SPMD partitioner
(SURVEY.md C9). Run::

    python -m tpu_trainer.training.train_ddp --model_size small --max_steps 50

or via ``scripts/train_ddp.sh``.
"""

import sys

from tpu_trainer.training.cli import run_training

def main(argv=None) -> int:
    return run_training(argv, mode="ddp")


if __name__ == "__main__":
    sys.exit(main())
