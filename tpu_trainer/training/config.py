"""Training configuration and LR schedule.

Single unified ``TrainingConfig`` replacing the reference's two divergent
copies (``ddp_trainer.py:34-63`` lr=6e-4/accum=4 vs ``fsdp_trainer.py:78-93``
lr=3e-4/accum=8 — SURVEY.md §5.6). Defaults follow the DDP copy; the FSDP CLI
overrides what it needs.

The schedule is the reference's linear-warmup → cosine-to-10%-of-peak
(``ddp_trainer.py:237-271``), with the two reference bugs fixed by design
(SURVEY.md §2.1):

- b1: the LR is a pure function of the step, applied functionally *inside*
  the optimizer at each step — no set-after-step off-by-one.
- b4: ``decay_ratio`` is clamped to [0, 1] so training past ``max_steps``
  holds at ``min_lr`` (the DDP copy rises again past pi; the FSDP copy clamps).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TrainingConfig:
    """Unified training configuration (reference TrainingConfig union)."""

    # Data
    batch_size: int = 8           # per-data-shard micro-batch size
    max_seq_len: int = 1024

    # Optimization (reference ddp_trainer.py:40-45)
    learning_rate: float = 6e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0

    # Schedule (reference ddp_trainer.py:47-52)
    max_steps: int = 10000
    warmup_steps: int = 1000
    log_interval: int = 1
    eval_interval: int = 500
    save_interval: int = 1000

    # Mixed precision: "fp32" | "bf16" | "fp16" (reference ddp_trainer.py:55)
    mixed_precision: str = "bf16"

    # On-device Adam moment storage: "float32" (exact, default) |
    # "bfloat16" | "int8" (blockwise-absmax, second moment in sqrt-space —
    # utils/quant.py). Narrow moments cut the HBM-bound optimizer-update
    # traffic (~31 ms/step of a 108 ms MoE step at E=8, where the optimizer
    # pays for all 746M params while compute pays for the active 152M).
    # Orthogonal to cpu_offload's offload_dtype (host storage).
    optimizer_state_dtype: str = "float32"

    # Carry the compute-dtype copy of the params in the train state
    # (TrainState.params_c): the full-tree f32->compute cast fuses into the
    # optimizer update's epilogue instead of running as separate convert
    # passes at the top of every step (~1.7 ms at headline geometry), and
    # under ZeRO-3 the forward all-gathers move half the bytes. Costs one
    # extra compute-dtype copy of the params in HBM; numerics are identical
    # (the same cast, one step earlier). Auto-disabled when compute dtype
    # == param dtype and under cpu_offload (HBM-edge configs).
    carry_cast_params: bool = True

    # Gradient accumulation (reference ddp_trainer.py:58)
    gradient_accumulation_steps: int = 4

    # Step overlap (ISSUE 4). prefetch_depth: host-side batches assembled
    # ahead on the Prefetcher thread (0 = synchronous). device_prefetch_depth:
    # batches jax.device_put ahead with the batch sharding so H2D rides under
    # the previous step's compute (0 = place inside the step, the old
    # behavior). async_checkpointing: save_interval checkpoints snapshot to
    # host and commit on a background writer (utils/checkpoint.py AsyncSaver);
    # at most one save in flight, crash-safety contract unchanged.
    prefetch_depth: int = 2
    device_prefetch_depth: int = 2
    async_checkpointing: bool = True

    # Checkpointing (reference ddp_trainer.py:61-63). resume_from is consumed
    # by the training CLI entrypoints (tpu_trainer.training.train), which also
    # auto-resume from the latest checkpoint in checkpoint_dir — the
    # reference's resume_from was dead config (SURVEY.md §0.1).
    checkpoint_dir: str = "checkpoints"
    resume_from: Optional[str] = None

    # RNG
    seed: int = 0

    @property
    def min_lr(self) -> float:
        return 0.1 * self.learning_rate

    def lr_at(self, step) -> jnp.ndarray:
        """LR as a pure (jit-friendly) function of step."""
        step = jnp.asarray(step, jnp.float32)
        peak = self.learning_rate
        warmup = peak * step / max(1, self.warmup_steps)
        decay_steps = max(1, self.max_steps - self.warmup_steps)
        ratio = jnp.clip((step - self.warmup_steps) / decay_steps, 0.0, 1.0)
        coeff = 0.5 * (1.0 + jnp.cos(jnp.pi * ratio))
        cosine = self.min_lr + coeff * (peak - self.min_lr)
        return jnp.where(step < self.warmup_steps, warmup, cosine)
