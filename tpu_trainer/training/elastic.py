"""Elastic run supervisor: host-loss survival for multi-process training.

``python -m tpu_trainer.training.elastic --num_processes N --run_dir DIR \\
    -- --config tiny.yaml --checkpoint_dir DIR/ckpt ...``

launches N trainer processes (``train_ddp``/``train_fsdp`` over
``jax.distributed`` — on CPU the gloo collective fabric selected by
``parallel/mesh.initialize_distributed``), watches them, and keeps the run
alive through host loss:

1. **Launch**: each child gets ``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/
   ``PROCESS_ID`` (the env rendezvous ``mesh.initialize_distributed``
   reads), a bounded ``COORDINATOR_TIMEOUT_S``, and
   ``TPU_TRAINER_HEARTBEAT_DIR`` pointing at this attempt's heartbeat
   directory (``training/cli.py`` writes one beat per completed step
   through the flight-recorder path, ``utils/flight_recorder.py``).
2. **Watch**: a host is declared dead on (a) nonzero exit — a crash, OOM
   kill, or preemption that outran its grace — or (b) heartbeat staleness
   past ``--heartbeat_timeout_s`` — a *hung* host that holds the whole pod's
   collectives hostage without ever exiting (the failure mode exit codes
   cannot see; the ``hang_host`` chaos fault drives exactly this).
3. **Reform**: on any death the surviving processes are torn down too (they
   are blocked inside collectives with a dead peer and cannot make
   progress), the world shrinks to the survivors, and the run relaunches.
   Auto-resume restores the last *committed* checkpoint — the two-phase
   commit in ``utils/checkpoint.py`` guarantees a host death mid-save left
   either a complete meta.json or an invisible meta-less tree — and the
   cursor remap (``remap_data_state``) re-bases the data stream onto the
   resized mesh's batch granularity.

Every death/restart writes JSONL records to ``<run_dir>/supervisor.jsonl``:
``kind:"host_death"``, ``kind:"recovery"`` (detection -> first post-restart
step, the new ``recovery`` goodput category), and a final
``kind:"elastic_summary"`` — ``tools/analyze.py`` summarizes them and gates
on recovery time and restart-count regressions.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from tpu_trainer.utils import flight_recorder as flight_lib
from tpu_trainer.utils import telemetry as telemetry_lib
from tpu_trainer.utils.logging import SCHEMA_VERSION

# Child teardown: SIGTERM, then SIGKILL after this many seconds. Short —
# by the time the supervisor tears a survivor down it is wedged in a
# collective with a dead peer, and its last committed checkpoint is
# already durable (a mid-save death cannot produce a half-committed one).
_TERM_GRACE_S = 5.0


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Child:
    """One trainer process of the current attempt."""

    def __init__(self, host: int, proc: subprocess.Popen, log_path: str,
                 log_file):
        self.host = host
        self.proc = proc
        self.log_path = log_path
        self.log_file = log_file
        self.exited: Optional[int] = None  # exit code once reaped

    def poll(self) -> Optional[int]:
        if self.exited is None:
            rc = self.proc.poll()
            if rc is not None:
                self.exited = rc
                self.log_file.close()
        return self.exited


class Supervisor:
    """Launch/watch/reform loop around N trainer processes.

    ``trainer_argv`` is the child CLI (everything after ``--``); the
    supervisor owns ``--num_processes`` down to ``--min_processes`` and
    gives up after ``--max_restarts`` reforms (a deterministic crash would
    otherwise restart forever).
    """

    def __init__(
        self,
        trainer_argv: List[str],
        *,
        num_processes: int,
        run_dir: str,
        mode: str = "ddp",
        max_restarts: int = 2,
        min_processes: int = 1,
        heartbeat_timeout_s: float = 30.0,
        startup_grace_s: float = 300.0,
        poll_interval_s: float = 0.2,
        coordinator_timeout_s: float = 60.0,
        env: Optional[Dict[str, str]] = None,
    ):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        self.trainer_argv = list(trainer_argv)
        self.world = int(num_processes)
        self.run_dir = os.path.abspath(run_dir)
        self.mode = mode
        self.max_restarts = int(max_restarts)
        self.min_processes = int(min_processes)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.startup_grace_s = float(startup_grace_s)
        self.poll_interval_s = float(poll_interval_s)
        self.coordinator_timeout_s = float(coordinator_timeout_s)
        self.base_env = dict(os.environ if env is None else env)
        self.restarts = 0
        self.attempt = 0
        self.ledger = telemetry_lib.GoodputLedger()
        os.makedirs(self.run_dir, exist_ok=True)
        self.events_path = os.path.join(self.run_dir, "supervisor.jsonl")

    # --- plumbing -------------------------------------------------------

    def _log(self, msg: str) -> None:
        print(f"elastic | {msg}", flush=True)

    def _emit(self, record: dict) -> None:
        record = dict(record, schema_version=SCHEMA_VERSION, unix=time.time())
        with open(self.events_path, "a") as fh:
            fh.write(json.dumps(record) + "\n")
            fh.flush()

    def _hb_dir(self) -> str:
        # Per-attempt heartbeat directories: a stale beat file from the
        # previous attempt must not trip the staleness check (or satisfy
        # the first-beat recovery probe) of the next one.
        return os.path.join(self.run_dir, "heartbeats",
                            f"attempt{self.attempt}")

    def _launch(self) -> List[_Child]:
        port = _free_port()
        hb_dir = self._hb_dir()
        os.makedirs(hb_dir, exist_ok=True)
        children = []
        for host in range(self.world):
            env = dict(self.base_env)
            env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
            env["NUM_PROCESSES"] = str(self.world)
            env["PROCESS_ID"] = str(host)
            # A peer that dies before the rendezvous must become an error
            # the survivors (and this loop) can see, not an infinite wait.
            env["COORDINATOR_TIMEOUT_S"] = str(int(self.coordinator_timeout_s))
            env["TPU_TRAINER_HEARTBEAT_DIR"] = hb_dir
            log_path = os.path.join(
                self.run_dir, f"host{host}_attempt{self.attempt}.log")
            log_file = open(log_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 f"tpu_trainer.training.train_{self.mode}",
                 *self.trainer_argv],
                stdout=log_file, stderr=subprocess.STDOUT, env=env,
            )
            children.append(_Child(host, proc, log_path, log_file))
        self._log(f"attempt {self.attempt}: launched {self.world} "
                  f"process(es), coordinator 127.0.0.1:{port}, "
                  f"heartbeats {hb_dir}")
        return children

    def _teardown(self, children: List[_Child]) -> None:
        for c in children:
            if c.poll() is None:
                try:
                    c.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + _TERM_GRACE_S
        for c in children:
            if c.exited is not None:
                continue
            try:
                c.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    c.proc.kill()
                except OSError:
                    pass
                c.proc.wait()
            c.poll()

    # --- death detection ------------------------------------------------

    def _check_deaths(self, children: List[_Child], started: float) -> List[dict]:
        """Dead hosts this poll: nonzero exits plus heartbeat flatlines.

        Exit-based deaths are definitive. Staleness needs attribution: a
        single hung host stalls every survivor too (they block inside a
        collective with the silent peer and stop beating shortly after), so
        by detection time *several* beats may be stale. Blaming them all
        would shrink the world to nothing over one wedged host — so among
        stale hosts only the one whose stream flatlined FIRST is declared
        dead; the stalled survivors get a fresh start in the reformed run.
        """
        now = time.time()
        deaths = []
        stale = []
        for c in children:
            rc = c.poll()
            if rc is not None and rc != 0:
                deaths.append({"host": c.host, "cause": f"exit:{rc}",
                               "exit_code": rc})
                continue
            if rc is not None:
                continue  # clean exit: not a death, just done early/waiting
            beat = flight_lib.read_heartbeat(self._hb_dir(), c.host)
            if beat is None:
                if now - started > self.startup_grace_s:
                    deaths.append({"host": c.host, "cause": "startup_timeout",
                                   "exit_code": None})
            elif now - float(beat["unix"]) > self.heartbeat_timeout_s:
                stale.append((float(beat["unix"]),
                              {"host": c.host, "cause": "heartbeat_timeout",
                               "exit_code": None,
                               "step_last_beat": beat.get("step")}))
        if stale:
            deaths.append(min(stale, key=lambda t: t[0])[1])
        return deaths

    def _first_beat_unix(self) -> Optional[float]:
        """Earliest beat of the current attempt — the first post-restart
        step, closing the recovery window."""
        best = None
        for host in range(self.world):
            beat = flight_lib.read_heartbeat(self._hb_dir(), host)
            if beat is not None:
                t = float(beat["unix"])
                best = t if best is None else min(best, t)
        return best

    # --- the loop -------------------------------------------------------

    def run(self) -> int:
        pending_recovery: Optional[dict] = None  # death awaiting 1st new step
        while True:
            started = time.time()
            children = self._launch()
            try:
                result = self._watch(children, started, pending_recovery)
            finally:
                self._teardown(children)
            pending_recovery = None
            if result["outcome"] == "done":
                self._finish(0)
                return 0
            deaths = result["deaths"]
            detected = result["detected_unix"]
            for d in deaths:
                self._emit(dict(d, kind="host_death", attempt=self.attempt,
                                detected_unix=detected))
                self._log(f"host {d['host']} dead ({d['cause']})")
            new_world = self.world - len(deaths)
            if self.restarts >= self.max_restarts:
                self._log(f"restart budget exhausted "
                          f"({self.restarts}/{self.max_restarts}); giving up")
                self._finish(1)
                return 1
            if new_world < self.min_processes:
                self._log(f"only {new_world} host(s) left "
                          f"(< min_processes={self.min_processes}); giving up")
                self._finish(1)
                return 1
            self.restarts += 1
            self.attempt += 1
            pending_recovery = {
                "restart": self.restarts,
                "world_before": self.world,
                "world_after": new_world,
                "dead_hosts": [d["host"] for d in deaths],
                "cause": deaths[0]["cause"],
                "detected_unix": detected,
            }
            self.world = new_world
            self._log(f"reforming on {self.world} host(s) "
                      f"(restart {self.restarts}/{self.max_restarts})")

    def _watch(self, children: List[_Child], started: float,
               pending_recovery: Optional[dict]) -> dict:
        """Poll until every child exits cleanly (outcome "done") or a death
        is detected (outcome "death"). Also closes a pending recovery window
        at the attempt's first heartbeat."""
        while True:
            if pending_recovery is not None:
                first = self._first_beat_unix()
                if first is not None:
                    rec = dict(pending_recovery, kind="recovery",
                               first_step_unix=first,
                               recovery_seconds=max(
                                   0.0,
                                   first - pending_recovery["detected_unix"]))
                    self.ledger.add("recovery", rec["recovery_seconds"])
                    self._emit(rec)
                    self._log(f"recovered in {rec['recovery_seconds']:.1f}s "
                              f"(restart {rec['restart']}, world "
                              f"{rec['world_before']}→{rec['world_after']})")
                    pending_recovery = None
            deaths = self._check_deaths(children, started)
            if deaths:
                return {"outcome": "death", "deaths": deaths,
                        "detected_unix": time.time()}
            if all(c.poll() is not None for c in children):
                # All zero (nonzero would have been a death above).
                return {"outcome": "done"}
            time.sleep(self.poll_interval_s)

    def _finish(self, exit_code: int) -> None:
        self._emit({
            "kind": "elastic_summary",
            "restarts": self.restarts,
            "final_world": self.world,
            "exit_code": exit_code,
            "recovery_seconds_total": self.ledger.seconds("recovery"),
        })
        self._emit(self.ledger.record(final=True))
        self._log(f"summary: {self.restarts} restart(s), final world "
                  f"{self.world}, recovery "
                  f"{self.ledger.seconds('recovery'):.1f}s total")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_trainer.training.elastic",
        description="Elastic run supervisor: launch N trainer processes, "
                    "watch heartbeats/exits, restart on the surviving host "
                    "set from the last committed checkpoint. Trainer flags "
                    "go after '--'.",
    )
    p.add_argument("--num_processes", type=int, required=True)
    p.add_argument("--run_dir", type=str, required=True,
                   help="supervisor state: heartbeats, per-host logs, "
                        "supervisor.jsonl (the trainer's --checkpoint_dir "
                        "is its own flag, after '--')")
    p.add_argument("--mode", choices=["ddp", "fsdp"], default="ddp")
    p.add_argument("--max_restarts", type=int, default=2)
    p.add_argument("--min_processes", type=int, default=1)
    p.add_argument("--heartbeat_timeout_s", type=float, default=30.0)
    p.add_argument("--startup_grace_s", type=float, default=300.0,
                   help="allowance before the first beat of an attempt "
                        "(jax import + compile); only then does beat "
                        "absence count as a hang")
    p.add_argument("--poll_interval_s", type=float, default=0.2)
    p.add_argument("--coordinator_timeout_s", type=float, default=60.0)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        sup_argv, trainer_argv = argv[:split], argv[split + 1:]
    else:
        sup_argv, trainer_argv = argv, []
    args = build_parser().parse_args(sup_argv)
    sup = Supervisor(
        trainer_argv,
        num_processes=args.num_processes,
        run_dir=args.run_dir,
        mode=args.mode,
        max_restarts=args.max_restarts,
        min_processes=args.min_processes,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        startup_grace_s=args.startup_grace_s,
        poll_interval_s=args.poll_interval_s,
        coordinator_timeout_s=args.coordinator_timeout_s,
    )
    return sup.run()


if __name__ == "__main__":
    raise SystemExit(main())
