"""Elastic run supervisor: host-loss survival AND recovery for
multi-process training.

``python -m tpu_trainer.training.elastic --num_processes N --run_dir DIR \\
    -- --config tiny.yaml --checkpoint_dir DIR/ckpt ...``

launches N trainer processes (``train_ddp``/``train_fsdp`` over
``jax.distributed`` — on CPU the gloo collective fabric selected by
``parallel/mesh.initialize_distributed``), watches them, and keeps the run
alive through host loss:

1. **Launch**: each child gets ``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/
   ``PROCESS_ID`` (the env rendezvous ``mesh.initialize_distributed``
   reads), a bounded ``COORDINATOR_TIMEOUT_S``, ``TPU_TRAINER_ATTEMPT``
   (the two-phase checkpoint commit stamps DONE markers with it — see
   ``utils/checkpoint._markers_complete``), and
   ``TPU_TRAINER_HEARTBEAT_DIR`` pointing at this attempt's heartbeat
   directory (``training/cli.py`` writes one beat per completed step
   through the flight-recorder path, ``utils/flight_recorder.py``).
2. **Watch**: a host is declared dead on (a) nonzero exit — a crash, OOM
   kill, or preemption that outran its grace — or (b) heartbeat staleness
   past ``--heartbeat_timeout_s`` — a *hung* host that holds the whole pod's
   collectives hostage without ever exiting. A host that received a
   preemption *notice* (``utils/preemption.py``) is different: it drains
   proactively — checkpoint at the step boundary, drain marker in the
   heartbeat dir, clean exit — and the supervisor reforms without anyone
   having crashed, rolling back zero steps.
3. **Reform down**: on any death the surviving processes are torn down too
   (they are blocked inside collectives with a dead peer), the world
   shrinks to the survivors, and the run relaunches. Auto-resume restores
   the last *committed* checkpoint and the cursor remap
   (``remap_data_state``) re-bases the data stream onto the resized mesh's
   batch granularity.
4. **Reform up** (``--allow_grow``): the supervisor remembers the world it
   *wants* (``--num_processes``) and probes ``<run_dir>/capacity.json``
   (every ``--grow_probe_interval_s``) for re-granted hosts — written by an
   external cluster agent, or by the ``return_host`` chaos fault. On a
   grant it drains the running attempt gracefully (SIGTERM → the trainer's
   preemption path checkpoints at the next step boundary) and relaunches
   at the larger world; the same resharding restore + cursor remap handle
   the grow direction.
5. **Standby hosts** (``--standby_hosts K``): K warm spares are pre-spawned
   and parked *before* the jax.distributed rendezvous (interpreter + jax
   import already paid — the bulk of process cold-start). A reform
   promotes parked spares into the new attempt's ranks by writing their
   activation files, cutting ``recovery_seconds``; the pool is replenished
   after every launch.

Every death/restart/grow writes JSONL records to
``<run_dir>/supervisor.jsonl``: ``kind:"host_death"``, ``kind:"recovery"``
(detection -> first post-restart step, with ``rolled_back_steps`` and
standby promotion counts), ``kind:"world_grow"`` (grant detection -> first
step at the larger world, ``grow_seconds``), and a final
``kind:"elastic_summary"`` — ``tools/analyze.py`` summarizes them and gates
on recovery time, restart-count, grow time, and failure-to-regrow.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from tpu_trainer.utils import flight_recorder as flight_lib
from tpu_trainer.utils import preemption as preemption_lib
from tpu_trainer.utils import telemetry as telemetry_lib
from tpu_trainer.utils.logging import SCHEMA_VERSION


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def hold_standby(path: str, poll_interval_s: float = 0.05
                 ) -> Optional[Dict[str, str]]:
    """Child side of the standby protocol: park until the supervisor writes
    the activation file, then return its env (the same rendezvous env a
    fresh child would have been launched with). Returns None when the
    parent supervisor is gone — an orphaned spare must retire, not wait
    forever. Called by ``training/cli.py`` before the distributed
    rendezvous, because activation assigns coordinator/world/rank."""
    parent = os.getppid()
    while True:
        try:
            with open(path) as fh:
                data = json.load(fh)
            env = data.get("env") if isinstance(data, dict) else None
            if env:
                return {str(k): str(v) for k, v in env.items()}
        except (OSError, ValueError):
            pass  # not written yet (or mid-replace; atomic rename makes
            # this transient)
        if os.getppid() != parent:
            return None
        time.sleep(poll_interval_s)


class _Child:
    """One trainer process of the current attempt."""

    def __init__(self, host: int, proc: subprocess.Popen, log_path: str,
                 log_file):
        self.host = host
        self.proc = proc
        self.log_path = log_path
        self.log_file = log_file
        self.exited: Optional[int] = None  # exit code once reaped

    def poll(self) -> Optional[int]:
        if self.exited is None:
            rc = self.proc.poll()
            if rc is not None:
                self.exited = rc
                self.log_file.close()
        return self.exited


class _Standby:
    """A warm spare: spawned, imports paid, parked before the rendezvous."""

    def __init__(self, slot: int, proc: subprocess.Popen, file_path: str,
                 log_path: str, log_file):
        self.slot = slot
        self.proc = proc
        self.file_path = file_path  # activation file promotion writes
        self.log_path = log_path
        self.log_file = log_file

    def alive(self) -> bool:
        return self.proc.poll() is None


class Supervisor:
    """Launch/watch/reform loop around N trainer processes.

    ``trainer_argv`` is the child CLI (everything after ``--``); the
    supervisor owns ``--num_processes`` down to ``--min_processes`` and
    gives up after ``--max_restarts`` reforms (a deterministic crash would
    otherwise restart forever). With ``--allow_grow`` it also owns the way
    back up to ``--num_processes``.
    """

    def __init__(
        self,
        trainer_argv: List[str],
        *,
        num_processes: int,
        run_dir: str,
        mode: str = "ddp",
        max_restarts: int = 2,
        min_processes: int = 1,
        heartbeat_timeout_s: float = 30.0,
        startup_grace_s: float = 300.0,
        poll_interval_s: float = 0.2,
        coordinator_timeout_s: float = 60.0,
        term_grace_s: float = 5.0,
        allow_grow: bool = False,
        grow_probe_interval_s: float = 5.0,
        standby_hosts: int = 0,
        drain_grace_s: float = 60.0,
        death_settle_s: float = 1.0,
        env: Optional[Dict[str, str]] = None,
        metrics_port: Optional[int] = None,
    ):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        self.trainer_argv = list(trainer_argv)
        self.world = int(num_processes)
        # The world this run WANTS. Shrinks are survival; with allow_grow
        # the supervisor keeps probing for the capacity to get back here.
        self.desired_world = int(num_processes)
        self.run_dir = os.path.abspath(run_dir)
        self.mode = mode
        self.max_restarts = int(max_restarts)
        self.min_processes = int(min_processes)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.startup_grace_s = float(startup_grace_s)
        self.poll_interval_s = float(poll_interval_s)
        self.coordinator_timeout_s = float(coordinator_timeout_s)
        # Child teardown: SIGTERM, then SIGKILL after this grace. Short by
        # default — by the time the supervisor tears a survivor down it is
        # wedged in a collective with a dead peer and its last committed
        # checkpoint is already durable — but a flag, because slow-FS CI
        # boxes need the log flush to finish before the SIGKILL.
        self.term_grace_s = float(term_grace_s)
        self.allow_grow = bool(allow_grow)
        self.grow_probe_interval_s = float(grow_probe_interval_s)
        self.standby_hosts = int(standby_hosts)
        # Graceful-drain budget: how long a SIGTERMed (grow) or noticed
        # (preempt) attempt gets to checkpoint and exit before SIGKILL.
        self.drain_grace_s = float(drain_grace_s)
        # Co-death coalescing: after the first death of a poll, wait this
        # long and re-check so two hosts dying in the same interval cost
        # one teardown + one restart, not two (and so a drain marker racing
        # its writer's exit status is classified as the drain it is).
        self.death_settle_s = float(death_settle_s)
        self.base_env = dict(os.environ if env is None else env)
        self.restarts = 0
        self.grows = 0
        self.attempt = 0
        self.promoted_total = 0
        self.ledger = telemetry_lib.GoodputLedger()
        os.makedirs(self.run_dir, exist_ok=True)
        self.events_path = os.path.join(self.run_dir, "supervisor.jsonl")
        self.capacity_path = os.path.join(self.run_dir, "capacity.json")
        self.standby_dir = os.path.join(self.run_dir, "standby")
        self._standbys: List[_Standby] = []
        self._standby_seq = 0
        self._last_promoted = 0
        self._refill_pending = False
        # Live metrics plane (obs/): the supervisor serves its own
        # endpoint — attempt/world/restart/grow/standby state as gauges
        # and counters mirrored off the attributes above (set_function:
        # read at scrape time, zero cost in the poll loop). The server
        # starts in run() and dies with it; metrics_port=None means no
        # registry work at all beyond no-op constructors.
        self.metrics_port = metrics_port
        self._metrics_server = None
        self._install_metrics()

    def _install_metrics(self) -> None:
        from tpu_trainer.obs.metrics import NULL_REGISTRY, MetricsRegistry

        self.registry = (MetricsRegistry() if self.metrics_port is not None
                         else NULL_REGISTRY)
        reg = self.registry
        reg.gauge("elastic_attempt", "Current attempt number"
                  ).set_function(lambda: self.attempt)
        world = reg.gauge("elastic_world", "Host world size",
                          labelnames=("kind",))
        world.labels(kind="current").set_function(lambda: self.world)
        world.labels(kind="desired").set_function(
            lambda: self.desired_world)
        reg.gauge("elastic_standbys", "Warm spares parked"
                  ).set_function(lambda: len(self._standbys))
        reg.counter("elastic_restarts_total", "Fault restarts"
                    ).set_function(lambda: self.restarts)
        reg.counter("elastic_grows_total", "World grow-backs"
                    ).set_function(lambda: self.grows)
        reg.counter("elastic_promotions_total", "Standby promotions"
                    ).set_function(lambda: self.promoted_total)
        reg.gauge("elastic_recovery_seconds_total",
                  "Wall-clock spent in fault recovery").set_function(
                      lambda: self.ledger.seconds("recovery"))
        reg.gauge("elastic_grow_seconds_total",
                  "Wall-clock spent in grow relaunches").set_function(
                      lambda: self.ledger.seconds("grow"))

    def statusz(self) -> dict:
        return {
            "kind": "elastic_supervisor",
            "attempt": self.attempt,
            "world": self.world,
            "desired_world": self.desired_world,
            "restarts": self.restarts,
            "grows": self.grows,
            "standbys": len(self._standbys),
            "standby_promotions": self.promoted_total,
            "allow_grow": self.allow_grow,
            "max_restarts": self.max_restarts,
            "run_dir": self.run_dir,
        }

    # --- plumbing -------------------------------------------------------

    def _log(self, msg: str) -> None:
        print(f"elastic | {msg}", flush=True)

    def _emit(self, record: dict) -> None:
        record = dict(record, schema_version=SCHEMA_VERSION, unix=time.time())
        with open(self.events_path, "a") as fh:
            fh.write(json.dumps(record) + "\n")
            fh.flush()

    def _hb_dir(self) -> str:
        # Per-attempt heartbeat directories: a stale beat file (or drain
        # marker) from the previous attempt must not trip the staleness
        # check (or satisfy the first-beat recovery probe) of the next one.
        return os.path.join(self.run_dir, "heartbeats",
                            f"attempt{self.attempt}")

    def _child_env(self, host: int, port: int, hb_dir: str) -> Dict[str, str]:
        env = dict(self.base_env)
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = str(self.world)
        env["PROCESS_ID"] = str(host)
        # A peer that dies before the rendezvous must become an error
        # the survivors (and this loop) can see, not an infinite wait.
        env["COORDINATOR_TIMEOUT_S"] = str(int(self.coordinator_timeout_s))
        env["TPU_TRAINER_HEARTBEAT_DIR"] = hb_dir
        # DONE-marker stamp: a grown attempt re-saving a step dir must not
        # trust a prior same-world attempt's partial commit.
        env["TPU_TRAINER_ATTEMPT"] = str(self.attempt)
        env["TPU_TRAINER_CAPACITY_FILE"] = self.capacity_path
        env.pop("TPU_TRAINER_STANDBY_FILE", None)
        return env

    # --- standby pool ---------------------------------------------------

    def _spawn_standby(self) -> Optional[_Standby]:
        """One warm spare: same module + trainer argv, but parked by
        TPU_TRAINER_STANDBY_FILE before the rendezvous."""
        os.makedirs(self.standby_dir, exist_ok=True)
        slot = self._standby_seq
        self._standby_seq += 1
        file_path = os.path.join(self.standby_dir, f"standby{slot}.json")
        try:
            os.unlink(file_path)
        except OSError:
            pass
        env = dict(self.base_env)
        for key in ("COORDINATOR_ADDRESS", "NUM_PROCESSES", "PROCESS_ID",
                    "TPU_TRAINER_HEARTBEAT_DIR", "TPU_TRAINER_ATTEMPT"):
            env.pop(key, None)
        env["TPU_TRAINER_STANDBY_FILE"] = file_path
        log_path = os.path.join(self.run_dir, f"standby{slot}.log")
        log_file = open(log_path, "w")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 f"tpu_trainer.training.train_{self.mode}",
                 *self.trainer_argv],
                stdout=log_file, stderr=subprocess.STDOUT, env=env,
            )
        except OSError as e:
            log_file.close()
            self._log(f"standby spawn failed ({e}); continuing without")
            return None
        self._log(f"standby {slot}: parked warm spare (pid {proc.pid})")
        return _Standby(slot, proc, file_path, log_path, log_file)

    def _ensure_standbys(self) -> None:
        self._standbys = [s for s in self._standbys if s.alive()]
        while len(self._standbys) < self.standby_hosts:
            sb = self._spawn_standby()
            if sb is None:
                break
            self._standbys.append(sb)

    def _promote(self, host: int, port: int, hb_dir: str) -> Optional[_Child]:
        """Activate a parked spare as rank ``host`` of the new attempt: its
        cold-start (interpreter + imports) is already paid, so the attempt
        reaches the rendezvous sooner — the recovery_seconds cut standbys
        exist for."""
        while self._standbys:
            sb = self._standbys.pop(0)
            if not sb.alive():
                sb.log_file.close()
                continue
            activation = {"env": self._child_env(host, port, hb_dir)}
            tmp = sb.file_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(activation, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, sb.file_path)
            self._log(f"standby {sb.slot}: promoted to rank {host} "
                      f"(attempt {self.attempt})")
            return _Child(host, sb.proc, sb.log_path, sb.log_file)
        return None

    def _retire_standbys(self) -> None:
        for sb in self._standbys:
            if sb.alive():
                try:
                    sb.proc.terminate()
                except OSError:
                    pass
        for sb in self._standbys:
            try:
                sb.proc.wait(timeout=self.term_grace_s)
            except subprocess.TimeoutExpired:
                try:
                    sb.proc.kill()
                except OSError:
                    pass
                sb.proc.wait()
            sb.log_file.close()
        self._standbys = []

    # --- launch / teardown ----------------------------------------------

    def _launch(self) -> List[_Child]:
        port = _free_port()
        hb_dir = self._hb_dir()
        os.makedirs(hb_dir, exist_ok=True)
        children = []
        promoted = 0
        for host in range(self.world):
            child = self._promote(host, port, hb_dir)
            if child is not None:
                promoted += 1
                children.append(child)
                continue
            env = self._child_env(host, port, hb_dir)
            log_path = os.path.join(
                self.run_dir, f"host{host}_attempt{self.attempt}.log")
            log_file = open(log_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 f"tpu_trainer.training.train_{self.mode}",
                 *self.trainer_argv],
                stdout=log_file, stderr=subprocess.STDOUT, env=env,
            )
            children.append(_Child(host, proc, log_path, log_file))
        self._last_promoted = promoted
        self.promoted_total += promoted
        self._log(f"attempt {self.attempt}: launched {self.world} "
                  f"process(es) ({promoted} promoted standby(s)), "
                  f"coordinator 127.0.0.1:{port}, heartbeats {hb_dir}")
        # Replenish the pool AFTER the launch so the next reform also finds
        # warm spares — but on a reform, not before the new attempt's first
        # beat: a fresh spare's interpreter+import startup would contend
        # with the relaunch it is supposed to be cheaper than, inflating
        # the very recovery window the promotion just shortened.
        if self.attempt == 0:
            self._ensure_standbys()
        else:
            self._refill_pending = True
        return children

    def _teardown(self, children: List[_Child],
                  grace_s: Optional[float] = None) -> None:
        grace_s = self.term_grace_s if grace_s is None else grace_s
        for c in children:
            if c.poll() is None:
                try:
                    c.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + grace_s
        for c in children:
            if c.exited is not None:
                continue
            try:
                c.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    c.proc.kill()
                except OSError:
                    pass
                c.proc.wait()
            c.poll()

    def _await_exits(self, children: List[_Child], timeout_s: float) -> None:
        """Wait (bounded) for children that are exiting on their own — the
        coordinated drain path, where every host checkpoints and leaves."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(c.poll() is not None for c in children):
                return
            time.sleep(self.poll_interval_s)

    # --- death detection ------------------------------------------------

    def _check_deaths(self, children: List[_Child], started: float) -> List[dict]:
        """Dead hosts this poll: nonzero exits plus heartbeat flatlines.

        Exit-based deaths are definitive. Staleness needs attribution: a
        single hung host stalls every survivor too (they block inside a
        collective with the silent peer and stop beating shortly after), so
        by detection time *several* beats may be stale. Blaming them all
        would shrink the world to nothing over one wedged host — so among
        stale hosts only the one whose stream flatlined FIRST is declared
        dead; the stalled survivors get a fresh start in the reformed run.
        """
        now = time.time()
        deaths = []
        stale = []
        for c in children:
            rc = c.poll()
            if rc is not None and rc != 0:
                deaths.append({"host": c.host, "cause": f"exit:{rc}",
                               "exit_code": rc})
                continue
            if rc is not None:
                continue  # clean exit: not a death, just done early/waiting
            beat = flight_lib.read_heartbeat(self._hb_dir(), c.host)
            if beat is None:
                if now - started > self.startup_grace_s:
                    deaths.append({"host": c.host, "cause": "startup_timeout",
                                   "exit_code": None})
            elif now - float(beat["unix"]) > self.heartbeat_timeout_s:
                stale.append((float(beat["unix"]),
                              {"host": c.host, "cause": "heartbeat_timeout",
                               "exit_code": None,
                               "step_last_beat": beat.get("step")}))
        if stale:
            deaths.append(min(stale, key=lambda t: t[0])[1])
        return deaths

    def _drain_deaths(self, children: List[_Child],
                      drains: List[dict]) -> List[dict]:
        """Classify a coordinated proactive drain: the noticed host(s) — the
        drain-marker writers — are the 'deaths' the world reforms without;
        peers exiting 143 alongside them are the planned pod-wide drain,
        not crashes. A peer that died some OTHER way in the same window
        (nonzero exit that is neither 143 nor a marker writer) is still a
        real death and is reported as one."""
        drained_hosts = {d["host"] for d in drains}
        deaths = [{"host": d["host"], "cause": d.get("cause",
                                                     "preempt_notice"),
                   "exit_code": None, "proactive": True,
                   "drain_step": d.get("step")}
                  for d in drains]
        for c in children:
            rc = c.poll()
            if (rc is not None and rc not in (0, 143)
                    and c.host not in drained_hosts):
                deaths.append({"host": c.host, "cause": f"exit:{rc}",
                               "exit_code": rc})
        return deaths

    def _first_beat(self) -> Optional[dict]:
        """Earliest beat of the current attempt — the first post-reform
        step, closing any pending recovery/grow windows. The beat record
        carries start_step (the step the attempt resumed at), which is what
        rolled-back accounting needs."""
        best = None
        for host in range(self.world):
            beat = flight_lib.read_heartbeat(self._hb_dir(), host)
            if beat is not None and (best is None
                                     or float(beat["unix"]) < best["unix"]):
                best = {"unix": float(beat["unix"]),
                        "start_step": beat.get("start_step")}
        return best

    def _last_beat_step(self) -> Optional[int]:
        """Newest completed-work watermark of the current attempt (max beat
        step across hosts), read before reforming away from it."""
        best = None
        for host in range(self.world):
            beat = flight_lib.read_heartbeat(self._hb_dir(), host)
            if beat is not None and beat.get("step") is not None:
                step = int(beat["step"])
                best = step if best is None else max(best, step)
        return best

    # --- the loop -------------------------------------------------------

    def run(self) -> int:
        pending: List[dict] = []  # reform windows awaiting the 1st new beat
        if self.metrics_port is not None:
            from tpu_trainer.obs.http import MetricsServer

            self._metrics_server = MetricsServer(
                self.registry, port=self.metrics_port,
                statusz_fn=self.statusz)
            self._log(f"metrics: serving {self._metrics_server.url}/metrics")
        # The pool is first filled by _launch AFTER attempt 0 is up: the
        # first attempt's ranks gain nothing from spares (everyone is
        # equally cold), but every reform after it does.
        try:
            return self._run_loop(pending)
        finally:
            self._retire_standbys()
            if self._metrics_server is not None:
                self._metrics_server.close()
                self._metrics_server = None

    def _run_loop(self, pending: List[dict]) -> int:
        while True:
            started = time.time()
            children = self._launch()
            try:
                result = self._watch(children, started, pending)
                if result["outcome"] == "grow":
                    # Graceful drain: SIGTERM rides the trainer's preemption
                    # path — checkpoint at the step boundary, exit 143 — so
                    # the grown attempt resumes with zero lost steps.
                    self._teardown(children, grace_s=self.drain_grace_s)
            finally:
                self._teardown(children)
            # Windows that never saw a beat (the reformed attempt died
            # first) are superseded by the new reform's window.
            pending = []
            if result["outcome"] == "done":
                self._finish(0)
                return 0
            last_beat = self._last_beat_step()

            if result["outcome"] == "grow":
                target = result["target"]
                granted = target - self.world
                preemption_lib.consume_capacity(self.capacity_path, granted)
                self.grows += 1
                self.attempt += 1
                pending.append({
                    "kind": "world_grow",
                    "grow": self.grows,
                    "world_before": self.world,
                    "world_after": target,
                    "granted_hosts": granted,
                    "detected_unix": result["detected_unix"],
                    "step_last_beat": last_beat,
                })
                self.world = target
                self._log(f"capacity re-granted: growing to {target} "
                          f"host(s) (grow {self.grows})")
                continue

            deaths = result["deaths"]
            detected = result["detected_unix"]
            for d in deaths:
                self._emit(dict(d, kind="host_death", attempt=self.attempt,
                                detected_unix=detected))
                self._log(f"host {d['host']} dead ({d['cause']})")
            new_world = self.world - len(deaths)
            if self.restarts >= self.max_restarts:
                self._log(f"restart budget exhausted "
                          f"({self.restarts}/{self.max_restarts}); giving up")
                self._finish(1)
                return 1
            if new_world < self.min_processes:
                self._log(f"only {new_world} host(s) left "
                          f"(< min_processes={self.min_processes}); giving up")
                self._finish(1)
                return 1
            self.restarts += 1
            self.attempt += 1
            window = {
                "kind": "recovery",
                "restart": self.restarts,
                "world_before": self.world,
                "world_after": new_world,
                "dead_hosts": [d["host"] for d in deaths],
                "cause": deaths[0]["cause"],
                "proactive": bool(deaths[0].get("proactive")),
                "detected_unix": detected,
                "step_last_beat": last_beat,
            }
            # Returned capacity can rejoin at reform time too — a host that
            # came back while this attempt was dying need not wait for the
            # next grow probe.
            if self.allow_grow and new_world < self.desired_world:
                extra = min(self.desired_world - new_world,
                            preemption_lib.read_capacity(self.capacity_path))
                if extra > 0:
                    preemption_lib.consume_capacity(self.capacity_path, extra)
                    new_world += extra
                    window["regrown_at_reform"] = extra
                    self._log(f"reform absorbs {extra} re-granted host(s)")
            window["world_after"] = new_world
            pending.append(window)
            self.world = new_world
            self._log(f"reforming on {self.world} host(s) "
                      f"(restart {self.restarts}/{self.max_restarts})")

    def _close_windows(self, pending: List[dict]) -> None:
        first = self._first_beat()
        if first is None:
            return
        for win in pending:
            win = dict(win)
            detected = win.pop("detected_unix")
            seconds = max(0.0, first["unix"] - detected)
            last = win.pop("step_last_beat", None)
            rolled_back = None
            if last is not None and first.get("start_step") is not None:
                # Beats record step+1 after completing a step; start_step is
                # where the new attempt resumed. Work past the resume point
                # was re-done: a clean proactive drain scores exactly 0.
                rolled_back = max(0, int(last) - int(first["start_step"]))
            if win["kind"] == "world_grow":
                rec = dict(win, detected_unix=detected,
                           first_step_unix=first["unix"],
                           grow_seconds=seconds,
                           rolled_back_steps=rolled_back)
                self.ledger.add("grow", seconds)
                self._emit(rec)
                self._log(f"grew to {win['world_after']} host(s) in "
                          f"{seconds:.1f}s (rolled back "
                          f"{rolled_back if rolled_back is not None else '?'}"
                          f" step(s))")
            else:
                rec = dict(win, detected_unix=detected,
                           first_step_unix=first["unix"],
                           recovery_seconds=seconds,
                           rolled_back_steps=rolled_back,
                           promoted_standbys=self._last_promoted,
                           cold_starts=self.world - self._last_promoted)
                self.ledger.add("recovery", seconds)
                self._emit(rec)
                self._log(f"recovered in {seconds:.1f}s "
                          f"(restart {rec['restart']}, world "
                          f"{rec['world_before']}→{rec['world_after']}, "
                          f"{self._last_promoted} standby promotion(s))")
        pending.clear()

    def _watch(self, children: List[_Child], started: float,
               pending: List[dict]) -> dict:
        """Poll until every child exits cleanly (outcome "done"), a death or
        proactive drain is detected (outcome "death"), or — with
        --allow_grow below the desired world — a capacity grant is found
        (outcome "grow"). Also closes pending recovery/grow windows at the
        attempt's first heartbeat."""
        last_probe = time.monotonic()
        while True:
            if pending:
                self._close_windows(pending)
            if not pending and self._refill_pending:
                # The reformed attempt has beaten (or never had a window):
                # now it is safe to spend cycles warming fresh spares. An
                # attempt that dies before its first beat leaves the flag
                # set; the next reform simply finds fewer warm spares.
                self._ensure_standbys()
                self._refill_pending = False
            drains = flight_lib.read_drains(self._hb_dir())
            if drains:
                # Coordinated proactive drain: every host is checkpointing
                # and leaving on its own — let them, bounded.
                self._await_exits(children, self.drain_grace_s)
                return {"outcome": "death",
                        "deaths": self._drain_deaths(children, drains),
                        "detected_unix": time.time()}
            deaths = self._check_deaths(children, started)
            if deaths:
                # Settle window: collect co-dying hosts (one teardown + one
                # restart for two hosts lost in the same interval) and any
                # drain marker still in flight from an exiting host.
                time.sleep(self.death_settle_s)
                drains = flight_lib.read_drains(self._hb_dir())
                if drains:
                    self._await_exits(children, self.drain_grace_s)
                    return {"outcome": "death",
                            "deaths": self._drain_deaths(children, drains),
                            "detected_unix": time.time()}
                seen = {d["host"] for d in deaths}
                for d in self._check_deaths(children, started):
                    if d["host"] not in seen:
                        deaths.append(d)
                        seen.add(d["host"])
                return {"outcome": "death", "deaths": deaths,
                        "detected_unix": time.time()}
            if all(c.poll() is not None for c in children):
                # All zero (nonzero would have been a death above).
                return {"outcome": "done"}
            if (self.allow_grow and self.world < self.desired_world
                    and time.monotonic() - last_probe
                    >= self.grow_probe_interval_s):
                last_probe = time.monotonic()
                granted = preemption_lib.read_capacity(self.capacity_path)
                if granted > 0:
                    target = min(self.desired_world, self.world + granted)
                    self._log(f"grow probe: {granted} host(s) available, "
                              f"draining to relaunch at {target}")
                    return {"outcome": "grow", "target": target,
                            "detected_unix": time.time()}
            time.sleep(self.poll_interval_s)

    def _finish(self, exit_code: int) -> None:
        self._emit({
            "kind": "elastic_summary",
            "restarts": self.restarts,
            "grows": self.grows,
            "final_world": self.world,
            "desired_world": self.desired_world,
            "allow_grow": self.allow_grow,
            "standby_hosts": self.standby_hosts,
            "standby_promotions": self.promoted_total,
            "exit_code": exit_code,
            "recovery_seconds_total": self.ledger.seconds("recovery"),
            "grow_seconds_total": self.ledger.seconds("grow"),
        })
        self._emit(self.ledger.record(final=True))
        self._log(f"summary: {self.restarts} restart(s), {self.grows} "
                  f"grow(s), final world {self.world}/{self.desired_world}, "
                  f"recovery {self.ledger.seconds('recovery'):.1f}s + grow "
                  f"{self.ledger.seconds('grow'):.1f}s total")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_trainer.training.elastic",
        description="Elastic run supervisor: launch N trainer processes, "
                    "watch heartbeats/exits, restart on the surviving host "
                    "set from the last committed checkpoint — and, with "
                    "--allow_grow, re-expand to the desired world when "
                    "capacity returns. Trainer flags go after '--'.",
    )
    p.add_argument("--num_processes", type=int, required=True)
    p.add_argument("--run_dir", type=str, required=True,
                   help="supervisor state: heartbeats, per-host logs, "
                        "capacity.json, supervisor.jsonl (the trainer's "
                        "--checkpoint_dir is its own flag, after '--')")
    p.add_argument("--mode", choices=["ddp", "fsdp"], default="ddp")
    p.add_argument("--max_restarts", type=int, default=2)
    p.add_argument("--min_processes", type=int, default=1)
    p.add_argument("--heartbeat_timeout_s", type=float, default=30.0)
    p.add_argument("--startup_grace_s", type=float, default=300.0,
                   help="allowance before the first beat of an attempt "
                        "(jax import + compile); only then does beat "
                        "absence count as a hang")
    p.add_argument("--poll_interval_s", type=float, default=0.2)
    p.add_argument("--coordinator_timeout_s", type=float, default=60.0)
    p.add_argument("--term_grace_s", type=float, default=5.0,
                   help="teardown grace between SIGTERM and SIGKILL — raise "
                        "on slow filesystems where children need longer to "
                        "flush logs")
    p.add_argument("--allow_grow", action="store_true",
                   help="re-expand toward --num_processes when "
                        "<run_dir>/capacity.json grants hosts back (written "
                        "by a cluster agent or the return_host fault)")
    p.add_argument("--grow_probe_interval_s", type=float, default=5.0,
                   help="seconds between capacity probes while running "
                        "below the desired world")
    p.add_argument("--standby_hosts", type=int, default=0,
                   help="warm spares parked before the rendezvous; reforms "
                        "promote them instead of paying process cold-start")
    p.add_argument("--drain_grace_s", type=float, default=60.0,
                   help="budget for a graceful drain (grow relaunch or "
                        "preemption notice) to checkpoint and exit before "
                        "SIGKILL")
    p.add_argument("--death_settle_s", type=float, default=1.0,
                   help="coalescing window after the first detected death "
                        "so same-interval co-deaths cost one restart")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve the supervisor's live /metrics + /healthz + "
                        "/statusz (attempt/world/restart/grow/standby "
                        "state) on this port; 0 = ephemeral")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        sup_argv, trainer_argv = argv[:split], argv[split + 1:]
    else:
        sup_argv, trainer_argv = argv, []
    args = build_parser().parse_args(sup_argv)
    sup = Supervisor(
        trainer_argv,
        num_processes=args.num_processes,
        run_dir=args.run_dir,
        mode=args.mode,
        max_restarts=args.max_restarts,
        min_processes=args.min_processes,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        startup_grace_s=args.startup_grace_s,
        poll_interval_s=args.poll_interval_s,
        coordinator_timeout_s=args.coordinator_timeout_s,
        term_grace_s=args.term_grace_s,
        allow_grow=args.allow_grow,
        grow_probe_interval_s=args.grow_probe_interval_s,
        standby_hosts=args.standby_hosts,
        drain_grace_s=args.drain_grace_s,
        death_settle_s=args.death_settle_s,
        metrics_port=args.metrics_port,
    )
    return sup.run()


if __name__ == "__main__":
    raise SystemExit(main())
