"""Optimizer construction (SURVEY.md C13/C14/C16).

optax chain: global-norm clip → AdamW with masked weight decay. Decay-mask
semantics follow the reference's *grouped* DDP optimizer
(``ddp_trainer.py:174-234``): parameters whose path mentions a norm layer (or
a bias, if one existed) are excluded from weight decay; everything else —
including the embedding — decays. The reference's FSDP trainer decays
everything (``fsdp_trainer.py:334-343`` — SURVEY.md §2.1 b5); the grouped
behavior is used everywhere here, as the survey prescribes.

Under GSPMD the optimizer is sharding-agnostic: the same chain runs
replicated (DDP), with sharded moments (ZeRO-2), or fully sharded (ZeRO-3) —
the global-norm clip's tree reduction becomes a partial-reduce + psum
automatically.
"""

from __future__ import annotations

from typing import Any

import jax
import optax

from tpu_trainer.training.config import TrainingConfig

_NO_DECAY_MARKERS = ("norm", "bias")


def decay_mask(params: Any) -> Any:
    """True where weight decay applies.

    Name-based, matching the reference's exclusion of params whose name
    contains 'bias' or 'norm' (``ddp_trainer.py:216-227``): our RMSNorm
    modules are named ``*norm*`` and their weight vectors are excluded; the
    projections and the (tied) embedding decay.
    """

    def keep(path, _leaf) -> bool:
        keys = [str(getattr(p, "key", getattr(p, "name", ""))) for p in path]
        return not any(marker in k.lower() for k in keys for marker in _NO_DECAY_MARKERS)

    return jax.tree_util.tree_map_with_path(keep, params)


def make_optimizer(config: TrainingConfig) -> optax.GradientTransformation:
    """clip_by_global_norm → AdamW(masked decay), at unit learning rate.

    The chain is built with ``learning_rate=1.0``; the trainer scales the
    final updates by ``config.lr_at(state.step)`` itself. This keeps the
    schedule a pure function of the trainer's step counter — including across
    fp16 overflow-skipped steps, where torch semantics are "scheduler
    advances, Adam's bias-correction count does not" (GradScaler skips
    ``optimizer.step`` while the LR scheduler still ticks). AdamW's decoupled
    decay is inside the chain, so the external scaling applies
    ``p -= lr * (adam_update + wd * p)`` exactly like torch AdamW.
    """
    return optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adamw(
            learning_rate=1.0,
            b1=config.beta1,
            b2=config.beta2,
            eps=1e-8,
            weight_decay=config.weight_decay,
            mask=decay_mask,
        ),
    )
