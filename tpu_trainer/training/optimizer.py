"""Optimizer construction (SURVEY.md C13/C14/C16).

optax chain: global-norm clip → AdamW with masked weight decay. Decay-mask
semantics follow the reference's *grouped* DDP optimizer
(``ddp_trainer.py:174-234``): parameters whose path mentions a norm layer (or
a bias, if one existed) are excluded from weight decay; everything else —
including the embedding — decays. The reference's FSDP trainer decays
everything (``fsdp_trainer.py:334-343`` — SURVEY.md §2.1 b5); the grouped
behavior is used everywhere here, as the survey prescribes.

Under GSPMD the optimizer is sharding-agnostic: the same chain runs
replicated (DDP), with sharded moments (ZeRO-2), or fully sharded (ZeRO-3) —
the global-norm clip's tree reduction becomes a partial-reduce + psum
automatically.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from tpu_trainer.training.config import TrainingConfig
from tpu_trainer.utils.quant import (
    QuantPack,
    dequantize_blockwise_int8,
    quantize_blockwise_int8,
)

_NO_DECAY_MARKERS = ("norm", "bias")

# Leaves below this size stay f32 in the quantized-state modes: the HBM win
# is negligible and small vectors (norm gains) are where quantization noise
# would bite hardest.
_QUANT_MIN_SIZE = 65536


def decay_mask(params: Any) -> Any:
    """True where weight decay applies.

    Name-based, matching the reference's exclusion of params whose name
    contains 'bias' or 'norm' (``ddp_trainer.py:216-227``): our RMSNorm
    modules are named ``*norm*`` and their weight vectors are excluded; the
    projections and the (tied) embedding decay.
    """

    def keep(path, _leaf) -> bool:
        keys = [str(getattr(p, "key", getattr(p, "name", ""))) for p in path]
        return not any(marker in k.lower() for k in keys for marker in _NO_DECAY_MARKERS)

    return jax.tree_util.tree_map_with_path(keep, params)


class ScaleByAdamQState(NamedTuple):
    """Adam state with narrow-dtype moments (``optimizer_state_dtype``)."""

    count: jax.Array  # int32 scalar
    mu: Any           # per-leaf: f32 array | bf16 array | int8 QuantPack
    nu: Any


def _q_eligible(p: jax.Array) -> bool:
    return p.ndim >= 2 and p.size >= _QUANT_MIN_SIZE


def _store_moment(x: jax.Array, state_dtype: str, *, nonneg: bool):
    if state_dtype == "int8":
        return quantize_blockwise_int8(x, nonneg=nonneg)
    return x.astype(jnp.bfloat16)


def _load_moment(packed, shape, *, nonneg: bool) -> jax.Array:
    if isinstance(packed, QuantPack):
        return dequantize_blockwise_int8(packed, shape, jnp.float32,
                                         nonneg=nonneg)
    return packed.astype(jnp.float32)


def scale_by_adam_quantized(
    b1: float, b2: float, eps: float, state_dtype: str
) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with moments stored narrow in HBM.

    Large leaves (ndim >= 2, >= 64k elements) hold ``mu``/``nu`` in
    ``state_dtype`` — ``"bfloat16"`` (a straight cast; one rounding per
    step) or ``"int8"`` (blockwise-absmax, ``nu`` in sqrt-space — see
    ``utils/quant.py``); small leaves stay exact f32. The update math is
    bitwise the optax recipe on the dequantized moments: the only delta vs
    ``optax.scale_by_adam`` is the store/load rounding.

    Why: the Adam update fusions are pure HBM traffic (~28 B/param/step at
    f32 state) and the single biggest slice of the MoE step on one chip
    (~31 ms of 108 at E=8 — the optimizer pays for every expert while
    per-token compute pays only for the active ones). int8 moments cut
    ~12 B/param/step. The same tradeoff as the 8-bit offload state, on
    device; the reference has no analogue (fp32 ``torch.optim.AdamW``,
    ``ddp_trainer.py:174-234``).
    """

    def init_fn(params):
        def zero_state(p, *, nonneg):
            if _q_eligible(p):
                return _store_moment(jnp.zeros(p.shape, jnp.float32),
                                     state_dtype, nonneg=nonneg)
            return jnp.zeros(p.shape, jnp.float32)

        return ScaleByAdamQState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(
                lambda p: zero_state(p, nonneg=False), params),
            nu=jax.tree_util.tree_map(
                lambda p: zero_state(p, nonneg=True), params),
        )

    def update_fn(updates, state, params=None):
        del params
        count_inc = optax.safe_int32_increment(state.count)
        c1 = 1.0 - b1 ** count_inc.astype(jnp.float32)
        c2 = 1.0 - b2 ** count_inc.astype(jnp.float32)

        # Flatten against the GRADS' structure: a quantized moment is a
        # QuantPack node where the grads have an array leaf, so the moment
        # trees flatten with an is-leaf predicate on the pack TYPE. A
        # params subtree that happened to use the keys {"q", "scale"}
        # cannot be mistaken for a pack and misalign this positional zip.
        is_pack = lambda x: isinstance(x, QuantPack)  # noqa: E731
        g_leaves, treedef = jax.tree_util.tree_flatten(updates)
        mu_leaves = jax.tree_util.tree_flatten(state.mu, is_leaf=is_pack)[0]
        nu_leaves = jax.tree_util.tree_flatten(state.nu, is_leaf=is_pack)[0]

        out_l, mu_l, nu_l = [], [], []
        for g, mu_s, nu_s in zip(g_leaves, mu_leaves, nu_leaves):
            g32 = g.astype(jnp.float32)
            mu = b1 * _load_moment(mu_s, g.shape, nonneg=False) \
                + (1 - b1) * g32
            nu = b2 * _load_moment(nu_s, g.shape, nonneg=True) \
                + (1 - b2) * (g32 * g32)
            out_l.append((mu / c1) / (jnp.sqrt(nu / c2) + eps))
            narrow = _q_eligible(g)
            mu_l.append(_store_moment(mu, state_dtype, nonneg=False)
                        if narrow else mu)
            nu_l.append(_store_moment(nu, state_dtype, nonneg=True)
                        if narrow else nu)

        unflatten = treedef.unflatten
        return unflatten(out_l), ScaleByAdamQState(
            count=count_inc, mu=unflatten(mu_l), nu=unflatten(nu_l)
        )

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(config: TrainingConfig) -> optax.GradientTransformation:
    """clip_by_global_norm → AdamW(masked decay), at unit learning rate.

    The chain is built with ``learning_rate=1.0``; the trainer scales the
    final updates by ``config.lr_at(state.step)`` itself. This keeps the
    schedule a pure function of the trainer's step counter — including across
    fp16 overflow-skipped steps, where torch semantics are "scheduler
    advances, Adam's bias-correction count does not" (GradScaler skips
    ``optimizer.step`` while the LR scheduler still ticks). AdamW's decoupled
    decay is inside the chain, so the external scaling applies
    ``p -= lr * (adam_update + wd * p)`` exactly like torch AdamW.
    """
    if config.optimizer_state_dtype != "float32":
        if config.optimizer_state_dtype not in ("bfloat16", "int8"):
            raise ValueError(
                f"optimizer_state_dtype {config.optimizer_state_dtype!r} "
                "not supported; choose float32, bfloat16, or int8"
            )
        # Same chain with narrow-moment Adam: scale_by_adam_quantized +
        # decoupled decay + descent-sign scale == optax.adamw(lr=1.0)
        # modulo the moment store/load rounding.
        return optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            scale_by_adam_quantized(
                config.beta1, config.beta2, 1e-8,
                config.optimizer_state_dtype,
            ),
            optax.add_decayed_weights(config.weight_decay, mask=decay_mask),
            optax.scale(-1.0),
        )
    return optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adamw(
            learning_rate=1.0,
            b1=config.beta1,
            b2=config.beta2,
            eps=1e-8,
            weight_decay=config.weight_decay,
            mask=decay_mask,
        ),
    )
