"""Training CLI: the shared main behind ``train_ddp`` and ``train_fsdp``.

TPU-native re-design of the reference's two ``main()`` entry points
(``/root/reference/src/training/ddp_trainer.py:490-625``,
``.../fsdp_trainer.py:530-616``), unified into one driver (the
``trainer_utils`` layer the reference promised but never wrote —
SURVEY.md §0.1). Differences by design:

- **YAML configs are actually loaded.** The reference documents
  ``--config configs/small_model.yaml`` but defines no such flag
  (SURVEY.md §0.1); here ``--config`` parses the same YAML schema
  (``/root/reference/configs/small_model.yaml``) into the dataclasses, with
  CLI flags taking precedence over YAML over defaults.
- **Resume is wired.** ``--resume_from`` restores a checkpoint; with no flag,
  the latest checkpoint under ``--checkpoint_dir`` is auto-restored (the
  reference's ``resume_from`` was dead config and ``load_checkpoint`` was
  never called — SURVEY.md §5.3).
- **Preemption handling.** SIGTERM (routine on TPU pools) checkpoints at the
  next step boundary and exits cleanly.
- **A real eval loop.** ``eval_interval`` triggers forward-only loss
  evaluation (the reference declares the field but has no eval loop anywhere
  — SURVEY.md §0.1).

Flag parity: every reference flag is accepted (DDP set,
``ddp_trainer.py:494-510``; FSDP set incl. ``--sharding``/``--cpu_offload``/
``--no_activation_checkpointing``, ``fsdp_trainer.py:531-538``).
"""

from __future__ import annotations

import argparse
import dataclasses
import signal
import time
from typing import Optional

import numpy as np

from tpu_trainer.data.device_prefetch import DevicePrefetcher
from tpu_trainer.models.config import GPTConfig
from tpu_trainer.parallel import comms_model as comms_lib
from tpu_trainer.parallel import mesh as mesh_lib
from tpu_trainer.parallel import planner as planner_lib
from tpu_trainer.training.config import TrainingConfig
from tpu_trainer.training.trainer import (
    _MP_TO_DTYPE, ParallelConfig, RecompileWatchdog, Trainer,
)
from tpu_trainer.utils import checkpoint as ckpt_lib
from tpu_trainer.utils import faults, guards, profiling
from tpu_trainer.utils import preemption as preemption_lib
from tpu_trainer.utils import flight_recorder as flight_lib
from tpu_trainer.utils import telemetry as telemetry_lib
from tpu_trainer.utils.logging import MetricLogger, flops_per_token

# Steps between cross-host preemption votes (each vote is a collective, so
# it must run at a cadence every host reaches at the same step).
_PREEMPT_VOTE_INTERVAL = 10

# Steps a metric future may stay in flight before the host materializes it
# (utils/telemetry.py DeferredFetcher): by fetch time the device has long
# finished that step, so the device_get returns ~immediately; the spike
# detector and NaN guards see values this many steps late, which recovery
# (bounded by checkpoint cadence, not by the window) absorbs.
_DEFERRED_SYNC_WINDOW = 2


def _nan_loss_transform(metrics: dict) -> dict:
    """Injected-fault mutation, applied to the *fetched* host copy at
    maturity — the live metrics are still in flight on device when the
    fault fires."""
    metrics = dict(metrics)
    metrics["loss"] = float("nan")
    return metrics


def _loss_spike_transform(metrics: dict) -> dict:
    # Large but finite: the early-warning path must engage before anything
    # trips the NaN guard.
    metrics = dict(metrics)
    metrics["loss"] = float(metrics["loss"]) * 8.0 + 5.0
    return metrics

_SHARDING_CHOICES = [
    "FULL_SHARD", "SHARD_GRAD_OP", "NO_SHARD", "HYBRID_SHARD",
    "zero3", "zero2", "replicated", "ddp",
]

# Optimizer-state storage dtypes (host-offloaded: trainer.py _offload_*;
# on-device: optimizer.py scale_by_adam_quantized). Shared between the
# argparse choices and the YAML validation below — any other string would
# flow into jnp.dtype() as a silently-corrupting storage cast (e.g. int16
# truncates Adam moments to zero).
_OFFLOAD_DTYPES = ["float32", "bfloat16", "int8"]


def _require_choice(value, choices, name):
    if value not in choices:
        raise SystemExit(
            f"{name} {value!r} not supported; choose one of {choices}"
        )
    return value


def build_parser(mode: str) -> argparse.ArgumentParser:
    """Argument parser; defaults are ``None`` sentinels so that explicit CLI
    flags can be layered over YAML over dataclass defaults."""
    p = argparse.ArgumentParser(
        description=f"TPU-native GPT training ({mode})",
    )
    p.add_argument("--config", type=str, default=None,
                   help="YAML config (reference configs/*.yaml schema)")
    # model (reference ddp_trainer.py:495-499)
    p.add_argument("--model_size", type=str, default=None,
                   choices=["small", "medium", "large", "xl"])
    p.add_argument("--seq_len", type=int, default=None)
    p.add_argument("--gradient_checkpointing", action="store_true", default=None)
    p.add_argument("--no_flash_attention", action="store_true", default=None)
    # training (reference ddp_trainer.py:496-502)
    p.add_argument("--batch_size", type=int, default=None,
                   help="per-data-shard micro-batch size")
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--learning_rate", type=float, default=None)
    p.add_argument("--warmup_steps", type=int, default=None)
    p.add_argument("--grad_accum", "--gradient_accumulation_steps",
                   dest="grad_accum", type=int, default=None)
    p.add_argument("--mixed_precision", type=str, default=None,
                   choices=["fp32", "bf16", "fp16"])
    # data (reference ddp_trainer.py:503-510)
    p.add_argument("--dataset", type=str, default=None,
                   choices=["dummy", "tinystories", "openwebtext"])
    p.add_argument("--data_path", type=str, default=None)
    p.add_argument("--max_tokens", type=int, default=None)
    p.add_argument("--streaming", action="store_true", default=None)
    p.add_argument("--pack_sequences", action="store_true", default=None,
                   help="first-fit sequence packing: ragged documents share "
                        "rows, a segment-id channel keeps attention and "
                        "loss per-document (data/packing.py); batches are "
                        "[rows, seq, 2] (tokens, segment ids)")
    p.add_argument("--max_open_bins", type=int, default=None,
                   help="packing: max simultaneously open bins before the "
                        "oldest is flushed (default 8)")
    p.add_argument("--pack_strategy", type=str, default=None,
                   choices=("first_fit", "best_fit"),
                   help="packing bin selection: first_fit (stream order) or "
                        "best_fit (best-fit-decreasing over a lookahead "
                        "window — fewer stranded bin tails; default "
                        "first_fit)")
    p.add_argument("--mask_doc_boundaries", action="store_true", default=None,
                   help="concatenating text stream: derive segment ids from "
                        "EOS positions so attention/loss never leak across "
                        "documents (default off — bit-compat with runs "
                        "checkpointed on the leaky stream)")
    p.add_argument("--data_mixture", type=str, default=None,
                   help="weighted multi-source mixture "
                        "'name:weight[:path],...', e.g. 'tinystories:0.7:"
                        "ts.txt,dummy:0.3'; names from {dummy, tinystories, "
                        "openwebtext}; overrides --dataset (data/mixture.py)")
    p.add_argument("--cache_max_tokens", type=int, default=None)
    p.add_argument("--num_workers", type=int, default=None,
                   help="streaming tokenizer thread-pool size (0 = inline; "
                        "reference DataLoader num_workers)")
    p.add_argument("--prefetch", "--prefetch_depth", dest="prefetch",
                   type=int, default=None,
                   help="host-side prefetch depth: batches assembled ahead "
                        "on a background thread (0 disables the host "
                        "input/compute overlap)")
    p.add_argument("--device_prefetch_depth", type=int, default=None,
                   help="batches placed on device (with the batch sharding) "
                        "ahead of the step so H2D copies ride under compute "
                        "(default 2; 0 places inside the step)")
    p.add_argument("--no_async_checkpointing", action="store_true",
                   default=None,
                   help="commit interval checkpoints synchronously in the "
                        "step loop instead of snapshotting to host and "
                        "writing on a background thread")
    p.add_argument("--num_batches", type=int, default=None,
                   help="dummy-dataset corpus size in batches")
    p.add_argument("--tokenizer", type=str, default=None)
    # schedule / logging / checkpointing
    p.add_argument("--log_interval", type=int, default=None)
    p.add_argument("--eval_interval", type=int, default=None)
    p.add_argument("--eval_batches", type=int, default=None)
    p.add_argument("--eval_split", type=float, default=None,
                   help="held-out tail fraction of map-style text chunks "
                        "(default 0.02; 0 disables eval on text datasets)")
    p.add_argument("--eval_holdout_every", type=int, default=None,
                   help="streaming: reserve every N-th line for eval "
                        "(0 = no streaming eval)")
    p.add_argument("--save_interval", type=int, default=None)
    p.add_argument("--checkpoint_dir", type=str, default=None)
    p.add_argument("--resume_from", type=str, default=None)
    p.add_argument("--no_auto_resume", action="store_true", default=None)
    p.add_argument("--keep_last_n", type=int, default=None,
                   help="checkpoint GC: keep only the newest N completed "
                        "checkpoints (0 = keep all)")
    # fault tolerance (divergence rollback; utils/checkpoint.py hardening)
    p.add_argument("--max_rollbacks", type=int, default=None,
                   help="on a non-finite loss or cross-host divergence, "
                        "rewind to the last good checkpoint and retry up to "
                        "this many times before failing (0 = crash at once)")
    p.add_argument("--skip_batches_on_rollback", type=int, default=None,
                   help="on rollback, fast-forward the data stream this many "
                        "batches past the batch that diverged (0 = replay "
                        "the same data and rely on the LR backoff)")
    p.add_argument("--rollback_lr_backoff", type=float, default=None,
                   help="multiply the peak LR by this factor on each "
                        "rollback (1.0 disables the backoff)")
    p.add_argument("--inject_fault", type=str, default=None,
                   help="debug: deterministic fault injection, "
                        "'kind@step[,kind@step...]' — kinds: nan_loss, "
                        "loss_spike, kill, kill_in_save, truncate_meta, "
                        "corrupt_shard, sigterm, kill_host, hang_host, "
                        "preempt_notice, return_host (utils/faults.py)")
    p.add_argument("--preemption_grace_s", type=float, default=None,
                   help="hard deadline (seconds) for the SIGTERM exit path: "
                        "drain the in-flight async save and take the final "
                        "checkpoint within this budget, exiting 143 even if "
                        "the save had to be abandoned (0 = wait "
                        "indefinitely, the pre-elastic behavior)")
    p.add_argument("--preempt_notice", type=str, default=None,
                   help="proactive preemption notice source "
                        "(utils/preemption.py): 'file:<path>', an http(s) "
                        "GCE-metadata-shaped URL, or 'metadata' (the real "
                        "GCE endpoint). A received notice drains at the "
                        "next step boundary — checkpoint, deregister, exit "
                        "143 — before the kill lands. SIGTERM stays the "
                        "always-on fallback")
    p.add_argument("--preempt_notice_poll_s", type=float, default=None,
                   help="throttle for probing the notice source "
                        "(default 1.0s; the HTTP probe is a network "
                        "round-trip on the step path)")
    p.add_argument("--preempt_vote_interval", type=int, default=None,
                   help="steps between cross-host preemption/notice votes "
                        "on multi-process runs (each vote is a collective; "
                        "default 10). Single-process runs vote every step")
    p.add_argument("--metrics_jsonl", type=str, default=None)
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve live /metrics + /healthz + /statusz on this "
                        "port (0 = ephemeral; host 0 only; off by default "
                        "— records and streams are identical either way)")
    p.add_argument("--wandb_project", type=str, default=None,
                   help="log metrics to Weights & Biases (import-guarded)")
    p.add_argument("--tensorboard_dir", type=str, default=None,
                   help="log metrics to TensorBoard event files")
    p.add_argument("--seed", type=int, default=None)
    # profiling (SURVEY.md §5.1) and numerics/divergence guards (§5.2)
    p.add_argument("--profile_dir", type=str, default=None,
                   help="capture a jax.profiler trace window to this dir")
    p.add_argument("--profile_start", type=int, default=None,
                   help="first traced step (default 5; lets compile pass)")
    p.add_argument("--profile_steps", type=int, default=None,
                   help="number of steps to trace (default 5)")
    p.add_argument("--guard_interval", type=int, default=None,
                   help="steps between finite-loss + cross-host sync checks "
                        "(default 100; 0 disables)")
    # telemetry / goodput / early warning (utils/telemetry.py)
    p.add_argument("--telemetry_interval", type=int, default=None,
                   help="steps between in-graph telemetry steps (per-layer "
                        "grad/param/update norms, activation RMS/absmax, MoE "
                        "router health — a second compiled step variant, so "
                        "steps in between pay nothing; default 0 = off)")
    p.add_argument("--spike_sigma", type=float, default=None,
                   help="loss-spike early warning: raise (and roll back) when "
                        "the logged loss exceeds the rolling median by this "
                        "many MAD-sigmas (default 6; 0 disables)")
    # run anatomy (ISSUE 3): comms model, recompile watchdog, flight recorder
    p.add_argument("--no_comms_model", action="store_true", default=None,
                   help="skip the one-time kind:\"comms_model\" record "
                        "(analytic per-axis collective bytes/step + "
                        "comms-vs-compute roofline, cross-checked against "
                        "the compiled HLO)")
    p.add_argument("--flight_recorder_steps", type=int, default=None,
                   help="crash flight recorder: ring-buffer capacity of "
                        "recent JSONL records dumped (with a config/mesh/env "
                        "snapshot) as crash_report.json under "
                        "--checkpoint_dir on SIGTERM/rollback/crash "
                        "(default 256; 0 disables)")
    p.add_argument("--nan_scan", action="store_true", default=None,
                   help="debug: run one forward-only activation scan on the "
                        "first batch, report the first layer/site with a "
                        "non-finite value, and exit without training")
    # mesh / multi-host
    p.add_argument("--mesh", type=str, default=None, choices=["auto"],
                   help="'auto' runs the mesh auto-planner at startup "
                        "(parallel/planner.py): enumerate every feasible "
                        "data x fsdp x sequence x tensor x expert x stage "
                        "split, score with the analytic comms + roofline "
                        "model, log a kind:\"mesh_plan\" record, and train "
                        "on the winner. Mutually exclusive with explicit "
                        "--mesh_* flags.")
    p.add_argument("--hbm_gb", type=float, default=None,
                   help="per-device HBM budget in GiB for --mesh auto "
                        "pruning (default: the device's reported limit; "
                        "no pruning on CPU)")
    p.add_argument("--mesh_data", type=int, default=None)
    p.add_argument("--mesh_fsdp", type=int, default=None)
    p.add_argument("--mesh_sequence", type=int, default=None,
                   help="ring-attention sequence-parallel axis size")
    p.add_argument("--mesh_tensor", type=int, default=None)
    p.add_argument("--mesh_expert", type=int, default=None,
                   help="expert-parallel axis size (MoE models)")
    p.add_argument("--mesh_stage", type=int, default=None,
                   help="pipeline-parallel stage axis size (GPipe schedule)")
    p.add_argument("--pipeline_microbatches", type=int, default=None,
                   help="GPipe microbatches per step (default: one per stage)")
    p.add_argument("--num_experts", type=int, default=None,
                   help="> 0 turns every block's FFN into a routed MoE")
    p.add_argument("--moe_impl", type=str, default=None,
                   choices=["capacity", "dropless"],
                   help="MoE routing discipline: fixed-capacity slots with "
                        "token dropping, or dropless grouped-matmul experts")
    p.add_argument("--num_kv_heads", type=int, default=None,
                   help="grouped-query attention: K/V heads (< num_heads "
                        "shrinks the KV cache by the group factor)")
    p.add_argument("--optimizer_state_dtype", default=None,
                   choices=["float32", "bfloat16", "int8"],
                   help="on-device Adam moment storage; narrow dtypes cut "
                        "the HBM-bound optimizer update traffic (int8 = "
                        "blockwise-absmax, second moment in sqrt-space)")
    p.add_argument("--multihost", action="store_true", default=None,
                   help="force jax.distributed.initialize() autodetect")
    p.add_argument("--device", type=str, default=None,
                   choices=["cpu", "tpu"],
                   help="force a JAX platform (cpu works even when a TPU "
                        "plugin is registered; the TPU->CPU fallback chain "
                        "replaces the reference's cuda->mps->cpu)")
    if mode == "fsdp":
        # reference fsdp_trainer.py:531-538
        p.add_argument("--sharding", type=str, default=None,
                       choices=_SHARDING_CHOICES)
        p.add_argument("--cpu_offload", action="store_true", default=None)
        p.add_argument("--offload_dtype", default=None,
                       choices=_OFFLOAD_DTYPES,
                       help="host storage dtype for offloaded optimizer "
                            "state; bfloat16 halves the host-link stream, "
                            "int8 (blockwise-absmax moments) quarters it")
        p.add_argument("--offload_budget_gb", type=float, default=None,
                       help="partial offload: GB of the largest optimizer-"
                            "moment leaves kept device-resident (exact "
                            "f32); only the overflow streams to host")
        p.add_argument("--no_activation_checkpointing", action="store_true",
                       default=None)
    return p


def load_yaml(path: Optional[str]) -> dict:
    if not path:
        return {}
    import yaml

    with open(path) as f:
        loaded = yaml.safe_load(f) or {}
    if not isinstance(loaded, dict):
        raise ValueError(f"{path}: expected a mapping at top level")
    return loaded


def _pick(*values):
    """First non-None value (CLI > YAML > default layering)."""
    for v in values:
        if v is not None:
            return v
    return None


def _pickf(*values) -> Optional[float]:
    """_pick + float coercion: YAML 1.1 parses bare '6e-4' as a string."""
    v = _pick(*values)
    return None if v is None else float(v)


def _picki(*values) -> Optional[int]:
    v = _pick(*values)
    return None if v is None else int(v)


def _preset_from_name(name: Optional[str]) -> Optional[str]:
    """Map a YAML model name like 'gpt2-small' to a preset key."""
    if not name:
        return None
    for key in ("small", "medium", "large", "xl"):
        if key in name:
            return key
    return None


def resolve_configs(args, mode: str):
    """Layer CLI flags over YAML over dataclass defaults → config objects."""
    y = load_yaml(args.config)
    y_model = y.get("model", {}) or {}
    y_train = y.get("training", {}) or {}
    y_dist = y.get("distributed", {}) or {}
    y_fsdp = y.get("fsdp", {}) or {}
    y_data = y.get("data", {}) or {}
    y_ckpt = y.get("checkpoint", {}) or {}
    y_ft = y.get("fault_tolerance", {}) or {}

    # --- model ---------------------------------------------------------
    preset = _pick(args.model_size, _preset_from_name(y_model.get("name")), "small")
    model_config = GPTConfig.preset(preset)
    overrides = {}
    # Any GPTConfig field may appear under `model:` (yaml keys == field
    # names; the reference schema's keys are a subset). Unknown keys fail
    # loudly — a silently-dropped `pipeline_schedule: 1f1b` once trained a
    # different configuration than the yaml said.
    _model_fields = {f.name for f in dataclasses.fields(GPTConfig)}
    for yaml_key, val in y_model.items():
        if yaml_key == "name":
            continue  # preset selector, handled above
        if yaml_key not in _model_fields:
            raise SystemExit(
                f"unknown model config key {yaml_key!r} in {args.config}; "
                f"valid keys: name, {', '.join(sorted(_model_fields))}"
            )
        overrides[yaml_key] = val
    if "hidden_size" in overrides and "intermediate_size" not in overrides:
        # Re-derive 4*hidden in __post_init__ rather than inheriting the
        # preset's intermediate size for a different hidden size.
        overrides["intermediate_size"] = None
    if args.seq_len is not None:
        overrides["max_seq_len"] = args.seq_len
    if args.num_experts is not None:
        overrides["num_experts"] = args.num_experts
    if args.moe_impl is not None:
        overrides["moe_impl"] = args.moe_impl
    if args.num_kv_heads is not None:
        overrides["num_kv_heads"] = args.num_kv_heads
    if args.gradient_checkpointing:
        overrides["gradient_checkpointing"] = True
    if mode == "fsdp":
        # FSDP default: activation checkpointing ON unless disabled
        # (reference fsdp_trainer.py:312-328, --no_activation_checkpointing).
        no_ckpt = getattr(args, "no_activation_checkpointing", None)
        if no_ckpt:
            overrides["gradient_checkpointing"] = False
        elif "gradient_checkpointing" not in overrides and not args.gradient_checkpointing:
            overrides["gradient_checkpointing"] = True
    if args.no_flash_attention:
        overrides["use_flash_attention"] = False
    elif "use_flash_attention" not in overrides:
        overrides["use_flash_attention"] = True
    if args.pipeline_microbatches is not None:
        overrides["pipeline_microbatches"] = args.pipeline_microbatches
    model_config = dataclasses.replace(model_config, **overrides)

    # --- training ------------------------------------------------------
    defaults = TrainingConfig()
    training_config = TrainingConfig(
        batch_size=_picki(args.batch_size, y_train.get("batch_size"),
                          defaults.batch_size),
        max_seq_len=model_config.max_seq_len,
        learning_rate=_pickf(args.learning_rate, y_train.get("learning_rate"),
                             defaults.learning_rate),
        weight_decay=_pickf(y_train.get("weight_decay"), defaults.weight_decay),
        beta1=_pickf(y_train.get("beta1"), defaults.beta1),
        beta2=_pickf(y_train.get("beta2"), defaults.beta2),
        grad_clip=_pickf(y_train.get("grad_clip"), defaults.grad_clip),
        max_steps=_picki(args.max_steps, y_train.get("max_steps"),
                         defaults.max_steps),
        warmup_steps=_picki(args.warmup_steps, y_train.get("warmup_steps"),
                            defaults.warmup_steps),
        log_interval=_picki(args.log_interval, y_train.get("log_interval"),
                            defaults.log_interval),
        eval_interval=_picki(args.eval_interval, y_train.get("eval_interval"),
                             defaults.eval_interval),
        save_interval=_picki(args.save_interval, y_train.get("save_interval"),
                             defaults.save_interval),
        mixed_precision=_pick(args.mixed_precision,
                              y_dist.get("mixed_precision"),
                              y_train.get("mixed_precision"),
                              defaults.mixed_precision),
        optimizer_state_dtype=_require_choice(
            _pick(args.optimizer_state_dtype,
                  y_train.get("optimizer_state_dtype"),
                  defaults.optimizer_state_dtype),
            _OFFLOAD_DTYPES, "optimizer_state_dtype"),
        gradient_accumulation_steps=_picki(
            args.grad_accum, y_train.get("gradient_accumulation_steps"),
            defaults.gradient_accumulation_steps),
        checkpoint_dir=_pick(args.checkpoint_dir, y_ckpt.get("dir"),
                             defaults.checkpoint_dir),
        resume_from=_pick(args.resume_from, y_ckpt.get("resume_from")),
        seed=_picki(args.seed, y_train.get("seed"), defaults.seed),
        # Step overlap (ISSUE 4): one resolved value each, read from here by
        # both the loaders and the startup summary.
        prefetch_depth=_picki(args.prefetch, y_data.get("prefetch"),
                              defaults.prefetch_depth),
        device_prefetch_depth=_picki(args.device_prefetch_depth,
                                     y_data.get("device_prefetch"),
                                     defaults.device_prefetch_depth),
        async_checkpointing=bool(_pick(
            False if args.no_async_checkpointing else None,
            y_ckpt.get("async"), defaults.async_checkpointing)),
    )

    # --- parallelism ---------------------------------------------------
    cpu_offload = False
    offload_dtype = "float32"
    offload_budget_gb = 0.0
    if mode == "fsdp":
        strategy = _pick(getattr(args, "sharding", None),
                         y_fsdp.get("sharding_strategy"), "FULL_SHARD")
        cpu_offload = bool(
            _pick(getattr(args, "cpu_offload", None),
                  y_fsdp.get("cpu_offload"), False)
        )
        offload_dtype = _require_choice(
            _pick(getattr(args, "offload_dtype", None),
                  y_fsdp.get("offload_dtype"), "float32"),
            _OFFLOAD_DTYPES, "offload_dtype")
        offload_budget_gb = _pickf(
            getattr(args, "offload_budget_gb", None),
            y_fsdp.get("offload_budget_gb"), 0.0)
        default_mesh = mesh_lib.MeshConfig(data=1, fsdp=-1)
    else:
        strategy = "replicated"
        default_mesh = mesh_lib.MeshConfig(data=-1, fsdp=1)
    mesh_auto = args.mesh == "auto"
    explicit_mesh = [
        flag for flag in ("mesh_data", "mesh_fsdp", "mesh_sequence",
                          "mesh_tensor", "mesh_expert", "mesh_stage")
        if getattr(args, flag) is not None
    ]
    if mesh_auto and explicit_mesh:
        raise SystemExit(
            "--mesh auto and explicit --" + "/--".join(explicit_mesh) +
            " are mutually exclusive: the planner picks every axis. Drop "
            "the explicit split, or drop --mesh auto to pin it yourself."
        )
    if strategy == "HYBRID_SHARD" and not mesh_auto \
            and args.mesh_data is None and args.mesh_fsdp is None:
        raise SystemExit(
            "HYBRID_SHARD needs an explicit mesh split: pass --mesh_data and "
            "--mesh_fsdp (data replicas x fsdp shards), or --mesh auto. (In "
            "the reference this mode is documented but unselectable — "
            "SURVEY.md §2.)"
        )
    mesh_config = mesh_lib.MeshConfig(
        data=_pick(args.mesh_data, default_mesh.data),
        fsdp=_pick(args.mesh_fsdp, default_mesh.fsdp),
        sequence=_pick(args.mesh_sequence, default_mesh.sequence),
        tensor=_pick(args.mesh_tensor, default_mesh.tensor),
        expert=_pick(args.mesh_expert, 1),
        stage=_pick(args.mesh_stage, 1),
    )
    parallel_config = ParallelConfig(
        mesh=mesh_config, sharding_strategy=strategy,
        cpu_offload=cpu_offload, offload_dtype=offload_dtype,
        offload_budget_gb=offload_budget_gb,
    )

    data_opts = {
        "dataset": _pick(args.dataset, y_data.get("dataset"), "dummy"),
        "data_path": _pick(args.data_path, y_data.get("path")),
        "max_tokens": _pick(args.max_tokens, y_data.get("max_tokens")),
        "streaming": bool(_pick(args.streaming, y_data.get("streaming"), False)),
        "pack_sequences": bool(_pick(args.pack_sequences,
                                     y_data.get("pack_sequences"), False)),
        "max_open_bins": _picki(args.max_open_bins,
                                y_data.get("max_open_bins"), 8),
        "pack_strategy": _pick(args.pack_strategy,
                               y_data.get("pack_strategy")) or "first_fit",
        "mask_doc_boundaries": bool(_pick(args.mask_doc_boundaries,
                                          y_data.get("mask_doc_boundaries"),
                                          False)),
        "data_mixture": _pick(args.data_mixture, y_data.get("mixture")),
        "cache_max_tokens": _pick(args.cache_max_tokens,
                                  y_data.get("cache_max_tokens")),
        "num_workers": _pick(args.num_workers, y_data.get("num_workers"), 0),
        "prefetch": training_config.prefetch_depth,
        "device_prefetch": training_config.device_prefetch_depth,
        "num_batches": _pick(args.num_batches, 100),
        "tokenizer": _pick(args.tokenizer, y_data.get("tokenizer"), "gpt2"),
        "metrics_jsonl": args.metrics_jsonl,
        "metrics_port": args.metrics_port,
        "wandb_project": args.wandb_project,
        "tensorboard_dir": args.tensorboard_dir,
        "eval_batches": _pick(args.eval_batches, 8),
        "eval_split": _pick(args.eval_split, y_data.get("eval_split"), 0.02),
        "eval_holdout_every": _pick(args.eval_holdout_every,
                                    y_data.get("eval_holdout_every"), 0),
        "auto_resume": not args.no_auto_resume,
        "profile_dir": args.profile_dir,
        "profile_start": _pick(args.profile_start, 5),
        "profile_steps": _pick(args.profile_steps, 5),
        "guard_interval": _pick(args.guard_interval, 100),
        # Fault tolerance (YAML: checkpoint.keep_last_n + fault_tolerance.*).
        # Defaults favor surviving a multi-day run: two rollbacks with
        # half-LR backoff, skipping one batch past the offending window.
        "keep_last_n": _picki(args.keep_last_n, y_ckpt.get("keep_last_n"), 0),
        "max_rollbacks": _picki(args.max_rollbacks,
                                y_ft.get("max_rollbacks"), 2),
        "skip_batches_on_rollback": _picki(
            args.skip_batches_on_rollback,
            y_ft.get("skip_batches_on_rollback"), 1),
        "rollback_lr_backoff": _pickf(args.rollback_lr_backoff,
                                      y_ft.get("rollback_lr_backoff"), 0.5),
        "inject_fault": args.inject_fault,
        "preemption_grace_s": _pickf(args.preemption_grace_s,
                                     y_ft.get("preemption_grace_s"), 0.0),
        "preempt_notice": _pick(args.preempt_notice,
                                y_ft.get("preempt_notice")),
        "preempt_notice_poll_s": _pickf(args.preempt_notice_poll_s,
                                        y_ft.get("preempt_notice_poll_s"),
                                        1.0),
        "preempt_vote_interval": _picki(args.preempt_vote_interval,
                                        y_ft.get("preempt_vote_interval"),
                                        _PREEMPT_VOTE_INTERVAL),
        # Telemetry / goodput / early warning (utils/telemetry.py).
        "telemetry_interval": _picki(args.telemetry_interval, None, 0),
        "spike_sigma": _pickf(args.spike_sigma, None, 6.0),
        "nan_scan": bool(_pick(args.nan_scan, False)),
        # Run anatomy (ISSUE 3).
        "comms_model": not bool(_pick(args.no_comms_model, False)),
        "flight_recorder_steps": _picki(args.flight_recorder_steps,
                                        None, 256),
        # Mesh auto-planner (--mesh auto; parallel/planner.py).
        "mesh_auto": mesh_auto,
        "hbm_gb": args.hbm_gb,
    }
    return model_config, training_config, parallel_config, data_opts


def parse_mixture_spec(spec: str) -> dict:
    """``'name:weight[:path],...'`` → ``{name: (weight, path)}``. Names must
    be distinct dataset kinds from {dummy, tinystories, openwebtext} (the
    mixture cursor keys per-source state by name)."""
    out = {}
    for part in spec.split(","):
        fields = part.strip().split(":", 2)
        if len(fields) < 2:
            raise SystemExit(
                f"bad --data_mixture entry {part.strip()!r}: expected "
                f"name:weight[:path]"
            )
        name = fields[0].strip()
        if name not in ("dummy", "tinystories", "openwebtext"):
            raise SystemExit(f"unknown mixture source {name!r}")
        if name in out:
            raise SystemExit(f"duplicate mixture source {name!r}")
        try:
            weight = float(fields[1])
        except ValueError:
            raise SystemExit(
                f"bad mixture weight {fields[1]!r} for source {name!r}"
            ) from None
        path = fields[2].strip() if len(fields) > 2 else None
        out[name] = (weight, path)
    return out


def _packed_synthetic_loader(rows, seq_len, vocab_size, num_batches, seed,
                             feed_rank, feed_world, max_open_bins, pack=True,
                             strategy="first_fit"):
    """Packed loader over a deterministic synthetic ragged corpus — the
    dummy dataset's packed counterpart (and the bench's --packed input).
    Documents stride across feed ranks so hosts pack disjoint rows."""
    from tpu_trainer.data.packing import (PackedDataLoader,
                                          synthetic_documents)

    mean_len = max(8, seq_len // 4)
    # Enough documents that every host can fill its num_batches * rows
    # rows: ~seq/mean docs land per packed row, plus slack for pad waste
    # in the pad-to-seq baseline lane (pack=False needs one row per doc).
    per_row = max(1, seq_len // mean_len) if pack else 1
    total_docs = num_batches * rows * feed_world * (per_row + 2)

    def doc_fn():
        docs = synthetic_documents(total_docs, mean_len, vocab_size,
                                   seed=seed)
        return (d for i, d in enumerate(docs)
                if i % feed_world == feed_rank)

    return PackedDataLoader(
        doc_fn, rows, seq_len, max_open_bins=max_open_bins, pack=pack,
        strategy=strategy, seed=seed, num_batches=num_batches,
    )


def _packed_text_loader(data_opts, rows, seq_len, feed_rank, feed_world,
                        seed):
    """Packed loader binning a text file's documents (lines) into full rows
    via ``StreamingTextDataset.iter_documents`` — shard/holdout/budget rules
    identical to the concatenating stream."""
    from tpu_trainer.data.packing import PackedDataLoader
    from tpu_trainer.data.text import StreamingTextDataset, TextDataLoader

    holdout_every = (data_opts["eval_holdout_every"]
                     if data_opts["streaming"] else 0)
    common = dict(
        tokenizer_name=data_opts["tokenizer"],
        max_tokens=data_opts["max_tokens"],
        cache_max_tokens=data_opts["cache_max_tokens"],
        shard_id=feed_rank,
        num_shards=feed_world,
        tokenizer_on_fallback="error",
    )
    ds = StreamingTextDataset(
        data_opts["data_path"], seq_len,
        num_workers=data_opts["num_workers"],
        holdout=("train", holdout_every) if holdout_every else None,
        **common,
    )
    train = PackedDataLoader(
        ds.iter_documents, rows, seq_len,
        max_open_bins=data_opts["max_open_bins"],
        strategy=data_opts.get("pack_strategy", "first_fit"), seed=seed,
    )
    eval_loader = None
    if holdout_every:
        # Held-out eval stays on the plain concatenating stream ([rows,
        # seq]): eval_step handles both formats, and eval loss on unpacked
        # rows is comparable across packed/unpacked training runs.
        eval_ds = StreamingTextDataset(
            data_opts["data_path"], seq_len,
            holdout=("eval", holdout_every), **common,
        )
        eval_loader = TextDataLoader(
            eval_ds, rows, process_index=feed_rank,
            process_count=feed_world, seed=seed, prefetch=0,
        )
    return train, eval_loader


def build_dataloaders(data_opts, trainer: Trainer, model_config: GPTConfig):
    """Train + (optional) eval loaders yielding per-host ``[rows, seq]``
    (or ``[rows, seq, 2]`` with a segment-id channel when packing or
    boundary masking is on).

    rows = grad_accum x micro_batch x (local data shards) — the reference's
    loader-batch semantics (``ddp_trainer.py:538``) applied per host.
    """
    c = trainer.training_config
    # Feed ranks come from the mesh's row coverage (Trainer.data_feed_*):
    # hosts sharing a data shard (sequence/tensor axes spanning hosts) get
    # the same rank and load identical rows.
    feed_rank, feed_world = trainer.data_feed_rank, trainer.data_feed_world
    rows = (c.gradient_accumulation_steps * c.batch_size * trainer.dp_size
            ) // feed_world
    if data_opts.get("data_mixture"):
        return _build_mixture(data_opts, trainer, model_config, rows,
                              feed_rank, feed_world)
    name = data_opts["dataset"]
    pack = data_opts.get("pack_sequences")
    if pack and name != "dummy":
        if not data_opts["data_path"]:
            raise SystemExit(f"--data_path is required for dataset {name!r}")
        return _packed_text_loader(data_opts, rows, c.max_seq_len,
                                   feed_rank, feed_world, c.seed)
    if name == "dummy":
        if pack:
            train = _packed_synthetic_loader(
                rows, c.max_seq_len, model_config.vocab_size,
                data_opts["num_batches"], c.seed + 1234, feed_rank,
                feed_world, data_opts["max_open_bins"],
                strategy=data_opts.get("pack_strategy", "first_fit"),
            )
            eval_loader = _packed_synthetic_loader(
                rows, c.max_seq_len, model_config.vocab_size,
                data_opts["eval_batches"], c.seed + 4321, feed_rank,
                feed_world, data_opts["max_open_bins"],
                strategy=data_opts.get("pack_strategy", "first_fit"),
            )
            return train, eval_loader
        from tpu_trainer.data.dummy import create_dummy_dataloader

        train = create_dummy_dataloader(
            batch_size=rows * feed_world,
            seq_len=c.max_seq_len,
            vocab_size=model_config.vocab_size,
            num_batches=data_opts["num_batches"],
            seed=c.seed + 1234,
            process_index=feed_rank,
            process_count=feed_world,
        )
        eval_loader = create_dummy_dataloader(
            batch_size=rows * feed_world,
            seq_len=c.max_seq_len,
            vocab_size=model_config.vocab_size,
            num_batches=data_opts["eval_batches"],
            seed=c.seed + 4321,   # disjoint synthetic eval corpus
            process_index=feed_rank,
            process_count=feed_world,
        )
        return train, eval_loader
    if name == "tinystories":
        from tpu_trainer.data.tinystories import create_tinystories_dataloader as factory
    elif name == "openwebtext":
        from tpu_trainer.data.openwebtext import create_openwebtext_dataloader as factory
    else:
        raise ValueError(f"unknown dataset {name!r}")
    if not data_opts["data_path"]:
        raise SystemExit(f"--data_path is required for dataset {name!r}")
    train = factory(
        data_opts["data_path"],
        batch_size=rows,
        seq_len=c.max_seq_len,
        tokenizer_name=data_opts["tokenizer"],
        max_tokens=data_opts["max_tokens"],
        streaming=data_opts["streaming"],
        cache_max_tokens=data_opts["cache_max_tokens"],
        process_index=feed_rank,
        process_count=feed_world,
        seed=trainer.training_config.seed,
        num_workers=data_opts["num_workers"],
        prefetch=data_opts["prefetch"],
        # Tokenizer guardrail (VERDICT r1 weak #6): training never falls
        # back to byte-level ids silently — choose it as --tokenizer byte.
        tokenizer_on_fallback="error",
        # Held-out eval (VERDICT r1 weak #5: the old "eval" re-read the
        # training data): map-style carves the tail eval_split fraction of
        # chunks; streaming reserves every eval_holdout_every-th line.
        # Train/eval rows are disjoint by construction (data/text.py).
        eval_split=0.0 if data_opts["streaming"] else data_opts["eval_split"],
        eval_holdout_every=(data_opts["eval_holdout_every"]
                            if data_opts["streaming"] else 0),
        # Cross-document loss-leak fix (streaming only; map-style chunks
        # have no in-chunk boundary metadata to derive segments from).
        mask_doc_boundaries=(data_opts["mask_doc_boundaries"]
                             if data_opts["streaming"] else False),
    )
    return train, train.eval_loader


def _build_mixture(data_opts, trainer, model_config, rows, feed_rank,
                   feed_world):
    """Weighted multi-source mixture (``--data_mixture``). Every source
    yields the same per-host batch shape — plain ``[rows, seq]``, or
    ``[rows, seq, 2]`` when ``--pack_sequences`` puts all sources (dummy
    included, via the synthetic ragged corpus) on the packed format."""
    from tpu_trainer.data.mixture import MixtureDataLoader

    c = trainer.training_config
    spec = parse_mixture_spec(data_opts["data_mixture"])
    pack = data_opts.get("pack_sequences")
    mask = data_opts.get("mask_doc_boundaries")
    if mask and not pack and "dummy" in spec:
        raise SystemExit(
            "--data_mixture with --mask_doc_boundaries cannot include the "
            "'dummy' source (its batches carry no segment channel, so the "
            "shapes would disagree); add --pack_sequences or drop dummy"
        )
    sources, weights = {}, {}
    for idx, nm in enumerate(sorted(spec)):
        weight, path = spec[nm]
        weights[nm] = weight
        sub_seed = c.seed + 1000 * (idx + 1)   # disjoint per-source streams
        if nm == "dummy":
            if pack:
                sources[nm] = _packed_synthetic_loader(
                    rows, c.max_seq_len, model_config.vocab_size,
                    data_opts["num_batches"], sub_seed, feed_rank,
                    feed_world, data_opts["max_open_bins"],
                    strategy=data_opts.get("pack_strategy", "first_fit"),
                )
            else:
                from tpu_trainer.data.dummy import create_dummy_dataloader

                sources[nm] = create_dummy_dataloader(
                    batch_size=rows * feed_world, seq_len=c.max_seq_len,
                    vocab_size=model_config.vocab_size,
                    num_batches=data_opts["num_batches"], seed=sub_seed,
                    process_index=feed_rank, process_count=feed_world,
                )
            continue
        if not path:
            raise SystemExit(
                f"mixture source {nm!r} needs a path "
                f"('{nm}:<weight>:<path>')"
            )
        if pack:
            opts = dict(data_opts, data_path=path, streaming=True,
                        eval_holdout_every=0)
            train, _ = _packed_text_loader(opts, rows, c.max_seq_len,
                                           feed_rank, feed_world, sub_seed)
            sources[nm] = train
        else:
            from tpu_trainer.data.text import create_text_dataloader

            sources[nm] = create_text_dataloader(
                path, batch_size=rows, seq_len=c.max_seq_len,
                tokenizer_name=data_opts["tokenizer"],
                max_tokens=data_opts["max_tokens"], streaming=True,
                cache_max_tokens=data_opts["cache_max_tokens"],
                process_index=feed_rank, process_count=feed_world,
                seed=sub_seed, num_workers=data_opts["num_workers"],
                # Sub-loaders draw on demand; background prefetch threads
                # would race the mixture's deterministic draw order for no
                # overlap win (the mixture itself sits behind feed prefetch).
                prefetch=0, tokenizer_on_fallback="error",
                mask_doc_boundaries=bool(mask),
            )
    train = MixtureDataLoader(sources, weights, seed=c.seed)
    # No held-out eval across a mixture (per-source holdouts would need
    # per-source eval weighting to mean anything); eval stays available via
    # single-source runs.
    return train, None


def run_training(argv=None, mode: str = "ddp") -> int:
    args = build_parser(mode).parse_args(argv)
    import os

    import jax

    # --- standby host (elastic supervisor's warm spares) ---------------
    # A standby has paid the cold-start bill — interpreter, imports (jax is
    # the multi-second item), arg parsing — and parks HERE, before the
    # jax.distributed rendezvous binds coordinator/world/rank. Promotion
    # (the supervisor writing the activation file) hands it the same env a
    # fresh child would get, and it proceeds down the normal path.
    standby_file = os.environ.get("TPU_TRAINER_STANDBY_FILE")
    if standby_file:
        from tpu_trainer.training import elastic as elastic_lib
        print(f"standby: parked before rendezvous ({standby_file})",
              flush=True)
        activation = elastic_lib.hold_standby(standby_file)
        if activation is None:
            print("standby: supervisor gone; retiring unpromoted",
                  flush=True)
            return 0
        os.environ.update(activation)
        print(f"standby: promoted to rank {activation.get('PROCESS_ID')} "
              f"(world {activation.get('NUM_PROCESSES')})", flush=True)

    if args.device:
        # Honor an explicit platform choice even when a site hook
        # pre-registered an accelerator plugin (same workaround as
        # tests/conftest.py). The JAX_PLATFORMS env var is NOT re-asserted
        # here: jax reads it itself at backend init, and re-applying it
        # would override an embedding harness's explicit jax.config choice
        # (e.g. the test suite's forced 8-device CPU backend).
        jax.config.update("jax_platforms", args.device)
    # Partitionable threefry, same as tests/conftest.py: without it the
    # pipeline stage shard_map lowers per-step RNG to a PartitionId
    # instruction the SPMD partitioner rejects — stage>1 meshes (the
    # planner picks them freely) would crash at the first train step.
    jax.config.update("jax_threefry_partitionable", True)
    mesh_lib.initialize_distributed(auto=args.multihost)

    model_config, training_config, parallel_config, data_opts = resolve_configs(
        args, mode
    )

    # --- mesh auto-planner / early mesh validation ---------------------
    # Both paths share planner_lib.feasibility_error, so a split the CLI
    # accepts here is exactly one the Trainer's own divisibility checks
    # accept below — the predicate can't disagree with the pruning.
    plan_record = None
    n_devices = jax.device_count()
    plan_mc = dataclasses.replace(
        model_config, dtype=_MP_TO_DTYPE[training_config.mixed_precision])
    plan_opt_bytes = {"float32": 4, "bfloat16": 2, "int8": 1}.get(
        training_config.optimizer_state_dtype, 4)
    if data_opts["mesh_auto"]:
        # Hold the global batch a pure-DP run would have (per-shard
        # batch_size on every device) fixed across candidates; the winner's
        # per-shard batch is global_rows / its data*fsdp world.
        global_rows = training_config.batch_size * n_devices
        # The CPU SPMD partitioner cannot lower the GPipe stage shard_map
        # (PartitionId rejection), so correctness-mode planning must not
        # hand back a mesh the Trainer then crashes on. Real TPUs plan
        # all six axes.
        exclude = (() if jax.devices()[0].platform == "tpu"
                   else ("stage",))
        try:
            plan_record = planner_lib.plan(
                plan_mc, n_devices,
                global_rows=global_rows,
                max_seq_len=training_config.max_seq_len,
                grad_accum=training_config.gradient_accumulation_steps,
                strategy=parallel_config.sharding_strategy,
                hbm_gb=data_opts["hbm_gb"],
                opt_state_bytes=plan_opt_bytes,
                carry_cast=training_config.carry_cast_params,
                exclude_axes=exclude)
        except planner_lib.NoFeasiblePlanError as plan_err:
            raise SystemExit(f"--mesh auto: {plan_err}") from plan_err
        plan_record["auto"] = True
        chosen = plan_record["chosen"]
        parallel_config = dataclasses.replace(
            parallel_config, mesh=planner_lib.mesh_config_for(chosen))
        if chosen["batch_per_shard"] != training_config.batch_size:
            training_config = dataclasses.replace(
                training_config, batch_size=chosen["batch_per_shard"])
        if jax.process_index() == 0:
            for line in planner_lib.render_table(plan_record):
                print(line, flush=True)
    else:
        try:
            resolved = parallel_config.mesh.resolve(n_devices)
        except ValueError as mesh_err:
            raise SystemExit(f"mesh: {mesh_err}") from mesh_err
        sizes = dict(zip(mesh_lib.MESH_AXES, resolved))
        feas_err = planner_lib.feasibility_error(
            sizes, plan_mc, n_devices=n_devices,
            global_rows=training_config.batch_size
            * sizes[mesh_lib.DATA_AXIS] * sizes[mesh_lib.FSDP_AXIS],
            max_seq_len=training_config.max_seq_len)
        if feas_err:
            raise SystemExit(
                f"mesh: infeasible split {tuple(resolved)} "
                f"({'x'.join(mesh_lib.MESH_AXES)}): {feas_err} — fix the "
                f"--mesh_* split, or let --mesh auto pick one")

    trainer = Trainer(model_config, training_config, parallel_config)
    main = trainer.is_main_process
    if main:
        print(f"mode={mode} strategy={trainer.strategy} "
              f"mesh={dict(trainer.mesh.shape)} devices={jax.device_count()} "
              f"processes={trainer.process_count}")
        print(f"model: {model_config.num_parameters():,} params | "
              f"global batch {trainer.global_batch_size} seqs x "
              f"{training_config.max_seq_len} tokens")
        print(f"overlap: host_prefetch={training_config.prefetch_depth} "
              f"device_prefetch={training_config.device_prefetch_depth} "
              f"async_checkpointing="
              f"{'on' if training_config.async_checkpointing else 'off'}")
        if trainer.cpu_offload and trainer.offload_resident_bytes:
            print(f"partial offload: "
                  f"{trainer.offload_resident_bytes / 2**30:.2f} GB of "
                  f"optimizer moments device-resident (exact f32), "
                  f"overflow streams to host")

    # --- fault injection (--inject_fault debug flag; utils/faults.py) --
    installed_plan = None
    if data_opts["inject_fault"]:
        # process_count makes install validate TPU_TRAINER_FAULT_HOST once,
        # up front — a typo'd target rank must fail the run loudly, not
        # quietly neuter the chaos fault it was meant to aim.
        installed_plan = faults.install(data_opts["inject_fault"],
                                        process_count=trainer.process_count)

    # --- goodput ledger: attribute every second of the run -------------
    ledger = telemetry_lib.GoodputLedger()

    # --- resume (SURVEY.md §5.3: actually wired) -----------------------
    state = None
    tokens_seen = 0
    data_state = None
    resume_path = training_config.resume_from
    if resume_path:
        # Explicit --resume_from: failures raise — the user asked for this
        # exact checkpoint, silently substituting another would be worse.
        with ledger.track("checkpoint_restore"):
            state, meta = ckpt_lib.restore_checkpoint(resume_path, trainer)
        tokens_seen = meta.get("tokens_seen", 0)
        data_state = meta.get("data_state")
        if main:
            print(f"resumed from {resume_path} at step {int(state.step)}")
    elif data_opts["auto_resume"]:
        # Auto-resume hardening: a corrupt/partial latest checkpoint is
        # quarantined and the previous valid step restores instead — one
        # bad save must never brick the restart loop of a multi-day run.
        with ledger.track("checkpoint_restore"):
            restored = ckpt_lib.restore_latest(
                training_config.checkpoint_dir, trainer, verify=True
            )
        if restored is not None:
            state, meta, resume_path = restored
            tokens_seen = meta.get("tokens_seen", 0)
            data_state = meta.get("data_state")
            if main:
                print(f"resumed from {resume_path} at step {int(state.step)}")
    if state is None:
        state = trainer.init_state()

    train_loader, eval_loader = build_dataloaders(data_opts, trainer, model_config)
    if data_state is not None and hasattr(train_loader, "load_state_dict"):
        # Exact data resume: continue at the consumed-batch cursor saved in
        # the checkpoint instead of re-reading the dataset from the start.
        # If this run's mesh resized the global batch or feed world since
        # the save (elastic restart on fewer hosts), remap the cursor onto
        # the new batch granularity first — at-least-once semantics, never
        # skipping data.
        data_state, replayed = ckpt_lib.remap_data_state(
            data_state,
            new_global_batch_size=trainer.global_batch_size,
            new_feed_world=trainer.data_feed_world,
        )
        if replayed and main:
            print(f"data cursor remapped for the resized mesh: replaying "
                  f"{replayed} already-seen sequences (at-least-once window, "
                  f"batch granularity)", flush=True)
        try:
            train_loader.load_state_dict(data_state)
        except ValueError as e:
            if main:
                print(f"data state not restored ({e}); reading the dataset "
                      f"from the start", flush=True)

    # --- crash flight recorder (ISSUE 3): ring of emitted records ------
    recorder = None
    if data_opts["flight_recorder_steps"] > 0:
        recorder = flight_lib.FlightRecorder(
            capacity=data_opts["flight_recorder_steps"],
            snapshot=flight_lib.env_snapshot(
                trainer=trainer, model_config=model_config,
                training_config=training_config, argv=argv),
        )

    # --- heartbeats for the elastic run supervisor ---------------------
    # The supervisor (training/elastic.py) exports TPU_TRAINER_HEARTBEAT_DIR
    # to its children; standalone runs skip this entirely. One beat per
    # completed step — the supervisor's staleness check is how a hung (not
    # dead) host gets caught.
    heartbeat = None
    hb_dir = os.environ.get("TPU_TRAINER_HEARTBEAT_DIR")
    if hb_dir:
        heartbeat = flight_lib.HeartbeatWriter(
            hb_dir, host=trainer.process_index,
            min_interval_s=float(
                os.environ.get("TPU_TRAINER_HEARTBEAT_INTERVAL_S", "0")),
            recorder=recorder,
            # Every beat carries the step this attempt resumed at: the
            # supervisor computes rolled-back work as (dead attempt's last
            # beat) - (new attempt's start_step) — exactly 0 for a
            # proactive notice drain, whose exit checkpoint IS the resume
            # point.
            start_step=int(state.step),
        )

    def dump_flight(reason: str, exc: Optional[BaseException] = None):
        """Best-effort crash_report.json — never masks the real failure."""
        if recorder is None:
            return
        try:
            path = recorder.dump(
                training_config.checkpoint_dir, reason=reason, exc=exc,
                step=int(state.step) if state is not None else None)
            if main:
                print(f"flight recorder: wrote {path} ({reason})", flush=True)
        except Exception as dump_err:
            if main:
                print(f"flight recorder dump failed: {dump_err}", flush=True)

    # Live metrics plane (ISSUE 18): registry + bridge + HTTP endpoint,
    # host 0 only. The bridge rides the MetricLogger observer hook, so
    # every record the sinks see also updates the scrapeable registry —
    # and nothing else changes: with --metrics_port unset this whole
    # block is skipped and the run is bit-identical.
    metrics_server = None
    metrics_bridge = None
    if data_opts["metrics_port"] is not None and main:
        from tpu_trainer.obs.http import MetricsServer
        from tpu_trainer.obs.metrics import MetricsRegistry

        metrics_bridge = telemetry_lib.MetricsBridge(MetricsRegistry())
        metrics_server = MetricsServer(
            metrics_bridge.registry, port=data_opts["metrics_port"],
            statusz_fn=metrics_bridge.statusz)
        # Ready once the run has produced its first record — before
        # that the process is alive but still compiling/restoring.
        metrics_server.health.add_probe(
            "first_record", lambda: metrics_bridge.n_records > 0)
        print(f"metrics: serving {metrics_server.url}/metrics", flush=True)

    logger = MetricLogger(
        model_config,
        tokens_per_step=trainer.tokens_per_step,
        log_interval=training_config.log_interval,
        jsonl_path=data_opts["metrics_jsonl"],
        is_main_process=main,
        wandb_project=data_opts["wandb_project"],
        tensorboard_dir=data_opts["tensorboard_dir"],
        run_config={
            "model": dataclasses.asdict(model_config),
            "training": dataclasses.asdict(training_config),
        },
        seq_len=training_config.max_seq_len,
        recorder=recorder,
        observer=metrics_bridge,
    )
    logger.tokens_seen = tokens_seen

    if plan_record is not None:
        # The ranked table already printed at plan time (before the mesh
        # existed); this persists the record to the JSONL sinks.
        logger.log_record(plan_record)

    # --- nan_scan debug mode: bisect the first non-finite layer, exit --
    if data_opts["nan_scan"]:
        try:
            batch = next(iter(train_loader))
            report = trainer.nan_scan(state, batch)
            first = report["first_nan"]
            verdict = (
                "no non-finite activations in the forward" if first is None
                else f"first non-finite value at layer {first['layer']}, "
                     f"site '{first['site']}'"
            )
            if main:
                print(f"nan_scan | {verdict}")
                stats = report["stats"]
                layers = sorted({k.rsplit("/L", 1)[1]
                                 for k in stats if "/L" in k})
                for li in layers:
                    row = " ".join(
                        f"{site}={stats.get(f'nan_scan/act/{site}_absmax/L{li}', float('nan')):.3e}"
                        for site in ("attn", "ffn", "block")
                    )
                    print(f"nan_scan | layer {li} absmax: {row}")
                head = " ".join(
                    f"{site}={stats[f'nan_scan/act/{site}_absmax']:.3e}"
                    for site in ("embed_out", "final_norm", "logits")
                    if f"nan_scan/act/{site}_absmax" in stats
                )
                print(f"nan_scan | head absmax: {head}")
                print(f"nan_scan | loss: {stats['nan_scan/loss']:.6g}")
            logger.log_record({
                "kind": "nan_scan", "step": int(state.step),
                "first_nan": first, "sites": report["sites"],
                **report["stats"],
            })
            return 0
        finally:
            logger.close()
            if metrics_server is not None:
                metrics_server.close()
            if installed_plan is not None:
                faults.clear()

    # --- preemption handler (TPU maintenance SIGTERM) ------------------
    # "at" anchors the --preemption_grace_s deadline at signal receipt, not
    # at the (cadenced) vote that notices it.
    preempted = {"hit": False, "at": None}

    def _on_sigterm(signum, frame):
        preempted["hit"] = True
        if preempted["at"] is None:
            preempted["at"] = time.monotonic()

    old_handler = signal.signal(signal.SIGTERM, _on_sigterm)

    # --- proactive preemption notice (utils/preemption.py) -------------
    # The polled notice arrives BEFORE the kill deadline starts running
    # (SIGTERM is the fallback that arrives after). A noticed host drains
    # at the next vote boundary: checkpoint, write a drain marker
    # (deregister — the supervisor reforms without counting a crash), exit.
    notice_source = preemption_lib.build_notice_source(
        data_opts["preempt_notice"]
        or os.environ.get("TPU_TRAINER_PREEMPT_NOTICE"),
        poll_interval_s=data_opts["preempt_notice_poll_s"])
    notice = {"rec": None}

    def check_notice(step: int) -> bool:
        """Poll the notice source (and the preempt_notice fault) once per
        step; sticky. Logs on first receipt."""
        if notice["rec"] is not None:
            return True
        if faults.fire("preempt_notice", step) and faults.targets_host(
                trainer.process_index, trainer.process_count):
            grace = data_opts["preemption_grace_s"]
            notice["rec"] = preemption_lib.PreemptionNotice(
                source="fault:preempt_notice",
                received_unix=time.time(),
                deadline_unix=(time.time() + grace) if grace else None)
        elif notice_source is not None:
            notice["rec"] = notice_source.poll()
        if notice["rec"] is not None:
            remaining = notice["rec"].remaining_s()
            print(f"preemption notice received ({notice['rec'].source})"
                  + (f": {remaining:.1f}s to the kill deadline"
                     if remaining is not None else "")
                  + "; draining at the next step boundary", flush=True)
            return True
        return False

    # Async checkpointing (ISSUE 4): the periodic save snapshots to host and
    # returns; shards + meta commit on the saver's writer thread. At most one
    # commit is in flight — the next save, a rollback, SIGTERM, and exit all
    # drain it first, and that wait is attributed to checkpoint_commit_wait
    # (in steady state the commit finishes under the following steps' compute
    # and the drain costs ~nothing).
    saver = ckpt_lib.AsyncSaver() if training_config.async_checkpointing else None

    def drain_save(timeout: Optional[float] = None) -> bool:
        """Drain the in-flight async commit; False when ``timeout`` expired
        with the commit still running (daemon writer — it dies with the
        process, leaving the usual crash-safe meta-less tree)."""
        if saver is not None and saver.in_flight:
            with ledger.track("checkpoint_commit_wait"):
                saver.wait(timeout)
            return not saver.in_flight
        return True

    def save(tag: str = "", wait: bool = False,
             deadline: Optional[float] = None):
        if deadline is not None:
            # Preemption grace: both drains are bounded by the remaining
            # budget; an expired budget abandons the save rather than
            # outliving the scheduler's kill.
            if not drain_save(max(0.0, deadline - time.monotonic())):
                if main:
                    print("preemption grace spent draining the in-flight "
                          "commit; skipping the final checkpoint", flush=True)
                return
        else:
            drain_save()
        with ledger.track("checkpoint_save"):
            # The feed's cursor, not the raw loader's: with device prefetch
            # the loader runs up to depth batches ahead of what the trainer
            # consumed, and resuming from its cursor would skip the
            # buffered batches. The feed signature (global batch size, feed
            # world) rides along so an elastic restart on a resized mesh
            # can remap the cursor's units.
            data_sd = feed.state_dict()
            if data_sd is not None:
                data_sd = dict(data_sd, **trainer.feed_signature)
            save_fn = saver.save if saver is not None else ckpt_lib.save_checkpoint
            path = save_fn(
                training_config.checkpoint_dir, state,
                model_config=model_config, training_config=training_config,
                tokens_seen=logger.tokens_seen,
                data_state=data_sd,
                keep_last_n=data_opts["keep_last_n"],
            )
        if wait:
            # Terminal saves (final/preempt/crash): the process is about to
            # exit, so the checkpoint must be durable before we return.
            if not drain_save(None if deadline is None
                              else max(0.0, deadline - time.monotonic())):
                if main:
                    print("preemption grace expired before the final commit "
                          "landed; exiting with the commit in flight",
                          flush=True)
                return
        if main:
            print(f"saved checkpoint{' (' + tag + ')' if tag else ''}: {path}")

    eval_warned = {"hit": False}

    def run_eval():
        if eval_loader is None:
            return
        losses = []
        with ledger.track("eval"):
            for i, batch in enumerate(eval_loader):
                if i >= data_opts["eval_batches"]:
                    break
                # Device value: each eval step dispatches async and the
                # loop keeps feeding; the single device_get below is the
                # only host sync for the whole eval pass (the old
                # per-batch float() serialized host and device).
                losses.append(trainer.eval_step(state, batch))
            losses = [float(x) for x in jax.device_get(losses)]
        if losses and main:
            logger.log_eval(int(state.step), float(np.mean(losses)),
                            len(losses))
        elif not losses and main and not eval_warned["hit"]:
            eval_warned["hit"] = True
            print(
                "eval | no full eval batch (held-out rows < batch rows x "
                "hosts); grow --eval_split / --eval_holdout_every or the "
                "dataset", flush=True,
            )

    # --- the step loop (reference ddp_trainer.py:582-616) --------------
    data_iter = iter(train_loader)
    # Per-source loss telemetry (mixture loaders): sources are recorded at
    # PULL time in a FIFO — the device prefetcher pulls ahead of the
    # consuming step, but pull order == consume order, so popping one entry
    # per consumed batch re-aligns them exactly. source_by_step then feeds
    # the (window-lagged) metric log as the `data_source` extra.
    source_fifo = []
    source_by_step = {}

    def next_batch():
        nonlocal data_iter
        try:
            b = next(data_iter)
        except StopIteration:
            data_iter = iter(train_loader)  # new epoch
            try:
                b = next(data_iter)
            except StopIteration:
                raise SystemExit(
                    "the dataset yields zero batches for this configuration: "
                    "it is smaller than one global batch stride "
                    f"(batch_size x grad_accum x data shards = "
                    f"{trainer.global_batch_size} sequences of "
                    f"{training_config.max_seq_len} tokens). Use a larger "
                    "dataset or reduce batch_size/grad_accum."
                ) from None
        src = getattr(train_loader, "last_source", None)
        if src is not None:
            source_fifo.append(src)
        return b

    # Device prefetch (ISSUE 4): the feed owns the trainer-consumed cursor
    # (data/device_prefetch.py docstring) — every checkpoint/rollback reads
    # feed.state_dict(), never the raw loader's. place binds late so an LR
    # backoff's rebuilt trainer is picked up without respawning the feed.
    def make_feed():
        # Batches buffered in a discarded feed were pulled but never
        # consumed; their FIFO entries would desync the source alignment.
        source_fifo.clear()
        return DevicePrefetcher(
            next_batch,
            place=lambda b: trainer.place_batch(b),
            cursor_fn=(train_loader.state_dict
                       if hasattr(train_loader, "state_dict") else None),
            depth=data_opts["device_prefetch"],
        )

    feed = make_feed()

    profiler = profiling.WindowedTrace(
        data_opts["profile_dir"],
        start=int(state.step) + data_opts["profile_start"],
        num_steps=data_opts["profile_steps"],
    )
    guard_interval = data_opts["guard_interval"]

    # Rollback budget (divergence recovery): on a non-finite loss or
    # cross-host divergence, rewind to the last good checkpoint, skip the
    # offending data window, shrink the LR, and retry — bounded by
    # --max_rollbacks so a deterministic failure still fails loudly.
    max_rollbacks = data_opts["max_rollbacks"]
    rollbacks = 0
    steps_this_run = 0
    base_lr = training_config.learning_rate

    # Telemetry cadence + loss-spike early warning (ISSUE 2). The spike
    # check reads only records the logger actually emitted, and (ISSUE 4)
    # only on matured — window-lagged — host values.
    telemetry_interval = data_opts["telemetry_interval"]
    spike = (telemetry_lib.SpikeDetector(sigma=data_opts["spike_sigma"])
             if data_opts["spike_sigma"] > 0 else None)
    # Deferred host sync (ISSUE 4): each step's metrics go into a bounded
    # window of in-flight futures instead of being read back immediately;
    # the logger/spike/guard consumers below run on matured (lagged) host
    # values, so the host never blocks on the step it just dispatched.
    deferred = telemetry_lib.DeferredFetcher(window=_DEFERRED_SYNC_WINDOW)

    def consume(entries, check: bool = True):
        """Log, spike-check, and guard each matured metric entry.
        ``check=False`` (exit paths) logs without raising."""
        for mstep, mmetrics in entries:
            src = source_by_step.pop(mstep, None)
            rec = logger.log(
                mstep, mmetrics,
                extra=None if src is None else {"data_source": src})
            if not check:
                continue
            if spike is not None and rec is not None:
                is_spike, z = spike.update(rec["loss"])
                if is_spike:
                    if main:
                        print(
                            f"loss spike at step {mstep}: loss "
                            f"{rec['loss']:.4f} is z={z:.1f} above "
                            f"the rolling median (sigma="
                            f"{data_opts['spike_sigma']:g}); rolling "
                            "back before divergence", flush=True)
                    raise guards.LossSpikeError(
                        f"loss spike (z={z:.1f}) at step {mstep}")
            if guard_interval and (mstep + 1) % guard_interval == 0:
                loss = (rec or {}).get("loss")
                if loss is None:
                    loss = float(mmetrics["loss"])
                guards.check_finite(mstep, loss)
                guards.check_hosts_in_sync(mstep, loss)
    # Goodput attribution: the first execution of each jitted step variant
    # pays tracing + XLA compilation, so its wall-clock goes to "compile";
    # later executions go to "step" (or "rollback_replay" while re-covering
    # ground rewound by a rollback). Reset on LR backoff — rebuilding the
    # trainer recompiles both variants.
    jit_warm = {"step": False, "telemetry": False}
    cost_emitted = False
    replay_until = -1   # steps <= this are rollback replay, not fresh work
    # Recompile watchdog (ISSUE 3): executable-cache growth after warmup
    # means XLA recompiled the step — log it; repeated growth is a storm
    # (loader shape churn) and warns loudly.
    watchdog = RecompileWatchdog(trainer)

    try:
        while True:
            try:
                start_step = int(state.step)
                step = start_step
                if heartbeat is not None:
                    # Entry beat: start_step steps ARE completed (resumed)
                    # when the loop starts, so this is a true beat — and it
                    # marks the host live for the supervisor before the
                    # first step's multi-second compile, which would
                    # otherwise be silent. The recovery window (death →
                    # first beat of the new attempt) therefore measures
                    # time-to-resumed-and-ready, not compile time the dead
                    # host would have paid too.
                    heartbeat.beat(start_step)
                for step in range(start_step, training_config.max_steps):
                    if faults.fire("kill", step):
                        faults.kill()
                    if faults.fire("sigterm", step):
                        # A preemption notice that DID arrive: deliver a real
                        # SIGTERM to ourselves so the drain/grace exit path
                        # is exercised through the actual handler.
                        os.kill(os.getpid(), signal.SIGTERM)
                    if faults.fire("kill_host", step) and faults.targets_host(
                            trainer.process_index, trainer.process_count):
                        # Chaos lane: this rank dies hard; the others keep
                        # running until the supervisor reforms the mesh.
                        faults.kill()
                    if faults.fire("hang_host", step) and faults.targets_host(
                            trainer.process_index, trainer.process_count):
                        # Chaos lane: look dead without dying — only the
                        # supervisor's heartbeat-staleness check catches it.
                        if heartbeat is not None:
                            heartbeat.stop()
                    if (faults.fire("return_host", step)
                            and trainer.process_index == 0
                            and int(os.environ.get("TPU_TRAINER_ATTEMPT",
                                                   "0")) > 0):
                        # Chaos lane: the cluster re-grants a host. Not
                        # host-targeted — rank 0 plays the granting agent,
                        # and it must stay live at world 1, where a shrunk
                        # run is exactly the one that needs to grow back.
                        # Armed only on attempt > 0: a "returned" host only
                        # exists after a death, and async dispatch lets the
                        # first attempt's Python loop run steps ahead of the
                        # collective a dying peer just abandoned — an
                        # attempt-0 grant would regrow the reform straight
                        # into the re-armed kill fault.
                        cap_file = os.environ.get("TPU_TRAINER_CAPACITY_FILE")
                        if cap_file:
                            total = preemption_lib.grant_capacity(cap_file, 1)
                            print(f"fault return_host@{step}: capacity grant "
                                  f"written ({total} host(s) available)",
                                  flush=True)
                    has_notice = check_notice(step)
                    # profiler.step returns a StepTraceAnnotation context
                    # inside the trace window (per-step grouping in the
                    # viewer), a nullcontext outside it.
                    with profiler.step(step):
                        with ledger.track("data_wait"):
                            # Device-resident (or at least enqueued) already
                            # when device_prefetch_depth > 0 — the H2D copy
                            # ran under the previous step's compute.
                            batch = feed.next()
                        if source_fifo:
                            source_by_step[step] = source_fifo.pop(0)
                        tel_step = bool(
                            telemetry_interval
                            and (step + 1) % telemetry_interval == 0)
                        variant = "telemetry" if tel_step else "step"
                        expected_compile = not jit_warm[variant]
                        category = ("compile" if expected_compile
                                    else "rollback_replay"
                                    if step <= replay_until else "step")
                        # The matured metric fetches are the device sync
                        # point, so they stay inside the tracked block —
                        # otherwise async dispatch would bank the real
                        # compute under "untracked".
                        with ledger.track(category):
                            state, metrics = trainer.train_step(
                                state, batch, telemetry=tel_step)
                            if not jit_warm[variant]:
                                jax.block_until_ready(metrics["loss"])
                                jit_warm[variant] = True
                            steps_this_run += 1
                            transform = None
                            if faults.fire("nan_loss", step):
                                transform = _nan_loss_transform
                            if faults.fire("loss_spike", step):
                                transform = _loss_spike_transform
                            # Padding-waste accounting: loaders that pack
                            # (or segment) expose the cumulative non-pad
                            # fraction; the logger turns it into
                            # effective_tokens_per_sec, the ledger into the
                            # run-level non-pad goodput numbers. Loaders
                            # without the stat count as fully dense.
                            npf = getattr(train_loader, "non_pad_frac", None)
                            if npf is not None:
                                logger.non_pad_frac = float(npf)
                            ledger.add_tokens(
                                trainer.tokens_per_step,
                                None if npf is None else int(round(
                                    trainer.tokens_per_step * float(npf))),
                            )
                            consume(deferred.push(step, metrics, transform))
                    if heartbeat is not None:
                        heartbeat.beat(step + 1)
                    wd_rec = watchdog.observe(step, batch,
                                              expected=expected_compile)
                    if wd_rec is not None:
                        wd_lines = [
                            f"recompile | step {step}: train step recompiled"
                            f" for {wd_rec['batch_abstract']} (executables: "
                            f"{wd_rec['executables']})"]
                        if wd_rec.get("storm"):
                            wd_lines.append(
                                "recompile | WARNING: steady-state "
                                f"recompilation ({wd_rec['recompiles_total']}"
                                " events after warmup) — input shapes are "
                                "churning; check the loader/bucketing")
                        logger.log_record(wd_rec, stdout_lines=wd_lines)
                    if not cost_emitted:
                        # One-time XLA cost model vs analytic FLOPs. Runs
                        # after the first step so .lower().compile() hits the
                        # executable cache instead of recompiling.
                        cost_emitted = True
                        cost = trainer.step_cost_analysis(state, batch)
                        if cost is not None:
                            analytic = (flops_per_token(
                                model_config, training_config.max_seq_len)
                                * trainer.tokens_per_step)
                            rec = {"kind": "cost_analysis", "step": step}
                            rec.update(cost)
                            rec["analytic_flops_per_step"] = analytic
                            lines = []
                            if cost.get("flops_per_step"):
                                rec["analytic_over_xla"] = (
                                    analytic / cost["flops_per_step"])
                                lines.append(
                                    "cost_analysis | xla "
                                    f"{cost['flops_per_step']:.3e} flops/step"
                                    f" | analytic {analytic:.3e}"
                                    f" (x{rec['analytic_over_xla']:.2f})")
                            if cost.get("peak_bytes"):
                                lines.append(
                                    "cost_analysis | predicted peak HBM "
                                    f"{cost['peak_bytes'] / 2**30:.2f} GiB")
                            logger.log_record(rec, stdout_lines=lines)
                        if data_opts["comms_model"]:
                            # One-time analytic collective-traffic record,
                            # cross-checked against the collectives GSPMD
                            # actually put in the compiled step's HLO.
                            try:
                                comms = comms_lib.build(trainer)
                                comms["step"] = step
                                hlo = trainer.compiled_step_text(state, batch)
                                if hlo:
                                    comms.update(
                                        comms_lib.crosscheck(comms, hlo))
                                logger.log_record(
                                    comms,
                                    stdout_lines=comms_lib.summary_lines(
                                        comms))
                            except Exception as comms_err:
                                if main:
                                    print("comms_model failed: "
                                          f"{type(comms_err).__name__}: "
                                          f"{comms_err}", flush=True)
                    if tel_step:
                        logger.log_record(ledger.record(step=step))
                    eval_now = (training_config.eval_interval > 0
                                and (step + 1) % training_config.eval_interval == 0)
                    save_now = (training_config.save_interval > 0
                                and (step + 1) % training_config.save_interval == 0)
                    if eval_now or save_now:
                        # Boundary: materialize outstanding metric futures
                        # so eval records order after the train records and
                        # the checkpoint's tokens_seen count is exact (the
                        # eval/snapshot sync pays the device wait anyway).
                        consume(deferred.drain())
                    if eval_now:
                        run_eval()
                    if save_now:
                        save()
                    # The preempt decision must be unanimous: the checkpoint
                    # save is a collective, so one host's SIGTERM pulls every
                    # host in. The cross-host vote is itself a collective, so
                    # on pods it runs at a fixed cadence every host hits at
                    # the same step (not on the local flag, which would
                    # desynchronize the allgather).
                    vote_now = (trainer.process_count == 1
                                or (step + 1)
                                % data_opts["preempt_vote_interval"] == 0)
                    if vote_now and mesh_lib.global_any(
                            preempted["hit"] or has_notice):
                        proactive = not preempted["hit"]
                        if main:
                            print("proactive drain: checkpointing and "
                                  "exiting before the kill lands"
                                  if proactive else
                                  "SIGTERM received: checkpointing and "
                                  "exiting")
                        consume(deferred.drain(), check=False)
                        grace = data_opts["preemption_grace_s"]
                        deadline = None
                        rec = notice["rec"]
                        if rec is not None and rec.deadline_unix is not None:
                            # The notice named the kill time; anchor the
                            # drain budget there, not at vote time.
                            deadline = (time.monotonic()
                                        + (rec.deadline_unix - time.time()))
                        elif grace and grace > 0:
                            deadline = (preempted["at"] or time.monotonic()
                                        ) + grace
                        save("preempt", wait=True, deadline=deadline)
                        if rec is not None and hb_dir:
                            # Deregister: the supervisor treats a drain
                            # marker as a planned departure (reform without
                            # this host), not a crash.
                            flight_lib.write_drain(
                                hb_dir, trainer.process_index,
                                step=int(state.step), cause=rec.source,
                                deadline_unix=rec.deadline_unix)
                        dump_flight("preempt_notice" if proactive
                                    else "sigterm")
                        if proactive:
                            mesh_lib.shutdown_distributed()
                        return 143
                consume(deferred.drain())
                save("final", wait=True)
                if not (training_config.eval_interval > 0
                        and step + 1 == training_config.max_steps
                        and (step + 1) % training_config.eval_interval == 0):
                    run_eval()  # skip only when the last step just ran eval
                break
            except (FloatingPointError, guards.DivergenceError) as err:
                if rollbacks >= max_rollbacks:
                    if main:
                        print(f"divergence persisted after {rollbacks} "
                              f"rollback(s); giving up", flush=True)
                    raise
                # The feed's cursor at failure points just past the last
                # batch the trainer consumed (with deferred sync, up to the
                # window past the batch that actually diverged); capture it
                # before the restore below rewinds the loader.
                failure_cursor = feed.state_dict()
                rollbacks += 1
                backoff = data_opts["rollback_lr_backoff"] ** rollbacks
                if backoff != 1.0:
                    # The LR schedule is traced into the jitted step as a
                    # pure function of the config, so backing off means
                    # rebuilding the trainer (a recompile — acceptable for
                    # an event this rare).
                    training_config = dataclasses.replace(
                        training_config, learning_rate=base_lr * backoff)
                    trainer = Trainer(model_config, training_config,
                                      parallel_config)
                    jit_warm = {"step": False, "telemetry": False}
                if spike is not None:
                    # The restored loss level predates the whole window;
                    # stale history would re-fire on the first post-rollback
                    # loss and burn the rollback budget.
                    spike.reset()
                # Un-matured metric futures predate the rollback: reading
                # them now would log pre-failure steps after the rollback
                # record; drop them (the window is a few steps of logs).
                deferred = telemetry_lib.DeferredFetcher(
                    window=_DEFERRED_SYNC_WINDOW)
                replay_until = step  # re-covered ground is not fresh goodput
                # An in-flight async commit may be writing (and GC-ing) the
                # very tree restore_latest is about to scan: drain it first.
                drain_save()
                with ledger.track("checkpoint_restore"):
                    restored = ckpt_lib.restore_latest(
                        training_config.checkpoint_dir, trainer, verify=True)
                if restored is None:
                    if main:
                        print("rollback impossible: no valid checkpoint to "
                              "rewind to", flush=True)
                    raise
                state, meta, ckpt_path = restored
                logger.tokens_seen = meta.get("tokens_seen", 0)
                skip = data_opts["skip_batches_on_rollback"]
                if hasattr(train_loader, "load_state_dict"):
                    if skip > 0 and failure_cursor is not None:
                        # Resume the data just past the diverging batch
                        # (failure cursor - 1 + skip) instead of replaying it.
                        cursor = dict(failure_cursor)
                        cursor["batch_index"] += skip - 1
                        train_loader.load_state_dict(cursor)
                    elif meta.get("data_state") is not None:
                        train_loader.load_state_dict(meta["data_state"])
                if hasattr(data_iter, "close"):
                    data_iter.close()
                data_iter = iter(train_loader)
                # Buffered batches belong to the abandoned timeline; a fresh
                # feed re-bases its cursor on the rewound loader.
                feed = make_feed()
                # The rebuilt trainer (LR backoff) has a fresh executable
                # cache; re-arm the watchdog on it either way so the
                # watermark matches the trainer actually stepping.
                watchdog = RecompileWatchdog(trainer)
                logger.log_record({
                    "kind": "rollback",
                    "step": int(step),
                    "rollback": rollbacks,
                    "max_rollbacks": max_rollbacks,
                    "cause": type(err).__name__,
                    "restored_step": int(state.step),
                    "lr_backoff": backoff,
                })
                dump_flight(f"rollback:{type(err).__name__}", exc=err)
                if main:
                    print(f"rollback {rollbacks}/{max_rollbacks}: "
                          f"{type(err).__name__} at step {step}; rewound to "
                          f"{ckpt_path} (step {int(state.step)}), lr x "
                          f"{backoff:g}, skipping {skip} batch(es)",
                          flush=True)
        logger.log_record(ledger.record(step=int(state.step), final=True),
                          stdout_lines=ledger.summary_lines())
    except (FloatingPointError, guards.DivergenceError) as err:
        dump_flight("divergence", exc=err)
        raise  # poisoned state: never crash-save it
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as err:
        dump_flight("crash", exc=err)
        # Best-effort crash checkpoint: only after real progress this run
        # (an immediate failure would just overwrite good state with noise).
        if steps_this_run >= 1:
            try:
                save("crash", wait=True)
            except Exception as save_err:
                if main:
                    print(f"crash checkpoint failed: {save_err}", flush=True)
        raise
    finally:
        if saver is not None and saver.in_flight:
            # Exception exits that skipped the terminal-save drains: let the
            # last scheduled checkpoint land rather than orphaning it
            # (best-effort — an incomplete tree is crash-safe regardless).
            try:
                drain_save()
            except Exception as commit_err:
                if main:
                    print(f"async checkpoint commit failed: {commit_err}",
                          flush=True)
        signal.signal(signal.SIGTERM, old_handler)
        profiler.close()
        logger.close()
        if metrics_server is not None:
            metrics_server.close()
        if installed_plan is not None:
            faults.clear()
    if main:
        print(f"done: {steps_this_run} steps this run, "
              f"{logger.tokens_seen:,} tokens total")
    return 0
