"""One-command on-hardware validation lane (VERDICT r3 item 7).

The CPU test suite cannot reach the compiled-only TPU code paths: the
Pallas kernels run there in interpret mode (one head per program,
multiply-xorshift dropout hash), while a compiled TPU run uses head-PAIR
programs at d=64, the core's hardware PRNG in fixed 512x512 tiles, and the
odd-head zero-pad; ``pinned_host`` offload and the axon memory-analysis
path likewise only exist on the chip. This module re-proves all of them
with ONE command, meant to run every round after any kernel change::

    python -m tpu_trainer.validate --tpu
    python bench.py --validate          # same lane, driver-friendly

Checks (each prints PASS/FAIL/SKIP; exit code 1 on any failure):

1-9.  The flash-kernel checks from round 3 (hw-PRNG determinism/variation,
      dropout unbiasedness, mask equality across tilings and iteration
      orders, linear-in-v gradient identity under mixed fwd/bwd tiling,
      odd-head-count outputs + grads, GQA vs repeated-KV oracle).
10.   Offload bitwise: the ``pinned_host``-offloaded train step produces
      bit-identical losses to the on-device step over 5 steps (f32
      storage), on the real chip's memory spaces.
11.   Offload int8: the blockwise-quantized stream trains to a loss within
      5% of the exact run over 8 steps.
12.   A compiled bf16 train step (flash kernel + fused CE + optimizer)
      runs and the loss is finite — the full production graph, not just
      the kernel.
13.   (>=2 devices only; SKIP on one chip) a 1F1B pipeline step on a real
      ``stage`` axis.

Referenced from benchmarks/results.md; replaces the hand-run
``benchmarks/validate_kernel_tpu.py`` (now a shim over this module).
"""

from __future__ import annotations

import sys

FAILURES = []


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}  {detail}")
    if not ok:
        FAILURES.append(name)


def skip(name, why):
    print(f"SKIP  {name}  ({why})")


def _kernel_checks():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_trainer.ops.flash import _keep, flash_attention

    def mask_via_kernel(bq, bk, seq, order, seed=0xFEEDBEEF, rate=0.25):
        """Extract the hw keep mask for the full [seq, seq] block grid,
        generating per (bq, bk) block in the given iteration order."""
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kern(seed_ref, o_ref):
            blocks = [(a, c) for a in range(0, seq, bq)
                      for c in range(0, seq, bk)]
            if order == "kmajor":
                blocks = [(a, c) for c in range(0, seq, bk)
                          for a in range(0, seq, bq)]
            for a, c in blocks:
                m = _keep(seed_ref[0, 0], jnp.uint32(5), a, c, bq, bk, seq,
                          rate, True)
                o_ref[a:a + bq, c:c + bk] = m.astype(jnp.int32)

        return np.asarray(pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_shape=jax.ShapeDtypeStruct((seq, seq), jnp.int32),
        )(jnp.full((1, 1), seed, jnp.uint32)))

    b, s, h, d = 2, 1024, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)
    rng = jax.random.PRNGKey(7)

    # 1. determinism / seed variation
    f = jax.jit(lambda q, k, v, r: flash_attention(
        q, k, v, dropout_rate=0.25, dropout_rng=r))
    o1, o2 = np.asarray(f(q, k, v, rng)), np.asarray(f(q, k, v, rng))
    o3 = np.asarray(f(q, k, v, jax.random.PRNGKey(8)))
    check("determinism per seed", np.array_equal(o1, o2))
    check("varies across seeds", not np.allclose(o1, o3))

    # 2. unbiasedness
    base = np.asarray(jax.jit(
        lambda q, k, v: flash_attention(q, k, v))(q, k, v)).astype(np.float64)
    acc = np.zeros_like(base)
    n = 32
    for i in range(n):
        acc += np.asarray(f(q, k, v, jax.random.PRNGKey(100 + i))
                          ).astype(np.float64)
    err = np.abs((acc / n)[:, 64:] - base[:, 64:]).mean()
    check("dropout unbiasedness", err < 0.05, f"mean|bias|={err:.4f}")

    # 3+4. mask tile equality across tilings and orders
    big = mask_via_kernel(1024, 1024, 1024, "qmajor")
    small = mask_via_kernel(512, 512, 1024, "qmajor")
    small_k = mask_via_kernel(512, 512, 1024, "kmajor")
    check("mask equal across tilings", np.array_equal(big, small),
          f"keep rate {big.mean():.4f}")
    check("mask equal across orders", np.array_equal(small, small_k))

    # 5. linear-in-v fd with mixed fwd(1024)/bwd(512) tiling
    qf, kf, vf = (x.astype(jnp.float32) for x in (q[:1], k[:1], v[:1]))
    probe = jax.random.normal(jax.random.PRNGKey(14), qf.shape, jnp.float32)
    direction = jax.random.normal(jax.random.PRNGKey(15), vf.shape,
                                  jnp.float32)

    def loss(vv):
        return jnp.sum(flash_attention(
            qf, kf, vv, dropout_rate=0.25, dropout_rng=rng) * probe)

    an = float(jnp.sum(jax.jit(jax.grad(loss))(vf) * direction))
    lp = jax.jit(loss)
    fd = (float(lp(vf + direction)) - float(lp(vf - direction))) / 2.0
    rel = abs(fd - an) / max(abs(an), 1e-9)
    check("linear-in-v grad identity", rel < 0.05,
          f"relerr={rel:.2e} (eval rounding ~1e-2 on this chip)")

    # 6. odd head count (zero-pad head)
    q25 = jax.random.normal(ks[0], (1, 256, 25, 64), jnp.bfloat16)
    k25 = jax.random.normal(ks[1], (1, 256, 25, 64), jnp.bfloat16)
    v25 = jax.random.normal(ks[2], (1, 256, 25, 64), jnp.bfloat16)

    def loss25(qq):
        return jnp.sum(flash_attention(qq, k25, v25).astype(jnp.float32))

    out25 = np.asarray(jax.jit(lambda a, b_, c: flash_attention(a, b_, c))(
        q25, k25, v25))
    out24 = np.asarray(jax.jit(lambda a, b_, c: flash_attention(a, b_, c))(
        q25[:, :, :24], k25[:, :, :24], v25[:, :, :24]))
    outlast = np.asarray(jax.jit(lambda a, b_, c: flash_attention(a, b_, c))(
        q25[:, :, 23:25], k25[:, :, 23:25], v25[:, :, 23:25]))
    ok = np.allclose(out25[:, :, :24], out24, atol=2e-2) and np.allclose(
        out25[:, :, 24], outlast[:, :, 1], atol=2e-2)
    check("odd head count (25)", ok)
    g25 = jax.jit(jax.grad(loss25))(q25)
    check("odd head grads finite",
          np.isfinite(np.asarray(g25, dtype=np.float32)).all())

    # 7. GQA (2 kv heads for 4 query heads) vs repeated-KV oracle
    kg = jax.random.normal(ks[1], (b, s, 2, d), jnp.bfloat16)
    vg = jax.random.normal(ks[2], (b, s, 2, d), jnp.bfloat16)
    got = np.asarray(jax.jit(lambda a, b_, c: flash_attention(a, b_, c))(
        q, kg, vg))
    krep = jnp.repeat(kg, 2, axis=2)
    vrep = jnp.repeat(vg, 2, axis=2)
    want = np.asarray(jax.jit(lambda a, b_, c: flash_attention(a, b_, c))(
        q, krep, vrep))
    check("GQA vs repeated-KV oracle", np.allclose(got, want, atol=2e-2))

    # 8. Pallas fused head+CE (ops/head_ce.py) vs the XLA blockwise loss —
    # compiled path at headline-like shapes (incl. the ragged vocab edge).
    from tpu_trainer.ops.head_ce import pallas_head_ce
    from tpu_trainer.ops.loss import _chunk_len, _chunked_ce

    bh, sh, hh, V = 4, 1024, 256, 50257
    kk = jax.random.split(jax.random.PRNGKey(21), 3)
    embw = jax.random.normal(kk[0], (V, hh), jnp.float32) * 0.02
    xh = jax.random.normal(kk[1], (bh, sh, hh), jnp.bfloat16)
    labs = jax.random.randint(kk[2], (bh, sh), 0, V)
    maskh = (jax.lax.broadcasted_iota(jnp.int32, (bh, sh), 1)
             < sh - 1).astype(jnp.float32)

    def _o(e_, x_):
        return _chunked_ce(e_, x_, labs, maskh, _chunk_len(bh, sh, 0))

    def _p(e_, x_):
        return pallas_head_ce(e_, x_, labs, maskh, None, False)

    (lo, go) = jax.jit(jax.value_and_grad(_o, argnums=(0, 1)))(embw, xh)
    (lp, gp) = jax.jit(jax.value_and_grad(_p, argnums=(0, 1)))(embw, xh)
    dl = abs(float(lo) - float(lp))
    de = float(jnp.max(jnp.abs(go[0] - gp[0])))
    dx = float(jnp.max(jnp.abs(go[1].astype(jnp.float32)
                               - gp[1].astype(jnp.float32))))
    check("fused head+CE kernel vs XLA loss",
          dl < 1e-4 and de < 1e-4 and dx < 1e-4,
          f"dloss={dl:.1e} dE={de:.1e} dx={dx:.1e}")


def _tiny_trainer(offload=False, offload_dtype="float32",
                  mixed_precision="fp32", flash=False, mesh_kw=None,
                  model_kw=None):
    from tpu_trainer.models.config import GPTConfig
    from tpu_trainer.parallel.mesh import MeshConfig
    from tpu_trainer.training.config import TrainingConfig
    from tpu_trainer.training.trainer import ParallelConfig, Trainer

    model = GPTConfig(
        vocab_size=256, hidden_size=128, num_layers=2, num_heads=2,
        max_seq_len=128, dropout=0.0, attention_dropout=0.0,
        use_flash_attention=flash, **(model_kw or {}),
    )
    train = TrainingConfig(
        batch_size=2, max_seq_len=128, gradient_accumulation_steps=1,
        mixed_precision=mixed_precision, warmup_steps=2, max_steps=50,
    )
    return Trainer(
        model, train,
        ParallelConfig(MeshConfig(**(mesh_kw or {"data": 1, "fsdp": -1})),
                       "zero3", cpu_offload=offload,
                       offload_dtype=offload_dtype),
    )


def _offload_checks():
    import numpy as np

    batch = np.random.default_rng(0).integers(0, 256, (2, 128), np.int32)

    def run(offload, dtype="float32", steps=5):
        t = _tiny_trainer(offload=offload, offload_dtype=dtype)
        if offload and not t.cpu_offload:
            return None
        state = t.init_state(seed=0)
        out = []
        for _ in range(steps):
            state, m = t.train_step(state, batch)
            out.append(float(m["loss"]))
        return out

    base = run(False)
    off = run(True)
    if off is None:
        skip("offload bitwise", "no pinned_host memory space here")
    else:
        check("offload bitwise (f32 storage)", off == base,
              f"losses {off[-1]:.6f} vs {base[-1]:.6f}")
    q = run(True, "int8", steps=8)
    base8 = run(False, steps=8)
    if q is None:
        skip("offload int8", "no pinned_host memory space here")
    else:
        rel = abs(q[-1] - base8[-1]) / max(abs(base8[-1]), 1e-9)
        check("offload int8 curve", rel < 0.05 and q[-1] < q[0],
              f"rel={rel:.3f}")


def _step_checks():
    import jax
    import numpy as np

    # 12. the full production graph: bf16 + flash kernel + fused CE.
    t = _tiny_trainer(mixed_precision="bf16", flash=True)
    state = t.init_state(seed=0)
    batch = np.random.default_rng(1).integers(0, 256, (2, 128), np.int32)
    state, m = t.train_step(state, batch)
    loss = float(m["loss"])
    check("bf16 flash train step", np.isfinite(loss), f"loss={loss:.4f}")
    ma = t.step_memory_analysis(state, batch)
    check("compiled memory_analysis", ma is not None and ma["peak_bytes"] > 0,
          f"peak={ma['peak_bytes'] / 2**20:.1f} MiB" if ma else "")

    # 13. 1F1B on a real stage axis (needs >= 2 devices).
    if jax.device_count() >= 2:
        t2 = _tiny_trainer(
            mixed_precision="bf16", flash=True,
            mesh_kw={"data": 1, "fsdp": 1, "stage": 2},
            model_kw={"pipeline_schedule": "1f1b",
                      "pipeline_microbatches": 2},
        )
        st = t2.init_state(seed=0)
        st, m2 = t2.train_step(st, batch)
        check("1F1B pipeline step", np.isfinite(float(m2["loss"])))
    else:
        skip("1F1B pipeline step", "needs >= 2 devices; CPU suite covers it")


def main(argv=None) -> int:
    import argparse

    import jax

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tpu", action="store_true",
                   help="require a TPU (fail instead of skipping)")
    args = p.parse_args(argv)
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if args.tpu and not on_tpu:
        print("FAIL  no TPU present (run the CPU suite for interpret mode)")
        return 1
    if on_tpu:
        _kernel_checks()
    else:
        skip("kernel checks 1-9", "no TPU; interpret mode is CPU-suite land")
    _offload_checks()
    _step_checks()
    print(f"\n{len(FAILURES)} failure(s)" if FAILURES
          else "\nall checks passed")
    return 1 if FAILURES else 0


if __name__ == "__main__":
    sys.exit(main())
