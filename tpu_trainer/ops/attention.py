"""Attention ops: jnp reference path + fused ("flash") path.

Mirrors the two attention paths of the reference
(``/root/reference/src/models/gpt.py:199-234``):

- ``reference_attention`` — the manual path (``gpt.py:230-234``): QK^T/sqrt(d)
  → causal mask → float32 softmax → dropout → @V. Kept as the numerics oracle
  for the fused kernel, exactly as the reference keeps its manual branch.
- ``flash_attention`` — the fused path (``gpt.py:199-206`` calls torch's
  ``scaled_dot_product_attention``). Here this dispatches to the Pallas TPU
  kernel (``tpu_trainer.ops.flash``) when available, falling back to XLA's
  fused attention otherwise.

All functions take ``q, k, v`` of shape ``[batch, seq, num_heads, head_dim]``
(BSHD layout — the natural layout for TPU, avoiding the transpose the reference
does for torch's BHSD convention).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def causal_mask(seq_len: int) -> jax.Array:
    """Boolean [seq, seq] mask, True where attention is allowed (lower tri)."""
    return jnp.tril(jnp.ones((seq_len, seq_len), dtype=jnp.bool_))


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Manual causal attention (reference ``gpt.py:230-234``).

    float32 softmax for stability (the reference passes ``dtype=torch.float32``
    to softmax), dropout applied to the attention weights.
    """
    _, s, _, d = q.shape
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = causal_mask(s)
    scores = jnp.where(mask[None, None, :, :], scores, jnp.finfo(scores.dtype).min)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    rope: Optional[tuple] = None,
) -> jax.Array:
    """Fused causal attention (reference flash path, ``gpt.py:199-206``).

    Dispatches to the Pallas TPU kernel when running on TPU — including
    training with attention-weight dropout (counter-based in-kernel mask)
    and RoPE fused into the kernel when ``rope=(cos, sin)`` is given.
    Off-TPU, applies rope externally and uses XLA's fused attention, with
    the manual path covering the dropout case (same semantics as the
    reference's manual branch).
    """
    active_dropout = dropout_rate > 0.0 and not deterministic
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if on_tpu:
        try:
            from tpu_trainer.ops import flash
        except ImportError:
            flash = None  # degrade to the XLA/manual paths below
        if flash is not None:
            return flash.flash_attention(
                q, k, v, causal=True,
                dropout_rate=dropout_rate if active_dropout else 0.0,
                dropout_rng=dropout_rng,
                rope=rope,
            )
    if rope is not None:
        from tpu_trainer.ops.rope import apply_rotary_pos_emb

        q, k = apply_rotary_pos_emb(q, k, rope[0], rope[1])
    if active_dropout:
        return reference_attention(
            q, k, v,
            dropout_rate=dropout_rate,
            deterministic=deterministic,
            dropout_rng=dropout_rng,
        )
    return jax.nn.dot_product_attention(q, k, v, is_causal=True)
