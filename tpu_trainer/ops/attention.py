"""Attention ops: jnp reference path + fused ("flash") path.

Mirrors the two attention paths of the reference
(``/root/reference/src/models/gpt.py:199-234``):

- ``reference_attention`` — the manual path (``gpt.py:230-234``): QK^T/sqrt(d)
  → causal mask → float32 softmax → dropout → @V. Kept as the numerics oracle
  for the fused kernel, exactly as the reference keeps its manual branch.
- ``flash_attention`` — the fused path (``gpt.py:199-206`` calls torch's
  ``scaled_dot_product_attention``). Here this dispatches to the Pallas TPU
  kernel (``tpu_trainer.ops.flash``) when available, falling back to XLA's
  fused attention otherwise.

All functions take ``q, k, v`` of shape ``[batch, seq, num_heads, head_dim]``
(BSHD layout — the natural layout for TPU, avoiding the transpose the reference
does for torch's BHSD convention).
"""

from __future__ import annotations

import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

# Test hook: route the dispatch to the Pallas kernel in interpret mode even
# off-TPU, so the fake-8-device CPU mesh tests exercise the kernel (and its
# shard_map wrapping) end to end. Set TPU_TRAINER_FLASH_INTERPRET=1.
_INTERPRET_ENV = "TPU_TRAINER_FLASH_INTERPRET"


def causal_mask(seq_len: int) -> jax.Array:
    """Boolean [seq, seq] mask, True where attention is allowed (lower tri)."""
    return jnp.tril(jnp.ones((seq_len, seq_len), dtype=jnp.bool_))


def repeat_kv(k: jax.Array, v: jax.Array, num_heads: int):
    """Expand grouped K/V heads to ``num_heads`` by contiguous-group repeat.

    THE query-to-KV-head mapping: query head ``i`` reads K/V head
    ``i // (num_heads // kv_heads)`` — the same contiguous-group order the
    flash kernel's BlockSpec index map uses (``ops/flash.py``). Every
    jnp-level GQA expansion goes through here so the mapping is pinned in
    one place.
    """
    kvh = k.shape[2]
    if kvh == num_heads:
        return k, v
    group = num_heads // kvh
    return jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2)


def _flash_mesh(q: jax.Array):
    """The active mesh context's mesh, when sharding the kernel is useful.

    Returns None (plain kernel call — GSPMD sees one device, nothing to
    partition) unless a mesh with a non-trivial ``data``/``fsdp``/``tensor``
    axis is published by the trainer (``parallel/context.py``). Attention is
    independent across batch and heads, so those axes shard the kernel
    losslessly; the ``sequence`` axis is the ring path's job and never
    reaches this dispatch (the model routes SP through ``ops/ring.py``).
    """
    from tpu_trainer.parallel.context import current_mesh

    mesh = current_mesh()
    if mesh is None:
        return None
    from tpu_trainer.parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS

    sizes = [
        mesh.shape.get(DATA_AXIS, 1),
        mesh.shape.get(FSDP_AXIS, 1),
        mesh.shape.get(TENSOR_AXIS, 1),
    ]
    if all(s == 1 for s in sizes):
        return None
    return mesh


def _sharded_kernel(q, k, v, mesh, kernel_kwargs):
    """Run the Pallas kernel under ``shard_map`` over batch/head mesh axes.

    A ``pallas_call`` is opaque to the SPMD partitioner: left inside a GSPMD
    region on a multi-device mesh it forces replication (all-gather of
    q/k/v). Wrapping it in ``shard_map`` over the axes attention is
    independent along — batch over ``data`` x ``fsdp``, heads over
    ``tensor`` — runs the unchanged kernel on each shard with zero
    communication. Axes that don't divide the dim (tiny test batches) stay
    replicated, mirroring ``ring_attention``'s spec fallback.

    In-kernel dropout stays decorrelated across shards by folding each
    shard's mesh coordinates into the PRNG key (the kernel's counter-based
    mask hashes *local* positions, which coincide across shards).
    """
    from tpu_trainer.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tpu_trainer.parallel.mesh import (
        attention_shard_coord, attention_shard_spec,
    )
    from tpu_trainer.ops import flash

    b, _, h, _ = q.shape
    b_spec, h_spec = attention_shard_spec(mesh, b, h, k.shape[2])
    if b_spec is None and h_spec is None:
        return flash.flash_attention(q, k, v, **kernel_kwargs)
    spec = P(b_spec, None, h_spec, None)

    # Traced values (rng key, rope tables, segment ids) enter shard_map as
    # explicit arguments, not closure captures. Segment ids shard with the
    # batch axis like every other per-row operand.
    static_kwargs = dict(kernel_kwargs)
    rng = static_kwargs.pop("dropout_rng")
    rope_tabs = static_kwargs.pop("rope")
    seg = static_kwargs.pop("segment_ids", None)
    has_rng = rng is not None
    has_rope = rope_tabs is not None
    has_seg = seg is not None
    extras = (() if not has_rng else (rng,)) + (
        tuple(rope_tabs) if has_rope else ()
    ) + ((seg,) if has_seg else ())
    extra_specs = (() if not has_rng else (P(),)) + (
        (P(None, None), P(None, None)) if has_rope else ()
    ) + ((P(b_spec, None),) if has_seg else ())

    def local(q, k, v, *extra):
        i = 0
        rng_local = None
        if has_rng:
            # Decorrelate the in-kernel dropout mask across (sharded-axis)
            # shards — see attention_shard_coord.
            coord = attention_shard_coord(mesh, b_spec, h_spec)
            rng_local = jax.random.fold_in(extra[0], coord)
            i = 1
        rope_local = (extra[i], extra[i + 1]) if has_rope else None
        if has_rope:
            i += 2
        seg_local = extra[i] if has_seg else None
        return flash.flash_attention(
            q, k, v, dropout_rng=rng_local, rope=rope_local,
            segment_ids=seg_local, **static_kwargs
        )

    # Manual only over the axes this wrapper actually shards: other axes
    # (e.g. a pipeline `stage` axis whose manual region we may be nested
    # inside) stay untouched, letting the kernel keep its batch/head
    # sharding inside the GPipe stage body. When tracing inside another
    # manual region, shard_map requires the *context* abstract mesh (same
    # axes, with the outer region's axes typed Manual) rather than the
    # concrete mesh.
    used_axes = set()
    if b_spec is not None:
        used_axes.update(b_spec)
    if h_spec is not None:
        used_axes.add(h_spec)
    try:
        from jax.sharding import get_abstract_mesh
    except ImportError:  # old jax: no abstract meshes — trace on the
        get_abstract_mesh = None  # concrete mesh as before

    sm_mesh = mesh
    if get_abstract_mesh is not None:
        ctx_mesh = get_abstract_mesh()
        if (getattr(ctx_mesh, "shape_tuple", ())
                and ctx_mesh.shape == mesh.shape):
            sm_mesh = ctx_mesh
    fn = shard_map(
        local,
        mesh=sm_mesh,
        in_specs=(spec, spec, spec) + extra_specs,
        out_specs=spec,
        axis_names=used_axes,
        check_vma=False,
    )
    return fn(q, k, v, *extras)


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """Boolean [batch, 1, seq, seq] mask, True where q and k positions share
    a segment id — the dense form of the kernels' packed-document
    isolation. Broadcastable against [batch, heads, q, k] score tensors."""
    return (segment_ids[:, None, :, None] == segment_ids[:, None, None, :])


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Manual causal attention (reference ``gpt.py:230-234``).

    float32 softmax for stability (the reference passes ``dtype=torch.float32``
    to softmax), dropout applied to the attention weights. Accepts grouped
    K/V (``num_kv_heads < num_heads``) by head repetition — the GQA oracle.
    ``segment_ids`` ([batch, seq] int) additionally restricts attention to
    same-segment pairs — the dense oracle for the packed flash kernels.
    """
    _, s, h, d = q.shape
    k, v = repeat_kv(k, v, h)
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = causal_mask(s)[None, None, :, :]
    if segment_ids is not None:
        mask = mask & segment_mask(segment_ids)
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    rope: Optional[tuple] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Fused causal attention (reference flash path, ``gpt.py:199-206``).

    Dispatches to the Pallas TPU kernel when running on TPU — including
    training with attention-weight dropout (counter-based in-kernel mask)
    and RoPE fused into the kernel when ``rope=(cos, sin)`` is given.
    Off-TPU, applies rope externally and uses XLA's fused attention, with
    the manual path covering the dropout case (same semantics as the
    reference's manual branch). ``segment_ids`` ([batch, seq] int)
    isolates attention within packed documents on every path.
    """
    active_dropout = dropout_rate > 0.0 and not deterministic
    interpret = os.environ.get(_INTERPRET_ENV, "0") == "1"
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if on_tpu or interpret:
        try:
            from tpu_trainer.ops import flash
        except ImportError:
            flash = None  # degrade to the XLA/manual paths below
        if flash is not None:
            kernel_kwargs = dict(
                causal=True,
                dropout_rate=dropout_rate if active_dropout else 0.0,
                dropout_rng=dropout_rng,
                rope=rope,
                interpret=interpret,
                segment_ids=segment_ids,
            )
            mesh = _flash_mesh(q)
            if mesh is not None:
                return _sharded_kernel(q, k, v, mesh, kernel_kwargs)
            return flash.flash_attention(q, k, v, **kernel_kwargs)
    if rope is not None:
        from tpu_trainer.ops.rope import apply_rotary_pos_emb

        q, k = apply_rotary_pos_emb(q, k, rope[0], rope[1])
    if active_dropout or segment_ids is not None:
        return reference_attention(
            q, k, v,
            dropout_rate=dropout_rate if active_dropout else 0.0,
            deterministic=deterministic and not active_dropout,
            dropout_rng=dropout_rng,
            segment_ids=segment_ids,
        )
    # jax.nn.dot_product_attention handles grouped K/V natively (K heads
    # dividing N) — pass the compact tensors straight through.
    return jax.nn.dot_product_attention(q, k, v, is_causal=True)
