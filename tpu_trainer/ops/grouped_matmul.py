"""Grouped (ragged) matmul for dropless MoE — Pallas kernel + jnp twin.

``gmm(lhs [G, H], rhs [E, H, N], group_sizes [E]) -> [G, N]`` multiplies
each contiguous row-group of ``lhs`` by its own expert weight block:
rows ``[offsets[e], offsets[e+1])`` (``offsets = cumsum(group_sizes)``)
hit ``rhs[e]``. This is the MegaBlocks grouped-GEMM primitive (arXiv:
2211.15841): expert FFN compute scales with the tokens actually routed
(``sum(group_sizes) == G``), not with a capacity-padded ``[E, C]``
buffer, so no token is ever dropped and no expert pays for an empty
queue.

Kernel shape (same dispatch contract as ``ops/flash.py`` — compiled on
TPU, the ``jnp`` reference twin off-TPU, ``interpret=True`` under
tests):

- The token dimension is cut into ``tile_tokens`` blocks and the kernel
  runs one grid step per (token-tile, group) *overlap* — a tile fully
  inside one group is visited once; a tile straddling ``b`` group
  boundaries is visited ``b + 1`` times, so the static grid bound is
  ``num_tiles + E - 1`` steps (ragged tails cost steps, not a second
  kernel).
- The schedule (which tile, which group, is this step live) is computed
  at trace time from ``group_sizes`` with O(E + steps) jnp work and
  **scalar-prefetched** into SMEM (``PrefetchScalarGridSpec``): the
  BlockSpec index maps read it to point each step's lhs/out blocks at
  the right token tile and its rhs block at the right expert — the
  weight block for step ``s`` is streaming into VMEM while step
  ``s - 1`` computes.
- Ragged boundaries are masked *in-block*: a boundary tile zeroes the
  rows outside ``[offsets[g], offsets[g+1])`` before the dot, and
  consecutive steps on the same output tile accumulate in VMEM (the
  revisit pattern — the block stays resident because the schedule
  orders steps by tile).

``tgmm`` is the transposed/dgrad variant (``lhs^T @ dout`` per group ->
``[E, H, N]``, the weight gradient); ``gmm``'s ``custom_vjp`` routes
d(lhs) through ``gmm`` against the transposed weights and d(rhs)
through ``tgmm``, so both backward passes reuse the same two kernels.

Two jnp twins exist. ``gmm_reference``/``tgmm_reference`` are the
*oracles* — ``jax.lax.ragged_dot`` / ``segment_sum``, the simplest
correct spelling, used by the tests as ground truth. The *dispatch*
twin (``_gmm_blocked``/``_tgmm_blocked``) replays the kernel's own tile
schedule in pure jnp — gather the scheduled (token-tile, expert-weight)
block pairs, one batched matmul over the steps, scatter-add back — which
XLA turns into a single dense batched GEMM plus cheap gathers
(~4x faster than ``ragged_dot``'s CPU lowering at bench shapes, and the
same masked-tile numerics as the kernel). The blocked twin is what runs
off-TPU (tier-1, the dropless bench lane) and under multi-device meshes,
where GSPMD can partition the jnp formulation but would treat an
un-shard_mapped ``pallas_call`` as an opaque replicated primitive.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

try:  # pragma: no cover - import guard mirrors ops/flash.py
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


class _GmmOpts(NamedTuple):
    """Static (hashable) dispatch knobs carried through the custom_vjp."""

    use_kernel: bool
    interpret: bool
    tile_tokens: int
    tile_cols: int


def _resolve_opts(use_kernel, interpret, tile_tokens, tile_cols) -> _GmmOpts:
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_kernel is None:
        # Same contract as ops/flash.py: the compiled kernel drives TPU,
        # everything else gets the reference twin (tests opt into the
        # kernel explicitly with use_kernel=True + interpret=True).
        use_kernel = jax.default_backend() == "tpu"
    return _GmmOpts(bool(use_kernel), bool(interpret),
                    int(tile_tokens), int(tile_cols))


# --- reference twin ---------------------------------------------------------

def _group_ids(group_sizes: jax.Array, num_rows: int) -> jax.Array:
    """Row -> group id, [G] int32 (rows past sum(group_sizes) get E)."""
    ends = jnp.cumsum(group_sizes)
    return jnp.searchsorted(
        ends, jnp.arange(num_rows, dtype=group_sizes.dtype), side="right"
    ).astype(jnp.int32)


def gmm_reference(lhs: jax.Array, rhs: jax.Array,
                  group_sizes: jax.Array) -> jax.Array:
    """jnp twin of ``gmm`` — ``jax.lax.ragged_dot`` where available.

    Accumulates in f32 and returns ``lhs.dtype`` (the kernel contract).
    """
    if hasattr(jax.lax, "ragged_dot"):
        out = jax.lax.ragged_dot(
            lhs, rhs, group_sizes.astype(jnp.int32),
            preferred_element_type=jnp.float32)
    else:  # pragma: no cover - jax without ragged_dot
        gid = _group_ids(group_sizes, lhs.shape[0])
        w = jnp.take(rhs, jnp.minimum(gid, rhs.shape[0] - 1), axis=0)
        out = jnp.einsum("gh,ghn->gn", lhs.astype(jnp.float32),
                         w.astype(jnp.float32))
    return out.astype(lhs.dtype)


def tgmm_reference(lhs: jax.Array, dout: jax.Array,
                   group_sizes: jax.Array) -> jax.Array:
    """jnp twin of ``tgmm``: per-group ``lhs^T @ dout -> [E, H, N]``."""
    E = group_sizes.shape[0]
    gid = _group_ids(group_sizes, lhs.shape[0])
    prod = (lhs.astype(jnp.float32)[:, :, None]
            * dout.astype(jnp.float32)[:, None, :])        # [G, H, N]
    return jax.ops.segment_sum(prod, gid, num_segments=E)


# --- blocked jnp twin (the off-TPU dispatch path) ---------------------------

def _blocked_inputs(lhs, group_sizes, tile):
    """Shared setup: pad to tiles, build the schedule, mask the scheduled
    lhs blocks. Returns ``(x [S, tile, H] masked, tiles, gids, num_tiles)``.
    """
    G, H = lhs.shape
    num_tiles = max(1, -(-G // tile))
    lhs_p = _pad_to(lhs, 0, tile)
    tiles, gids, lives, offs = _schedule(group_sizes, num_tiles, tile)
    blocks = lhs_p.reshape(num_tiles, tile, H)[tiles]       # [S, tile, H]
    rows = tiles[:, None] * tile + jnp.arange(tile)[None, :]
    mask = ((rows >= offs[gids][:, None]) & (rows < offs[gids + 1][:, None])
            & (lives[:, None] > 0))
    x = jnp.where(mask[..., None], blocks, jnp.zeros((), lhs.dtype))
    return x, tiles, gids, num_tiles


def _gmm_blocked(lhs, rhs, group_sizes, tile):
    """Kernel-schedule gmm in jnp: one batched GEMM over the grid steps.

    Step ``s`` multiplies the masked token tile ``tiles[s]`` by expert
    block ``rhs[gids[s]]``; tiles revisited across a group boundary are
    summed by the scatter-add exactly as the kernel's VMEM accumulation
    does. Padding steps are fully masked and add zero.
    """
    G = lhs.shape[0]
    x, tiles, gids, num_tiles = _blocked_inputs(lhs, group_sizes, tile)
    y = jnp.einsum("sth,shn->stn", x, rhs[gids],
                   preferred_element_type=jnp.float32)       # [S, tile, N]
    out = jnp.zeros((num_tiles, tile, rhs.shape[2]), jnp.float32)
    out = out.at[tiles].add(y)
    return out.reshape(num_tiles * tile, -1)[:G].astype(lhs.dtype)


def _tgmm_blocked(lhs, dout, group_sizes, tile):
    """Kernel-schedule tgmm in jnp: per-step ``x^T @ dout`` scatter-added
    into the owning expert's ``[H, N]`` block (f32, the tgmm contract).
    Avoids ``tgmm_reference``'s materialized ``[G, H, N]`` outer-product
    temp — the batched contraction keeps the temp at ``[S, H, N]``.
    """
    E = group_sizes.shape[0]
    x, tiles, gids, num_tiles = _blocked_inputs(lhs, group_sizes, tile)
    dout_p = _pad_to(dout, 0, tile)
    dblocks = dout_p.reshape(num_tiles, tile, -1)[tiles]     # [S, tile, N]
    y = jnp.einsum("sth,stn->shn", x, dblocks,
                   preferred_element_type=jnp.float32)       # [S, H, N]
    out = jnp.zeros((E, lhs.shape[1], dout.shape[1]), jnp.float32)
    return out.at[gids].add(y)


# --- the schedule -----------------------------------------------------------

def _schedule(group_sizes: jax.Array, num_tiles: int,
              tile_tokens: int) -> Tuple[jax.Array, ...]:
    """Trace-time (tile, group, live) arrays for the static step bound.

    Step ``s`` processes token tile ``tiles[s]`` against group
    ``gids[s]``; ``lives[s] == 0`` marks padding steps past the real
    work (the bound ``num_tiles + E - 1`` is only reached when every
    group boundary lands mid-tile). Both ``tiles`` and ``gids`` are
    nondecreasing — group e+1 starts where group e ends — which is what
    lets BOTH output indexings (by tile in gmm, by group in tgmm) see
    their revisits consecutively and accumulate in VMEM.
    """
    E = group_sizes.shape[0]
    sizes = group_sizes.astype(jnp.int32)
    ends = jnp.cumsum(sizes)
    starts = ends - sizes
    nonempty = sizes > 0
    first_tile = jnp.where(nonempty, starts // tile_tokens, 0)
    visits = jnp.where(
        nonempty, (ends - 1) // tile_tokens - first_tile + 1, 0)
    cum_visits = jnp.cumsum(visits)
    num_steps = num_tiles + E - 1
    s = jnp.arange(num_steps, dtype=jnp.int32)
    gid = jnp.searchsorted(cum_visits, s, side="right").astype(jnp.int32)
    live = (gid < E).astype(jnp.int32)
    gid_c = jnp.minimum(gid, E - 1)
    prev = jnp.where(gid_c > 0, cum_visits[jnp.maximum(gid_c - 1, 0)], 0)
    tile = first_tile[gid_c] + (s - prev.astype(jnp.int32))
    # Padding steps park on the last (tile, group) pair; the live mask
    # zeroes their contribution and — tiles/gids being clamped to the
    # maxima — they can never look like a fresh first visit of a block
    # that real work wrote.
    tile = jnp.where(live > 0, tile, num_tiles - 1)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), ends.astype(jnp.int32)])
    return tile.astype(jnp.int32), gid_c, live, offsets


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


# --- kernels ----------------------------------------------------------------

def _gmm_kernel(tiles, gids, lives, offs, lhs_ref, rhs_ref, out_ref, *,
                tile_tokens):
    s = pl.program_id(1)
    g = gids[s]
    rows = (tiles[s] * tile_tokens
            + jax.lax.broadcasted_iota(jnp.int32, (tile_tokens, 1), 0))
    mask = ((rows >= offs[g]) & (rows < offs[g + 1])
            & (lives[s] > 0))
    x = jnp.where(mask, lhs_ref[...], jnp.zeros_like(lhs_ref[...]))
    contrib = jnp.dot(x, rhs_ref[0],
                      preferred_element_type=jnp.float32)
    first = jnp.logical_or(s == 0, tiles[s] != tiles[jnp.maximum(s - 1, 0)])

    @pl.when(first)
    def _init():
        out_ref[...] = contrib

    @pl.when(jnp.logical_not(first))
    def _acc():
        out_ref[...] = out_ref[...] + contrib


def _tgmm_kernel(tiles, gids, lives, offs, lhs_ref, dout_ref, out_ref, *,
                 tile_tokens):
    s = pl.program_id(1)
    g = gids[s]
    rows = (tiles[s] * tile_tokens
            + jax.lax.broadcasted_iota(jnp.int32, (tile_tokens, 1), 0))
    mask = ((rows >= offs[g]) & (rows < offs[g + 1])
            & (lives[s] > 0))
    x = jnp.where(mask, lhs_ref[...], jnp.zeros_like(lhs_ref[...]))
    # Contract the token dim: [tile, H]^T @ [tile, N] -> [H, N].
    contrib = jax.lax.dot_general(
        x, dout_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    first = jnp.logical_or(s == 0, g != gids[jnp.maximum(s - 1, 0)])

    @pl.when(first)
    def _init():
        out_ref[0] = contrib

    @pl.when(jnp.logical_not(first))
    def _acc():
        out_ref[0] = out_ref[0] + contrib


def _gmm_pallas(lhs, rhs, group_sizes, opts: _GmmOpts):
    G, H = lhs.shape
    E, _, N = rhs.shape
    tm, tn = opts.tile_tokens, min(opts.tile_cols, max(N, 1))
    lhs_p = _pad_to(_pad_to(lhs, 0, tm), 1, 128)
    rhs_p = _pad_to(_pad_to(rhs, 1, 128), 2, tn)
    Gp, Hp = lhs_p.shape
    Np = rhs_p.shape[2]
    num_tiles = Gp // tm
    tiles, gids, lives, offs = _schedule(group_sizes, num_tiles, tm)
    grid = (Np // tn, tiles.shape[0])
    out = pl.pallas_call(
        functools.partial(_gmm_kernel, tile_tokens=tm),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, Hp),
                             lambda n, s, tiles, gids, lives, offs:
                             (tiles[s], 0)),
                pl.BlockSpec((1, Hp, tn),
                             lambda n, s, tiles, gids, lives, offs:
                             (gids[s], 0, n)),
            ],
            out_specs=pl.BlockSpec((tm, tn),
                                   lambda n, s, tiles, gids, lives, offs:
                                   (tiles[s], n)),
        ),
        out_shape=jax.ShapeDtypeStruct((Gp, Np), jnp.float32),
        interpret=opts.interpret,
    )(tiles, gids, lives, offs, lhs_p, rhs_p)
    return out[:G, :N].astype(lhs.dtype)


def _tgmm_pallas(lhs, dout, group_sizes, opts: _GmmOpts):
    G, H = lhs.shape
    N = dout.shape[1]
    E = group_sizes.shape[0]
    tm, tn = opts.tile_tokens, min(opts.tile_cols, max(N, 1))
    lhs_p = _pad_to(_pad_to(lhs, 0, tm), 1, 128)
    dout_p = _pad_to(_pad_to(dout, 0, tm), 1, tn)
    Gp, Hp = lhs_p.shape
    Np = dout_p.shape[1]
    num_tiles = Gp // tm
    tiles, gids, lives, offs = _schedule(group_sizes, num_tiles, tm)
    grid = (Np // tn, tiles.shape[0])
    out = pl.pallas_call(
        functools.partial(_tgmm_kernel, tile_tokens=tm),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, Hp),
                             lambda n, s, tiles, gids, lives, offs:
                             (tiles[s], 0)),
                pl.BlockSpec((tm, tn),
                             lambda n, s, tiles, gids, lives, offs:
                             (tiles[s], n)),
            ],
            out_specs=pl.BlockSpec((1, Hp, tn),
                                   lambda n, s, tiles, gids, lives, offs:
                                   (gids[s], 0, n)),
        ),
        out_shape=jax.ShapeDtypeStruct((E, Hp, Np), jnp.float32),
        interpret=opts.interpret,
    )(tiles, gids, lives, offs, lhs_p, dout_p)
    # Empty groups own no grid step, so their output blocks are never
    # written — replace whatever the backing buffer held with zeros.
    out = jnp.where(group_sizes[:, None, None] > 0, out, 0.0)
    return out[:, :H, :N]


# --- custom_vjp entry -------------------------------------------------------

def _gmm_dispatch(opts: _GmmOpts, lhs, rhs, group_sizes):
    if opts.use_kernel and _PALLAS_OK:
        return _gmm_pallas(lhs, rhs, group_sizes, opts)
    return _gmm_blocked(lhs, rhs, group_sizes, opts.tile_tokens)


def _tgmm_dispatch(opts: _GmmOpts, lhs, dout, group_sizes):
    if opts.use_kernel and _PALLAS_OK:
        return _tgmm_pallas(lhs, dout, group_sizes, opts)
    return _tgmm_blocked(lhs, dout, group_sizes, opts.tile_tokens)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _gmm(opts: _GmmOpts, lhs, rhs, group_sizes):
    return _gmm_dispatch(opts, lhs, rhs, group_sizes)


def _gmm_fwd(opts, lhs, rhs, group_sizes):
    return _gmm_dispatch(opts, lhs, rhs, group_sizes), (
        lhs, rhs, group_sizes)


def _gmm_bwd(opts, res, dout):
    lhs, rhs, group_sizes = res
    # dgrad: the same grouped matmul against the transposed weights
    # ([E, N, H] blocks); wgrad: the transposed variant.
    dlhs = _gmm_dispatch(
        opts, dout, jnp.swapaxes(rhs, 1, 2), group_sizes).astype(lhs.dtype)
    drhs = _tgmm_dispatch(opts, lhs, dout, group_sizes).astype(rhs.dtype)
    return dlhs, drhs, None


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


def gmm(lhs: jax.Array, rhs: jax.Array, group_sizes: jax.Array, *,
        use_kernel: Optional[bool] = None,
        interpret: Optional[bool] = None,
        tile_tokens: int = 128, tile_cols: int = 128) -> jax.Array:
    """Grouped matmul: row-groups of ``lhs`` times per-group weights.

    - ``lhs``: ``[G, H]`` rows SORTED by group (group e's rows are the
      contiguous slice ``[offsets[e], offsets[e+1])``).
    - ``rhs``: ``[E, H, N]`` stacked per-group weight blocks.
    - ``group_sizes``: ``[E]`` int, ``sum == G`` (enforced only by the
      caller — trailing rows past the sum produce zeros).

    Returns ``[G, N]`` in ``lhs.dtype`` (f32 accumulation either path).
    Differentiable via ``custom_vjp``: d(lhs) is a ``gmm`` against
    ``rhs^T``, d(rhs) a ``tgmm`` — ``group_sizes`` gets no gradient.

    ``use_kernel=None`` picks the Pallas kernel exactly on TPU (the
    reference twin elsewhere); ``interpret=True`` runs the kernel
    under the Pallas interpreter (the CPU test path).
    """
    opts = _resolve_opts(use_kernel, interpret, tile_tokens, tile_cols)
    if lhs.shape[0] == 0:
        return jnp.zeros((0, rhs.shape[2]), lhs.dtype)
    return _gmm(opts, lhs, rhs, group_sizes)


def tgmm(lhs: jax.Array, dout: jax.Array, group_sizes: jax.Array, *,
         use_kernel: Optional[bool] = None,
         interpret: Optional[bool] = None,
         tile_tokens: int = 128, tile_cols: int = 128) -> jax.Array:
    """Transposed grouped matmul (the wgrad): per-group
    ``lhs[slice]^T @ dout[slice]`` stacked to ``[E, H, N]`` f32.
    """
    opts = _resolve_opts(use_kernel, interpret, tile_tokens, tile_cols)
    if lhs.shape[0] == 0:
        return jnp.zeros(
            (group_sizes.shape[0], lhs.shape[1], dout.shape[1]),
            jnp.float32)
    return _tgmm_dispatch(opts, lhs, dout, group_sizes)
