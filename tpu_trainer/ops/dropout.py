"""Counter-based dropout: a fused-friendly alternative to threefry masks.

The reference applies ``nn.Dropout`` after the attention output projection
and the MLP (``/root/reference/src/models/gpt.py:241,282``). The direct JAX
translation (``jax.random.bernoulli`` per call site) runs threefry2x32 per
element — ~30 32-bit ALU ops each — and measures ~11 ms of the ~120 ms
headline step (24 masks of ~12.6M elements). This module derives the mask
from a murmur3-finalizer hash of the element's linear index instead (~6 ALU
ops), the same counter-based construction the flash kernel uses for its
in-kernel attention dropout (``ops/flash.py:_keep_mask``): cheap enough that
XLA fuses mask generation into the surrounding elementwise chain, and
deterministic given the PRNG key (the key collapses to a 32-bit seed).

Falls back to ``jax.random.bernoulli`` when the tensor has >= 2**32 elements
(index would overflow the uint32 counter).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _murmur_mix(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 — full avalanche on uint32."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_dropout(
    x: jax.Array,
    rate: float,
    rng: jax.Array,
    deterministic: bool = False,
) -> jax.Array:
    """Inverted dropout with a counter-based keep mask.

    Semantics match ``nn.Dropout``: each element is zeroed with probability
    ``rate`` and survivors are scaled by ``1 / (1 - rate)``; the mask is a
    deterministic function of ``rng``. Only the mask's bit stream differs
    (hash of the linear index vs threefry counters) — both are Bernoulli.
    """
    if deterministic or rate <= 0.0:
        return x
    if x.size >= 2**32:
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
    seed = jax.random.bits(rng, dtype=jnp.uint32)
    flat_iota = jax.lax.broadcasted_iota(
        jnp.uint32, (x.size,), 0
    ).reshape(x.shape)
    h = _murmur_mix(flat_iota ^ seed)
    threshold = jnp.uint32(min(int(rate * 2**32), 2**32 - 1))
    keep = h >= threshold
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
