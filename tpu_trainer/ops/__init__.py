from tpu_trainer.ops.attention import (
    causal_mask,
    flash_attention,
    reference_attention,
)

__all__ = ["causal_mask", "flash_attention", "reference_attention"]
