"""Rotary position embeddings (reference ``gpt.py:70-147`` — SURVEY.md C3).

Lives in ``ops`` (not ``models``) so the attention dispatch can apply it
without an import cycle: the fused flash path rotates q/k *inside* the
Pallas kernel, while the jnp/ring paths rotate here first. Tables are
recomputed under jit (XLA constant-folds them) and never checkpointed — the
reference persists them as buffers in every state_dict (SURVEY.md §2.1 b8).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rope_tables(
    seq_len: int, dim: int, base: float = 10000.0
) -> Tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables, shape ``[seq_len, dim]``.

    Matches the reference cache construction (``gpt.py:76-93``): inverse
    frequencies over even indices, angles tiled as ``concat(freqs, freqs)``.
    """
    inv_freq = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def rotate_half(x: jax.Array) -> jax.Array:
    """``[a, b, c, d] -> [-c, -d, a, b]`` (reference ``gpt.py:100-117``)."""
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_pos_emb(
    q: jax.Array, k: jax.Array, cos: jax.Array, sin: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Rotate q/k by position (reference ``gpt.py:120-147``).

    q, k: ``[batch, seq, heads, head_dim]``; cos, sin: ``[seq, head_dim]``,
    or ``[batch, seq, head_dim]`` for per-row positions (ragged decode:
    left-padded rows start their RoPE positions at their own first real
    token). Applied in float32, cast back to the inputs' dtype.
    """
    if cos.ndim == 3:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
    q_rot = q32 * cos + rotate_half(q32) * sin
    k_rot = k32 * cos + rotate_half(k32) * sin
    return q_rot.astype(q.dtype), k_rot.astype(k.dtype)
