"""Pallas fused LM-head + cross-entropy forward (flash-style softmax).

The XLA blockwise loss (``ops/loss.py``) is two HBM passes at headline
geometry: the head matmul writes a transient ``[tokens, vocab]`` f32 block
(~1.54 GB hidden behind the 190 TFLOP/s dot), then a logsumexp pass re-reads
all of it (~2.2 ms of pure HBM traffic on one v5e — the single removable
slice left in the round-4 step profile).

This kernel removes that pass the way flash attention removes the score
buffer: the logits tile is produced in VMEM by the MXU and the softmax
statistics (running row max ``m``, running scaled exp-sum ``s``, and the
exact-f32 label logit ``ll``) are folded in online before the tile leaves
the core. The logits are stored once, in COMPUTE dtype (bf16 — half the
f32 block the XLA path writes), solely as the backward's input; the loss
itself is ``(m + log s - ll)`` — exact f32 end to end (the label logit is
accumulated from the f32 MXU output, never the rounded store).

Backward is deliberately NOT Pallas: ``d logits = (softmax - onehot) * w``
feeds two roofline matmuls (``dx``, ``dE``) that XLA already fuses the
exp/onehot arithmetic into; its only change of regime is reading bf16
saved logits instead of CSE-reusing the f32 block, which rounds the
recomputed probabilities by 2^-9 — the same order as the flash kernel's
backward, which recomputes probabilities from bf16 q/k.

Grid: ``(vocab tiles, token tiles)``, token minor — the embedding tile is
loaded once per vocab tile (one full 77 MB sweep of E per step total) while
x re-reads scale with the vocab tile count. Running stats live in VMEM
scratch sized ``[1, padded tokens]`` and persist across the whole grid;
edge tiles rely on Pallas' masked stores plus an explicit column-validity
mask (cols >= vocab -> -1e30) so no operand is ever padded in HBM.

No reference counterpart (the reference materializes full logits into
``F.cross_entropy``, ``/root/reference/src/models/gpt.py:447-453``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = np.float32(-1e30)  # -inf stand-in (no inf-inf => NaN hazard)

# Tile shapes (one v5e core, 16 MB VMEM scope): x [256, H] + E [bv, H] +
# bf16 logits tile + f32 dot accumulator + double buffering. bv adapts to
# the hidden size — 2048 fits H=768 in ~11 MB, but H=1280 (gpt2-large)
# needs 1024 to stay under the scope (measured: 17.8 MB at bv=2048).
_BLOCK_T = 256


def _block_v(h: int, dtype_bytes: int) -> int:
    bt = _BLOCK_T
    for bv in (2048, 1536, 1024, 512):
        est = (2 * bv * h * dtype_bytes      # E tile, double-buffered
               + 2 * bt * h * dtype_bytes    # x tile, double-buffered
               + 2 * bv * bt * dtype_bytes   # logits out, double-buffered
               + bv * bt * 4)                # f32 dot accumulator
        if est <= 12 * 1024 * 1024:
            return bv
    return 256


def _head_ce_fwd_kernel(x_ref, e_ref, lab_ref, out_ref, m_ref, s_ref,
                        ll_ref, m_scr, s_scr, ll_scr, *, vocab: int,
                        block_t: int, block_v: int):
    v = pl.program_id(0)
    t = pl.program_id(1)

    # TRANSPOSED logits tile [bv, bt] — vocab-major comes free by swapping
    # the dot operands, and a [V, T] saved-logits layout (tokens minor) is
    # exactly what the backward's dx/dE matmuls consume without a relayout
    # (the row-major [T, V] variant measured a 5 ms copy + 4 ms convert in
    # the backward before the matmuls even started).
    lg = jax.lax.dot_general(
        e_ref[...], x_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bv, bt] f32

    # Global vocab row ids of this tile; mask the vocab overhang (the last
    # E tile reads out of bounds — Pallas gives undefined values there, and
    # -1e30 neutralizes them for max/exp/store alike).
    rows = v * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_v, block_t), 0
    )
    lg = jnp.where(rows < vocab, lg, _NEG)
    out_ref[...] = lg.astype(out_ref.dtype)

    # Label logit: the label's row lands in exactly one vocab tile.
    lab = lab_ref[...]  # [1, bt] int32
    hit = rows == lab.reshape(1, block_t)
    ll_c = jnp.sum(jnp.where(hit, lg, 0.0), axis=0)  # [bt] f32

    tile_max = jnp.max(lg, axis=0)  # [bt]
    sl = pl.ds(t * block_t, block_t)
    first = v == 0
    prev_m = jnp.where(first, jnp.full((block_t,), _NEG), m_scr[0, sl])
    prev_s = jnp.where(first, 0.0, s_scr[0, sl])
    prev_ll = jnp.where(first, 0.0, ll_scr[0, sl])

    m_new = jnp.maximum(prev_m, tile_max)
    # prev_m = -1e30 on the first tile: exp(-1e30 - m) == 0, so the stale
    # scratch value is multiplied away without an inf-inf.
    s_new = prev_s * jnp.exp(prev_m - m_new) + jnp.sum(
        jnp.exp(lg - m_new[None, :]), axis=0
    )
    ll_new = prev_ll + ll_c

    m_scr[0, sl] = m_new
    s_scr[0, sl] = s_new
    ll_scr[0, sl] = ll_new
    # Outputs are re-written on every vocab step (tiny [1, bt] blocks); the
    # final vocab tile's flush is the value the caller sees.
    m_ref[...] = m_new.reshape(1, block_t)
    s_ref[...] = s_new.reshape(1, block_t)
    ll_ref[...] = ll_new.reshape(1, block_t)


def head_ce_forward(x2: jax.Array, emb: jax.Array, labels: jax.Array,
                    *, interpret: bool = False):
    """Fused head+CE forward on flattened tokens.

    Args:
      x2: ``[T, H]`` hidden states (compute dtype).
      emb: ``[V, H]`` LM head weight, same dtype as ``x2``.
      labels: ``[T]`` int32 target ids.

    Returns ``(logitsT [V, T] compute-dtype, lse [T] f32, ll [T] f32)`` —
    the saved logits come back TRANSPOSED (vocab-major; see the kernel
    comment), ``lse`` is the exact f32 per-token logsumexp, ``ll`` the f32
    label logit; ``loss_t = lse - ll``.
    """
    T, H = x2.shape
    V = emb.shape[0]
    bt, bv = _BLOCK_T, _block_v(H, x2.dtype.itemsize)
    nt, nv = pl.cdiv(T, bt), pl.cdiv(V, bv)

    kernel = functools.partial(
        _head_ce_fwd_kernel, vocab=V, block_t=bt, block_v=bv
    )
    logits_t, m, s, ll = pl.pallas_call(
        kernel,
        grid=(nv, nt),
        in_specs=[
            pl.BlockSpec((bt, H), lambda v, t: (t, 0)),
            pl.BlockSpec((bv, H), lambda v, t: (v, 0)),
            pl.BlockSpec((1, bt), lambda v, t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((bv, bt), lambda v, t: (v, t)),
            pl.BlockSpec((1, bt), lambda v, t: (0, t)),
            pl.BlockSpec((1, bt), lambda v, t: (0, t)),
            pl.BlockSpec((1, bt), lambda v, t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((V, T), x2.dtype),
            jax.ShapeDtypeStruct((1, T), jnp.float32),
            jax.ShapeDtypeStruct((1, T), jnp.float32),
            jax.ShapeDtypeStruct((1, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, nt * bt), jnp.float32),
            pltpu.VMEM((1, nt * bt), jnp.float32),
            pltpu.VMEM((1, nt * bt), jnp.float32),
        ],
        interpret=interpret,
    )(x2, emb, labels.reshape(1, T))
    lse = m[0] + jnp.log(s[0])
    return logits_t, lse, ll[0]


# --- custom_vjp wrapper over [b, s] batches --------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def pallas_head_ce(emb, x, labels, mask, mesh=None, interpret=False):
    """Mean masked cross entropy via the fused kernel (labels pre-shifted).

    Same contract as ``ops/loss._chunked_ce``: ``x [b, s, h]``, shifted
    ``labels [b, s]``, ``mask [b, s]`` f32 weights; scalar f32 mean loss.
    ``mesh``/``interpret`` are trace-time constants (nondiff).
    """
    return _pallas_ce_fwd(emb, x, labels, mask, mesh, interpret)[0]


def _shard_axes(mesh, b: int, s: int):
    """Mesh axes the kernel shard_maps over: ``(batch_axes, seq_axes)``.

    Batch shards over data x fsdp when ``b`` divides; the sequence dim
    shards over the ``sequence`` axis when ``s`` divides (the shift and
    the position mask are computed GLOBALLY by the caller before dispatch,
    so shard-local labels/mask slices are already correct — no boundary
    exchange is needed at the kernel level). ``(None, None)`` = run the
    kernel unsharded (replicated manual region).
    """
    if mesh is None:
        return None, None
    from tpu_trainer.parallel.mesh import (
        DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS)

    axes = tuple(
        a for a in (DATA_AXIS, FSDP_AXIS) if mesh.shape.get(a, 1) > 1
    )
    size = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
    if axes and b % size != 0:
        axes = ()
    seq_axes = ()
    if (mesh.shape.get(SEQUENCE_AXIS, 1) > 1
            and s % mesh.shape[SEQUENCE_AXIS] == 0):
        seq_axes = (SEQUENCE_AXIS,)
    return (axes or None), (seq_axes or None)


def _fwd_parts(emb, x, labels, mask, mesh, interpret):
    b, s, h = x.shape
    e_c = emb.astype(x.dtype)

    def local(x_l, e_l, lab_l):
        bl, sl = x_l.shape[:2]
        logits_t, lse, ll = head_ce_forward(
            x_l.reshape(bl * sl, h), e_l, lab_l.reshape(bl * sl),
            interpret=interpret,
        )
        # Saved logits as [V, b, s]: with the token dim factored, each
        # shard's output declares its true (batch block, seq block)
        # position — a flat [V, T] out-spec would permute the global
        # token order when BOTH batch and sequence axes shard. A free
        # bitcast when unsharded.
        return (logits_t.reshape(-1, bl, sl), lse.reshape(bl, sl),
                ll.reshape(bl, sl))

    b_axes, s_axes = _shard_axes(mesh, b, s)
    if b_axes is None and s_axes is None:
        logits_t, lse, ll = local(x, e_c, labels)
    else:
        from tpu_trainer.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        # Partial-manual over the batch (and, round 5, sequence) axes only
        # (the attention dispatch's pattern, ops/attention.py): other mesh
        # axes stay under GSPMD. Under SP the caller's global shift/mask
        # make the shard-local label slices correct as-is (see
        # _shard_axes).
        t_axes = tuple(b_axes or ()) + tuple(s_axes or ())
        logits_t, lse, ll = shard_map(
            local, mesh=mesh,
            in_specs=(P(b_axes, s_axes), P(), P(b_axes, s_axes)),
            out_specs=(P(None, b_axes, s_axes), P(b_axes, s_axes),
                       P(b_axes, s_axes)),
            axis_names=set(t_axes),
            check_vma=False,
        )(x, e_c, labels)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    loss = jnp.sum((lse - ll) * mask) / denom
    return loss, logits_t, lse, denom


def _pallas_ce_fwd(emb, x, labels, mask, mesh, interpret):
    loss, logits_t, lse, denom = _fwd_parts(emb, x, labels, mask, mesh,
                                            interpret)
    return loss, (emb, x, labels, mask, logits_t, lse, denom)


def _pallas_ce_bwd(mesh, interpret, res, g):
    emb, x, labels, mask, logits_t, lse, denom = res
    b, s, h = x.shape
    vocab = emb.shape[0]
    e_c = emb.astype(x.dtype)
    scale = g / denom

    # (softmax - onehot) * weight, in the kernel's vocab-major layout —
    # XLA fuses the exp/onehot chain into the two matmuls' operand reads
    # (this is why the kernel emits vocab-major: the row-major variant
    # forced a measured 5 ms relayout + 4 ms convert before the matmuls),
    # so no vocab-major f32 cotangent is ever materialized.
    #
    # Shape regime, decided at trace time from the mesh: the flat [V, T]
    # form lowers to two plain GEMMs (the fast path — the factored 3-D
    # dot_general measured 18% off the headline, 115.7k -> 94.9k tok/s).
    # But whenever the SEQUENCE axis shards dim 2 of the residual, the
    # merged T cannot carry that sharding (batch-only sharding merges
    # fine — T blocks stay contiguous) and the reshape would reshard the
    # largest buffer of the step — there the backward stays in the
    # residual's [V, b, s] form.
    b_axes, s_axes = _shard_axes(mesh, b, s)
    if s_axes is not None:
        p_t = jnp.exp(logits_t.astype(jnp.float32) - lse[None, :, :])
        rows = jax.lax.broadcasted_iota(jnp.int32, (vocab, b, s), 0)
        onehot_t = (rows == labels[None, :, :]).astype(jnp.float32)
        dlg_t = ((p_t - onehot_t)
                 * (mask * scale)[None, :, :]).astype(x.dtype)  # [V, b, s]
        dx = jax.lax.dot_general(
            dlg_t, e_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)  # [b, s, h]
        de = jax.lax.dot_general(
            dlg_t, x, (((1, 2), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(emb.dtype)  # [V, h]
        return de, dx, None, None

    T = b * s
    p_t = jnp.exp(logits_t.reshape(vocab, T).astype(jnp.float32)
                  - lse.reshape(T)[None, :])
    rows = jax.lax.broadcasted_iota(jnp.int32, (vocab, T), 0)
    onehot_t = (rows == labels.reshape(T)[None, :]).astype(jnp.float32)
    w = (mask.reshape(T) * scale)
    dlg_t = ((p_t - onehot_t) * w[None, :]).astype(x.dtype)
    dx = jax.lax.dot_general(
        dlg_t, e_c, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype).reshape(b, s, h)
    de = jax.lax.dot_general(
        dlg_t, x.reshape(T, h), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(emb.dtype)
    return de, dx, None, None


pallas_head_ce.defvjp(_pallas_ce_fwd, _pallas_ce_bwd)
