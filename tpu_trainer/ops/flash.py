"""Pallas TPU causal flash attention (forward + backward).

The framework's native compute kernel (SURVEY.md C4): the TPU counterpart of
the reference's call into torch's fused ``scaled_dot_product_attention``
(``/root/reference/src/models/gpt.py:199-206``) — except implemented here as a
blockwise-streaming kernel rather than a library call.

Design (flash-attention-2 structure, written for the TPU memory hierarchy;
every structural choice below is trace-measured on v5e — see
benchmarks/results.md "Round-3 kernel push"):

- Grid ``(batch, heads/hp, seq // block_q)`` with ``hp`` heads per program
  (2 for head_dim 64 so the block lane width is 128; 1 for d%128==0).
  Each program owns one query block in VMEM and walks key/value blocks
  through the MXU with an online (running max / running sum) softmax. The
  ``[seq, seq]`` score matrix is never materialized in HBM — this is what
  removes the O(S^2) activation memory of the XLA fallback path.
- Block loops are STATIC Python unrolls with ``pl.when``-predicated bodies
  (softmax state in VMEM scratch), not ``fori_loop``s with data-dependent
  trip counts — Mosaic cannot schedule those, and causality's skipped
  blocks measured as costing full price. At ``seq <= block`` a
  single-block fast path drops the online softmax entirely.
- Operands are the model's FOLDED ``[b, s, h*d]`` layout, sliced per
  head(-pair) by the BlockSpecs: no BSHD transpose ever exists in HBM.
- Backward DISPATCHES on sequence length. At s <= 2048 it is one fused
  kernel (grid over key blocks) with its own block shape (512x512: it is
  FLOP-bound, causal skipping wins): one score/probability evaluation per
  block pair feeds dk, dv, and dq — dq accumulates in f32 in a
  VMEM-resident full-row block across sequential grid steps — using the
  saved per-row logsumexp and the precomputed ``delta = rowsum(dO * O)``.
  That full-row residency grows with s and overflows Mosaic's 16 MB
  default scope past s=2048, so longer sequences take the SPLIT
  two-kernel backward (the FlashAttention-2 structure): a dkv kernel
  gridded over key blocks (dk/dv accumulate in VMEM scratch while q/do
  blocks stream through an extra grid dimension) and a dq kernel gridded
  over query blocks (dq accumulates while k/v blocks stream). Nothing
  resident scales with s, at the cost of a second score evaluation
  (7 dots per block pair vs 5). ``backward="fused"|"split"|"auto"`` /
  ``TPU_TRAINER_FLASH_BWD`` override the dispatch.
- Attention-weight dropout runs in-kernel from the core's hardware PRNG
  (compiled) or a counter-based hash (interpret), generated in fixed
  512x512 tiles keyed by absolute position so the backward regenerates
  bit-identical masks under its different block shape.
- RoPE fuses in: q/k rotate in VMEM, and the forward *emits* the rotated
  (+ 1/sqrt(d)-scaled) q/k as outputs that replace the raw projections in
  the autodiff residuals — the backward never re-rotates per block.
- All accumulation in float32 regardless of input dtype (bf16 in, bf16 out).

The public API is BSHD ``[batch, seq, heads, head_dim]`` (the model's
layout), folded to ``[b, s, h*d]`` at the custom_vjp boundary so saved
residuals stay unpadded. Sequence lengths must be multiples of the block
size and head_dim must be 64 or a multiple of 128 when compiled; the
wrapper falls back to XLA fused attention otherwise.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# 1024-blocks won the v5e sweep: at s=1024 the whole head fits one block
# (no online-softmax rescaling at all — the kernel's single-block fast
# path, ~33% faster than 512-block streaming), and for longer sequences
# the [1024, 1024] score block still amortizes the per-block VPU work
# best. 128-blocks measure ~2.3x slower end to end (pipeline bubbles
# dominate the small dots). The wrapper clamps to the sequence length.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# The backward is FLOP-bound (5 dots/block, no online rescan): causal
# block-skipping at 512 measured faster than the single-block layout.
_BWD_BLOCK = 512
_NEG_INF = float("-inf")
# Mask value for SEGMENTED kernel instances. With segment skipping a
# q-row's first *processed* k-block can be fully masked (every column in
# another segment), and -inf there would meet the -inf running-max init:
# exp(-inf - (-inf)) = NaN. A large-finite mask keeps the online softmax
# NaN-free: the fully-masked block leaves m = -1e30 and garbage (l, acc)
# that the first genuinely-valid block wipes via alpha = exp(-1e30 - m)
# = 0, and once m is finite every masked score contributes
# exp(-1e30 - m) which underflows to exactly 0.0 in f32 — bit-identical
# to the -inf masking the dense reference uses. Every row attends at
# least to itself (same segment, causal diff 0), so the diagonal block
# always lands a finite max.
_SEG_MASK = -1e30
_GOLDEN = 0x9E3779B9  # Weyl increment for the per-(batch,head) salt


def _keep_mask(seed_u32, salt_u32, q_start, k_start, bq: int, bk: int,
               seq: int, rate: float):
    """Deterministic counter-based dropout mask for one score block.

    A multiply-xorshift hash of the *global* (q, k) position plus a
    per-(batch, head) salt — recomputable bit-for-bit in the backward
    kernels (the flash-attention equivalent of storing the mask, at zero
    memory). Pure jnp bitwise ops, so it runs identically compiled on TPU
    and interpreted on CPU (``pltpu.prng_*`` has no interpret lowering).
    Positions must fit uint32: seq < 2**16.

    The hash is deliberately small — 6 VPU ops per element on the
    [block_q, block_k] score block (the kernel's hot elementwise chain):
    two multiply+xorshift rounds. One round is not enough: consecutive
    positions along a row make the pre-mix values a Weyl progression with
    stride 0xC2B2AE35, and a single xorshift only partially breaks that
    lattice (keep decisions stay equidistributed but spatially
    correlated). The second round restores per-element independence to
    statistical quality (verified by the autocorrelation test in
    tests/test_flash.py); the full murmur3 finalizer beyond that buys
    nothing for a Bernoulli threshold.
    """
    # Per-row base on a [bq, 1] column (cheap) broadcast against the column
    # iota: one add per element instead of full 2-D index arithmetic.
    rows = (q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
            ).astype(jnp.uint32)
    cols = (k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            ).astype(jnp.uint32)
    x = rows * jnp.uint32(seq) + cols
    x = x ^ (seed_u32 + salt_u32 * jnp.uint32(_GOLDEN))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    threshold = jnp.uint32(min(int(rate * 2**32), 2**32 - 1))
    return x >= threshold  # keep with probability 1 - rate


def _keep(seed, salt, q_start, k_start, bq: int, bk: int, seq: int,
          rate: float, hw: bool):
    """Keep-mask for one [bq, bk] score block of one head. Two backends:

    - ``hw=True`` (compiled TPU): the core's hardware PRNG, reseeded
      deterministically per (seed, batch*head salt, block coordinates) so
      the backward kernels regenerate the identical mask from the same
      seed args (fwd and bwd block shapes are forced equal under
      dropout). Replaces ~8 VPU ops/element of hash arithmetic with a
      hardware bit stream + one compare. Generation is per head — a
      single [hp*bq, bk] generation for a paired program keeps an 8 MB
      uint32 block live across both heads' chains and blows the 16 MB
      scoped-VMEM budget in the in-model backward.
    - ``hw=False`` (interpret mode / CPU tests): the multiply-xorshift
      hash (``_keep_mask``) — ``pltpu.prng_*`` has no interpret lowering.

    The two backends draw different (both valid Bernoulli) masks; each is
    deterministic per seed within its backend, which is what training and
    the fwd/bwd mask-consistency contract require.
    """
    threshold = jnp.uint32(min(int(rate * 2**32), 2**32 - 1))
    if hw:
        from jax.experimental.pallas import tpu as pltpu

        # Generation runs in fixed 512x512 TILES keyed by absolute
        # coordinates, so the mask a block sees is independent of the
        # block shape as long as both passes use 512-divisible (or equal)
        # blocks — this is what lets the forward run its single-block
        # layout while the backward runs causal-skipping 512s. Mosaic's
        # prng_seed takes at most 2 scalars: fold the user seed with the
        # (batch, head) salt, and the tile coordinates into one position
        # unique per tile (mod 2^32 — still collision-free since
        # q*seq + k < seq^2 <= 2^32 for seq < 2**16).
        s0 = seed ^ (salt * jnp.uint32(_GOLDEN))
        tq = 512 if bq % 512 == 0 else bq
        tk = 512 if bk % 512 == 0 else bk
        rows = []
        for a in range(0, bq, tq):
            row = []
            for c in range(0, bk, tk):
                pos = (jnp.uint32(q_start + a) * jnp.uint32(seq)
                       + jnp.uint32(k_start + c))
                pltpu.prng_seed(s0, pos)
                row.append(pltpu.bitcast(pltpu.prng_random_bits((tq, tk)),
                                         jnp.uint32))
            rows.append(row[0] if len(row) == 1
                        else jnp.concatenate(row, axis=1))
        bits = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)
        return bits >= threshold
    return _keep_mask(seed, salt, q_start, k_start, bq, bk, seq, rate)


def _block_salt():
    """Per-(batch, head) hash salt from the grid position."""
    return (pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
            ).astype(jnp.uint32)


def _seed_from_ref(seed_ref):
    """uint32 seed scalar from the (1,1) SMEM input."""
    return seed_ref[0, 0]


def _rotate(x, cos, sin, out_dtype, scale=1.0):
    """RoPE rotation of one block (``x [n, d]``, ``cos/sin [n, d]`` f32):
    ``(x*cos + rotate_half(x)*sin) * scale``, f32 math, cast to ``out_dtype``.

    ``scale`` folds the attention's ``1/sqrt(d)`` into the (cheap) per-block
    q rotation so the [block_q, block_k] score matrix needs no per-element
    multiply. For even powers of two (d=16, 64, 256) the scale is itself a
    power of two, so the fold only adjusts exponents and is exact in bf16;
    for d=32/128 the scale is irrational and the folded q rounds once in
    the bf16 cast — one extra bf16-level rounding per q element relative
    to scaling the f32 score matrix, inside the tolerance the kernel tests
    already allow for bf16 inputs (tests/test_flash.py oracle comparison).
    """
    half = x.shape[-1] // 2
    x32 = x.astype(jnp.float32)
    rx = jnp.concatenate([-x32[..., half:], x32[..., :half]], axis=-1)
    out = x32 * cos + rx * sin
    if scale != 1.0:
        out = out * scale
    return out.astype(out_dtype)


def _unrotate_grad(g, cos, sin):
    """VJP of ``_rotate`` w.r.t. x applied to cotangent ``g`` (f32):
    ``g*cos + rotate_half^T(g*sin)`` where ``rotate_half^T([a,b]) = [b,-a]``."""
    half = g.shape[-1] // 2
    gs = g * sin
    rt = jnp.concatenate([gs[..., half:], -gs[..., :half]], axis=-1)
    return g * cos + rt


def _seg_predicates(qseg, kseg):
    """Block-skip predicates from loaded q/k segment-id slices.

    ``overlap``: some q row *may* share a segment with some k column —
    the interval test on [min, max]. Sound for arbitrary id layouts
    (min <= v <= max holds elementwise, so equal ids force overlapping
    intervals) and exact for the packer's sorted rows; padding-0 tails
    only over-approximate, which the elementwise mask then corrects.
    ``uniform``: both blocks are one identical segment end to end, so the
    block needs no elementwise segment mask at all — the segment
    analogue of the causal ``full`` predicate.
    """
    qf, ql = jnp.min(qseg), jnp.max(qseg)
    kf, kl = jnp.min(kseg), jnp.max(kseg)
    overlap = (qf <= kl) & (kf <= ql)
    uniform = (qf == ql) & (kf == kl) & (qf == kf)
    return overlap, uniform


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, *rest,
                block_k, scale, causal, dropout_rate, fuse_rope, hw_prng,
                hp, segmented=False):
    # Operands are the model's FOLDED layout, sliced per head *group* by
    # the BlockSpec: q_ref [1, block_q, hp*d] and k_ref/v_ref
    # [1, seq, hp*d] are column slices of [b, s, h*d] arrays. ``hp`` is
    # the number of heads per program — 2 for d=64 so the block's lane
    # width is 128 (Mosaic requires the last block dim to be a multiple
    # of 128 or the full array width), 1 for d a multiple of 128. Heads
    # within a program run as a static Python loop over static column
    # slices. No BSHD transpose ever happens in HBM — round 2 transposed
    # to [b, h, s, d] around every pallas call, costing a layout copy per
    # operand per layer. lse_ref: [1, hp, 1, seq] (full rows, written
    # blockwise). With fuse_rope, cos/sin [seq, d] ride along and q/k
    # rotate in VMEM — no rotated copies hit HBM.
    #
    # The k loop is a STATIC Python unroll with `pl.when`-predicated block
    # bodies (the splash-attention structure), not a `fori_loop` with
    # data-dependent trip counts. Measured on v5e: with dynamic trip
    # counts Mosaic cannot unroll/schedule the loop and the causal kernel
    # ran no faster than computing every block — causality's 2x FLOP
    # saving bought zero time. Static unroll + predication makes skipped
    # blocks actually free (a branch), and lets the scheduler software-
    # pipeline across block bodies. Softmax state (m, l, acc) lives in
    # per-head VMEM scratch across the predicated regions.
    # Under fuse_rope the kernel additionally WRITES the rotated
    # (and, for q, pre-scaled) projections as outputs: the backward then
    # consumes them directly instead of re-rotating q/k per block — the
    # rotate_half concatenate is a cross-lane shuffle, measured ~0.3 ms
    # per layer in the in-model backward. Same residual footprint (the
    # rotated tensors replace the raw ones in the autodiff save).
    if fuse_rope:
        cos_ref, sin_ref, *rest = rest
    if segmented:
        # Segment ids ride along as [1, block_q] (q rows) and [1, seq]
        # (full k row) int32 blocks; masking/skipping below treats blocks
        # whose q-range and k-range share no segment exactly like the
        # causal below-diagonal blocks.
        qseg_ref, kseg_ref, *rest = rest
    if fuse_rope:
        o_ref, lse_ref, qr_ref, kr_ref, *scrs = rest
    else:
        o_ref, lse_ref, *scrs = rest
        qr_ref = kr_ref = None
    m_scrs, l_scrs, acc_scrs = scrs[:hp], scrs[hp:2 * hp], scrs[2 * hp:]
    block_q = q_ref.shape[1]
    d = q_ref.shape[2] // hp
    seq = k_ref.shape[1]
    iq = pl.program_id(2)
    q_start = iq * block_q
    seed = _seed_from_ref(seed_ref)
    mask_val = _SEG_MASK if segmented else _NEG_INF
    if segmented:
        qseg = qseg_ref[0, :][:, None]        # [bq, 1]
        kseg_row = kseg_ref[0, :]             # [seq]
    # Hoisted out of the (pl.when-predicated) block bodies: program_id
    # staged inside a predicated body lowers as a plain cond branch in
    # interpret mode, where the primitive has no rule outside the grid
    # interpreter context.
    salt0 = _block_salt()

    def head_salt(t):
        # Unique per (batch, global head); equals _block_salt at hp == 1,
        # keeping the interpret-mode hash stream bit-stable with round 2.
        return salt0 * jnp.uint32(hp) + jnp.uint32(t)

    # Inputs stay in their storage dtype (bf16 in training): the MXU runs
    # bf16 x bf16 -> f32 at full rate, while f32 x f32 matmuls cost ~8x.
    # All softmax state is f32 via preferred_element_type. The 1/sqrt(d)
    # scale is folded into q once per program ([bq, d]) rather than into
    # every [bq, bk] score block.
    def load_q(t):
        q = q_ref[0, :, pl.ds(t * d, d)]  # [bq, d], static column slice
        if fuse_rope:
            q = _rotate(q, cos_ref[pl.ds(q_start, block_q), :],
                        sin_ref[pl.ds(q_start, block_q), :], q_ref.dtype,
                        scale=scale)
            qr_ref[0, :, pl.ds(t * d, d)] = q
            return q
        return (q.astype(jnp.float32) * scale).astype(q_ref.dtype)

    single = seq == block_k and seq == block_q
    if single:
        # Whole-sequence single block (the s <= 1024 fast path, and the
        # headline-config shape): no online softmax, no rescaling, no
        # scratch round-trips — one straight-line masked softmax per
        # (batch, head). Measured ~33% faster than 512-block streaming on
        # v5e at s=1024 even though the masked upper triangle is computed.
        valid = None
        if causal:
            diff = (jax.lax.broadcasted_iota(jnp.int32, (block_q, seq), 0)
                    - jax.lax.broadcasted_iota(jnp.int32, (block_q, seq), 1))
            valid = diff >= 0
        if segmented:
            same = qseg == kseg_row[None, :]
            valid = same if valid is None else valid & same
        for t in range(hp):
            q = load_q(t)
            k = k_ref[0, :, pl.ds(t * d, d)]
            v = v_ref[0, :, pl.ds(t * d, d)]
            if fuse_rope:
                k = _rotate(k, cos_ref[...], sin_ref[...], k_ref.dtype)
                kr_ref[0, :, pl.ds(t * d, d)] = k
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if valid is not None:
                s = jnp.where(valid, s, mask_val)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            l = jnp.sum(p, axis=-1, keepdims=True)
            if dropout_rate > 0.0:
                keep = _keep(seed, head_salt(t), 0, 0, block_q, block_k,
                             seq, dropout_rate, hw_prng)
                p = jnp.where(keep, p, 0.0)
            acc = jnp.dot(p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32)
            denom = l * (1.0 - dropout_rate) if dropout_rate > 0.0 else l
            o_ref[0, :, pl.ds(t * d, d)] = (acc / denom).astype(o_ref.dtype)
            lse_ref[0, t, 0, :] = m[:, 0] + jnp.log(l[:, 0])
        return

    for t in range(hp):
        m_scrs[t][...] = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
        l_scrs[t][...] = jnp.zeros((block_q, 1), jnp.float32)
        acc_scrs[t][...] = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # Row-minus-column iota difference, hoisted out of the block loop:
        # the diagonal block's mask is `diff >= k_start - q_start`, one
        # compare + one select per element instead of two iotas + compare +
        # select inside every masked block.
        diff = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                - jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))

    qs = [load_q(t) for t in range(hp)]

    def body(ik: int, masked: bool):
        k_start = ik * block_k  # static
        for t in range(hp):
            m, l, acc = m_scrs[t][...], l_scrs[t][...], acc_scrs[t][...]
            k = k_ref[0, pl.ds(k_start, block_k), pl.ds(t * d, d)]
            v = v_ref[0, pl.ds(k_start, block_k), pl.ds(t * d, d)]
            if fuse_rope:
                k = _rotate(k, cos_ref[pl.ds(k_start, block_k), :],
                            sin_ref[pl.ds(k_start, block_k), :], k_ref.dtype)
                kr_ref[0, pl.ds(k_start, block_k), pl.ds(t * d, d)] = k
            s = jax.lax.dot_general(
                qs[t], k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk] f32 (already scaled via q)
            if masked:
                valid = None
                if causal:
                    valid = diff >= k_start - q_start
                if segmented:
                    # k_start is a static unroll index: plain value slice.
                    same = qseg == kseg_row[k_start:k_start + block_k][None, :]
                    valid = same if valid is None else valid & same
                s = jnp.where(valid, s, mask_val)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            # The softmax normalizer sums the *undropped* weights (dropout
            # acts on normalized weights in the reference, gpt.py:230-234
            # semantics).
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            if dropout_rate > 0.0:
                # Survivors keep their raw weight here; the 1/(1-rate)
                # inverted-dropout scale folds into the final acc/l division
                # (one [bq, 1] multiply) instead of a per-element multiply
                # per block.
                keep = _keep(seed, head_salt(t), q_start, k_start,
                             block_q, block_k, seq, dropout_rate, hw_prng)
                p = jnp.where(keep, p, 0.0)
            m_scrs[t][...] = m_new
            l_scrs[t][...] = l_new
            acc_scrs[t][...] = acc * alpha + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32
            )

    for ik in range(seq // block_k):
        if not causal and not segmented:
            body(ik, masked=False)
            continue
        k_start = ik * block_k
        # Causal — needed: any (row, col) with row >= col, i.e. the
        # block's last row reaches its first column. full: every element
        # valid (last column <= first row). Both predicates depend on the
        # dynamic q_start. Segments compose the same way: no-overlap
        # blocks are skipped outright (the generalization of the
        # below-diagonal skip), and only non-uniform boundary blocks pay
        # the elementwise mask.
        if causal:
            needed = q_start + block_q - 1 >= k_start
            full = q_start >= k_start + block_k - 1
        else:
            needed = full = True
        if segmented:
            overlap, uniform = _seg_predicates(
                qseg, kseg_row[k_start:k_start + block_k])
            run_full = full & uniform
            run_masked = needed & overlap & jnp.logical_not(run_full)
        else:
            run_full = full
            run_masked = needed & jnp.logical_not(full)
        pl.when(run_full)(functools.partial(body, ik, False))
        pl.when(run_masked)(functools.partial(body, ik, True))

    for t in range(hp):
        m, l, acc = m_scrs[t][...], l_scrs[t][...], acc_scrs[t][...]
        denom = l * (1.0 - dropout_rate) if dropout_rate > 0.0 else l
        o_ref[0, :, pl.ds(t * d, d)] = (acc / denom).astype(o_ref.dtype)
        lse_ref[0, t, 0, pl.ds(q_start, block_q)] = m[:, 0] + jnp.log(l[:, 0])


def _seed_spec():
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _rope_specs(s, d):
    return [pl.BlockSpec((s, d), lambda ib, ih, i: (0, 0))] * 2


def _heads_per_program(d: int, interpret: bool) -> int:
    """Heads per kernel program. Mosaic needs the block's lane width to be
    a multiple of 128 (or the full array width): d=64 pairs two heads per
    program (width 128); d a multiple of 128 runs one head per program.
    Interpret mode has no lane constraint — keep hp=1 so the CPU-test hash
    salts stay bit-identical to the per-head design."""
    if interpret:
        return 1
    if d == 64:
        return 2
    if d % 128 == 0:
        return 1
    raise NotImplementedError(
        f"compiled flash kernel supports head_dim 64 or multiples of 128; "
        f"got {d} (use the XLA fallback path)"
    )


def _flash_forward(q3, k3, v3, seed_f, seg_f, rope, *, num_heads, head_dim,
                   num_kv_heads, causal, block_q, block_k, interpret,
                   dropout_rate, segmented=False):
    # q3: FOLDED [b, s, h*d]. k3/v3: [b, s, kvh*d] with kvh == h when
    # hp > 1 (the caller expands grouped K/V to per-query-head copies —
    # the repeated-KV-MHA identity — because a paired program's two query
    # heads may straddle a K/V head boundary); under hp == 1 GQA stays an
    # index map (grid head ih -> K/V columns (ih // group) * d). The
    # BlockSpecs slice per-head-group [*, hp*d] columns straight out of
    # the folded layout — no BSHD transpose/copy in HBM. seed_f: (1,1)
    # float32 bit-carrier (floats so custom_vjp has a well-defined
    # cotangent; re-bitcast to uint32 here, outside the kernel — Mosaic
    # can't bitcast scalars in-kernel). rope: None or (cos, sin) [s, d]
    # f32. seg_f: [b, s] float32 bit-carrier of the int32 segment ids
    # (same custom_vjp trick as seed_f) when ``segmented``; ignored
    # otherwise.
    seed_f = jax.lax.bitcast_convert_type(seed_f, jnp.uint32)
    b, s, _ = q3.shape
    h, d = num_heads, head_dim
    hp = _heads_per_program(d, interpret)
    group = h // num_kv_heads
    assert group == 1 or hp == 1, "caller expands K/V before pairing heads"
    scale = 1.0 / math.sqrt(d)
    grid = (b, h // hp, s // block_q)
    q_spec = pl.BlockSpec((1, block_q, hp * d),
                          lambda ib, ip, iq: (ib, iq, ip))
    kv_spec = pl.BlockSpec(
        (1, s, hp * d),
        (lambda ib, ip, iq: (ib, 0, ip)) if hp > 1 or group == 1
        else (lambda ib, ip, iq: (ib, 0, ip // group)),
    )
    row_spec = pl.BlockSpec((1, hp, 1, s), lambda ib, ip, iq: (ib, ip, 0, 0))
    fuse_rope = rope is not None
    rope_args = tuple(rope) if fuse_rope else ()
    seg_args = ()
    seg_specs = []
    if segmented:
        # The same [b, s] id array enters twice — once blocked by q rows,
        # once as the full k row — so the kernel's q/k segment views ride
        # the grid like every other operand.
        seg = jax.lax.bitcast_convert_type(seg_f, jnp.int32)
        seg_args = (seg, seg)
        seg_specs = [
            pl.BlockSpec((1, block_q), lambda ib, ip, iq: (ib, iq)),
            pl.BlockSpec((1, s), lambda ib, ip, iq: (ib, 0)),
        ]
    from jax.experimental.pallas import tpu as pltpu

    outs = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, scale=scale, causal=causal,
            dropout_rate=dropout_rate, fuse_rope=fuse_rope,
            hw_prng=not interpret, hp=hp, segmented=segmented,
        ),
        grid=grid,
        in_specs=[_seed_spec(), q_spec, kv_spec, kv_spec]
        + (_rope_specs(s, d) if fuse_rope else []) + seg_specs,
        out_specs=[q_spec, row_spec]
        + ([q_spec, kv_spec] if fuse_rope else []),
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), q3.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ]
        + ([jax.ShapeDtypeStruct((b, s, h * d), q3.dtype),
            jax.ShapeDtypeStruct(k3.shape, k3.dtype)] if fuse_rope else []),
        scratch_shapes=(
            [pltpu.VMEM((block_q, 1), jnp.float32)] * (2 * hp)
            + [pltpu.VMEM((block_q, d), jnp.float32)] * hp
        ),
        interpret=interpret,
    )(seed_f, q3, k3, v3, *rope_args, *seg_args)
    if fuse_rope:
        return outs  # (o3, lse, rotated-scaled q3, rotated k3)
    o3, lse = outs
    return o3, lse, None, None


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _bwd_fused_kernel(
    seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q, scale, causal, dropout_rate, fuse_rope, hw_prng, hp,
):
    """Single-pass backward: grid ``(b, h, seq // block_k)``.

    Each program owns one K/V block, streams the (causally relevant) query
    blocks once, and from a single score/probability computation produces
    its dk/dv block *and* the partial dq contributions. dq's BlockSpec index
    is constant in the kv grid dimension, so the full-row dq block stays
    resident in VMEM and accumulates across sequential grid steps (zeroed at
    the first kv block). Compared to separate dq and dk/dv kernels this
    halves the backward's score matmuls and q/do reads.

    With ``fuse_rope``, q/k blocks are re-rotated in VMEM for the score
    recomputation; dq/dk leave the kernel in *rotated* space and the caller
    applies the rotation's transpose (``_unrotate_grad``).
    """
    if fuse_rope:
        cos_ref, sin_ref, dq_ref, dk_ref, dv_ref, *scrs = rest
    else:
        dq_ref, dk_ref, dv_ref, *scrs = rest
    dk_scrs, dv_scrs = scrs[:hp], scrs[hp:]
    block_k = k_ref.shape[1]
    d = k_ref.shape[2] // hp
    seq = q_ref.shape[1]
    ik = pl.program_id(2)
    k_start = ik * block_k
    seed = _seed_from_ref(seed_ref)
    num_q = seq // block_q
    # Whole-sequence single block (mirrors the forward's fast path): no
    # dq accumulation across programs, no scratch round-trips, and the
    # dropout seed position is the same static (0, 0) the forward used.
    single = num_q == 1 and seq == block_k
    salt0 = _block_salt()  # hoisted out of the pl.when bodies (see _fwd_kernel)

    def head_salt(t):
        return salt0 * jnp.uint32(hp) + jnp.uint32(t)

    # Under fuse_rope the forward already wrote rotated k and
    # rotated-scaled q as outputs (see _fwd_kernel): they arrive here as
    # the residuals, so no per-block re-rotation happens — only the final
    # unrotate of dq/dk below needs cos/sin.
    ks = [k_ref[0, :, pl.ds(t * d, d)] for t in range(hp)]

    def body(iq, t, masked: bool, out=None):
        # ``iq``/``t`` are static Python ints: the q-block and head loops
        # are unrolled at trace time with `pl.when` predication per block
        # (see _fwd_kernel for the measured rationale). q is loaded
        # pre-scaled by 1/sqrt(d) (folded into the [bq, d] load /
        # rotation): the score recompute then needs no per-element scale,
        # and dk = sum ds^T @ q_scaled IS the correctly-scaled dk (chain
        # rule puts one factor of `scale` on each of dq and dk).
        k, v = ks[t], v_ref[0, :, pl.ds(t * d, d)]
        q_start = iq * block_q
        q = q_ref[0, pl.ds(q_start, block_q), pl.ds(t * d, d)]
        do = do_ref[0, pl.ds(q_start, block_q), pl.ds(t * d, d)]
        if not fuse_rope:
            # fuse_rope residuals arrive pre-scaled (the forward folds
            # 1/sqrt(d) into the q rotation it writes back).
            q = (q.astype(jnp.float32) * scale).astype(q_ref.dtype)
        lse = lse_ref[0, t, 0, pl.ds(q_start, block_q)][:, None]
        delta = delta_ref[0, t, 0, pl.ds(q_start, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk] (scaled via q)
        if masked:
            diff = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                    - jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(diff >= k_start - q_start, s, _NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] (normalized)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            # p_drop stays unscaled; the 1/(1-rate) folds into dv once at
            # the end ([bk, d] multiply instead of per-element per block).
            keep = _keep(seed, head_salt(t), iq * block_q, k_start,
                         block_q, block_k, seq, dropout_rate, hw_prng)
            p_drop = jnp.where(keep, p, 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_drop = p
        dv_new = jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)                      # [bq, bk]
        dk_new = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dq_part = jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        ) * scale
        if out is not None:
            # Single-block: grads are complete after this one body.
            out.append((dk_new, dv_new, dq_part))
        else:
            # Ref-based accumulation (pl.when bodies must return None).
            sl = pl.ds(q_start, block_q)
            dq_ref[0, sl, pl.ds(t * d, d)] += dq_part.astype(dq_ref.dtype)
            dk_scrs[t][...] += dk_new
            dv_scrs[t][...] += dv_new

    if single:
        for t in range(hp):
            out = []
            body(0, t, masked=causal, out=out)
            dk, dv, dq = out[0]
            if fuse_rope:
                dq = _unrotate_grad(dq, cos_ref[...], sin_ref[...])
                dk = _unrotate_grad(dk, cos_ref[...], sin_ref[...])
            if dropout_rate > 0.0:
                dv = dv / (1.0 - dropout_rate)
            dq_ref[0, :, pl.ds(t * d, d)] = dq.astype(dq_ref.dtype)
            dk_ref[0, :, pl.ds(t * d, d)] = dk.astype(dk_ref.dtype)
            dv_ref[0, :, pl.ds(t * d, d)] = dv.astype(dv_ref.dtype)
        return

    @pl.when(ik == 0)
    def _zero_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    for t in range(hp):
        dk_scrs[t][...] = jnp.zeros((block_k, d), jnp.float32)
        dv_scrs[t][...] = jnp.zeros((block_k, d), jnp.float32)
    for iq in range(num_q):
        q_start = iq * block_q

        def run(masked, iq=iq):
            for t in range(hp):
                body(iq, t, masked=masked)

        if not causal:
            run(False)
            continue
        # needed: the block's last row reaches its first column; full:
        # every element valid. k_start is dynamic (program id), so both
        # predicates are runtime branches on otherwise-static bodies.
        needed = q_start + block_q - 1 >= k_start
        full = q_start >= k_start + block_k - 1
        pl.when(full)(functools.partial(run, False))
        pl.when(needed & jnp.logical_not(full))(functools.partial(run, True))
    for t in range(hp):
        dk = dk_scrs[t][...]
        dv = dv_scrs[t][...]
        if fuse_rope:
            # dk leaves the kernel already un-rotated (the rotation's
            # transpose applied in VMEM) — no external f32
            # read-modify-write pass.
            cos_k = cos_ref[pl.ds(k_start, block_k), :]
            sin_k = sin_ref[pl.ds(k_start, block_k), :]
            dk = _unrotate_grad(dk, cos_k, sin_k)
        if dropout_rate > 0.0:
            dv = dv / (1.0 - dropout_rate)
        dk_ref[0, :, pl.ds(t * d, d)] = dk.astype(dk_ref.dtype)
        dv_ref[0, :, pl.ds(t * d, d)] = dv.astype(dv_ref.dtype)

    if fuse_rope:
        # dq finishes accumulating at the last kv grid step (its block index
        # is constant in this grid dimension, so the full-row block is still
        # VMEM-resident): un-rotate it in place before it is written back.
        @pl.when(ik == pl.num_programs(2) - 1)
        def _unrotate_dq():
            for t in range(hp):
                dq = dq_ref[0, :, pl.ds(t * d, d)]
                dq_ref[0, :, pl.ds(t * d, d)] = _unrotate_grad(
                    dq, cos_ref[...], sin_ref[...]
                ).astype(dq_ref.dtype)


# The fused kernel keeps full-sequence q/do/dq row blocks VMEM-resident,
# so its footprint grows with s: measured on v5e it fits Mosaic's 16 MB
# default scope through s=2048 and overflows at s=4096 (the old escape
# hatch was raising --xla_tpu_scoped_vmem_limit_kib, which steals scope
# from every other kernel in the step). Past this threshold the dispatch
# selects the two-kernel split backward, whose residency is per-block
# only (s-independent). Below it the fused kernel wins: one score
# evaluation feeds dk, dv, AND dq (the split path recomputes scores in
# each kernel — 7 dots per block pair vs 5).
_FUSED_BWD_MAX_SEQ = 2048


def _bwd_dkv_kernel(
    seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale, causal, dropout_rate, fuse_rope, hw_prng, hp, seq,
    segmented=False,
):
    """dk/dv half of the two-kernel (split) backward.

    Grid ``(b, h/hp, seq // block_k, seq // block_q)``: each program owns
    one K/V block (its index map is constant in the innermost grid
    dimension, so dk/dv accumulate in VMEM scratch across the sequential
    q-block walk) and sees one q/do block per grid step. Nothing resident
    scales with the sequence length — q/do arrive blocked through the
    grid, lse/delta arrive as per-q-block rows, and under ``fuse_rope``
    cos/sin arrive as the K-rows block (only the final dk un-rotation
    needs them; the residual q/k are pre-rotated). Causal below-diagonal
    blocks (q entirely before k) are skipped by ``pl.when`` predication,
    exactly as in the fused kernel.

    The per-(q,k) block math is the fused kernel's ``body`` verbatim minus
    the dq contribution, and the dropout mask comes from the same
    absolute-coordinate counter hash / PRNG tiles (``_keep``), so masks
    regenerate bit-for-bit across the forward and both split kernels.
    """
    if fuse_rope:
        cos_ref, sin_ref, *rest = rest
    if segmented:
        qseg_ref, kseg_ref, *rest = rest
    dk_ref, dv_ref, *scrs = rest
    dk_scrs, dv_scrs = scrs[:hp], scrs[hp:]
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    d = k_ref.shape[2] // hp
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    k_start = ik * block_k
    q_start = iq * block_q
    seed = _seed_from_ref(seed_ref)
    mask_val = _SEG_MASK if segmented else _NEG_INF
    if segmented:
        qseg = qseg_ref[0, :][:, None]        # [bq, 1]
        kseg = kseg_ref[0, :][None, :]        # [1, bk]
    salt0 = _block_salt()  # hoisted out of the pl.when bodies (see _fwd_kernel)

    def head_salt(t):
        return salt0 * jnp.uint32(hp) + jnp.uint32(t)

    @pl.when(iq == 0)
    def _zero():
        for t in range(hp):
            dk_scrs[t][...] = jnp.zeros((block_k, d), jnp.float32)
            dv_scrs[t][...] = jnp.zeros((block_k, d), jnp.float32)

    def body(masked: bool):
        for t in range(hp):
            k = k_ref[0, :, pl.ds(t * d, d)]
            v = v_ref[0, :, pl.ds(t * d, d)]
            q = q_ref[0, :, pl.ds(t * d, d)]
            do = do_ref[0, :, pl.ds(t * d, d)]
            if not fuse_rope:
                # fuse_rope residuals arrive pre-scaled (see _fwd_kernel).
                q = (q.astype(jnp.float32) * scale).astype(q_ref.dtype)
            lse = lse_ref[0, t, 0, :][:, None]
            delta = delta_ref[0, t, 0, :][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk] (scaled via q)
            if masked:
                valid = None
                if causal:
                    diff = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                            - jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
                    valid = diff >= k_start - q_start
                if segmented:
                    same = qseg == kseg
                    valid = same if valid is None else valid & same
                s = jnp.where(valid, s, mask_val)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dropout_rate > 0.0:
                keep = _keep(seed, head_salt(t), q_start, k_start,
                             block_q, block_k, seq, dropout_rate, hw_prng)
                p_drop = jnp.where(keep, p, 0.0)
                dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
            else:
                p_drop = p
            dv_scrs[t][...] += jax.lax.dot_general(
                p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta)
            dk_scrs[t][...] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if not causal and not segmented:
        body(False)
    else:
        if causal:
            needed = q_start + block_q - 1 >= k_start
            full = q_start >= k_start + block_k - 1
        else:
            needed = full = True
        if segmented:
            overlap, uniform = _seg_predicates(qseg, kseg)
            run_full = full & uniform
            run_masked = needed & overlap & jnp.logical_not(run_full)
        else:
            run_full = full
            run_masked = needed & jnp.logical_not(full)
        pl.when(run_full)(functools.partial(body, False))
        pl.when(run_masked)(functools.partial(body, True))

    @pl.when(iq == pl.num_programs(3) - 1)
    def _flush():
        for t in range(hp):
            dk = dk_scrs[t][...]
            dv = dv_scrs[t][...]
            if fuse_rope:
                dk = _unrotate_grad(dk, cos_ref[...], sin_ref[...])
            if dropout_rate > 0.0:
                dv = dv / (1.0 - dropout_rate)
            dk_ref[0, :, pl.ds(t * d, d)] = dk.astype(dk_ref.dtype)
            dv_ref[0, :, pl.ds(t * d, d)] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(
    seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale, causal, dropout_rate, fuse_rope, hw_prng, hp, seq,
    segmented=False,
):
    """dq half of the two-kernel (split) backward.

    Grid ``(b, h/hp, seq // block_q, seq // block_k)``: each program owns
    one q/do/dq block (dq accumulates in VMEM scratch across the
    sequential k-block walk; its output index map is constant in the
    innermost grid dimension) and sees one K/V block per grid step.
    Residency is per-block only — see ``_bwd_dkv_kernel``. Under
    ``fuse_rope`` cos/sin arrive as the Q-rows block for the final dq
    un-rotation. ``ds`` recomputes from the same ``p``/``dp``/dropout
    chain as the dkv kernel so both halves see identical score gradients.
    """
    if fuse_rope:
        cos_ref, sin_ref, *rest = rest
    if segmented:
        qseg_ref, kseg_ref, *rest = rest
    dq_ref, *dq_scrs = rest
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    d = q_ref.shape[2] // hp
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    q_start = iq * block_q
    k_start = ik * block_k
    seed = _seed_from_ref(seed_ref)
    mask_val = _SEG_MASK if segmented else _NEG_INF
    if segmented:
        qseg = qseg_ref[0, :][:, None]        # [bq, 1]
        kseg = kseg_ref[0, :][None, :]        # [1, bk]
    salt0 = _block_salt()  # hoisted out of the pl.when bodies (see _fwd_kernel)

    def head_salt(t):
        return salt0 * jnp.uint32(hp) + jnp.uint32(t)

    @pl.when(ik == 0)
    def _zero():
        for t in range(hp):
            dq_scrs[t][...] = jnp.zeros((block_q, d), jnp.float32)

    def body(masked: bool):
        for t in range(hp):
            q = q_ref[0, :, pl.ds(t * d, d)]
            do = do_ref[0, :, pl.ds(t * d, d)]
            k = k_ref[0, :, pl.ds(t * d, d)]
            v = v_ref[0, :, pl.ds(t * d, d)]
            if not fuse_rope:
                q = (q.astype(jnp.float32) * scale).astype(q_ref.dtype)
            lse = lse_ref[0, t, 0, :][:, None]
            delta = delta_ref[0, t, 0, :][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if masked:
                valid = None
                if causal:
                    diff = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                            - jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
                    valid = diff >= k_start - q_start
                if segmented:
                    same = qseg == kseg
                    valid = same if valid is None else valid & same
                s = jnp.where(valid, s, mask_val)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dropout_rate > 0.0:
                keep = _keep(seed, head_salt(t), q_start, k_start,
                             block_q, block_k, seq, dropout_rate, hw_prng)
                dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
            ds = p * (dp - delta)
            dq_scrs[t][...] += jnp.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32
            ) * scale

    if not causal and not segmented:
        body(False)
    else:
        if causal:
            needed = q_start + block_q - 1 >= k_start
            full = q_start >= k_start + block_k - 1
        else:
            needed = full = True
        if segmented:
            overlap, uniform = _seg_predicates(qseg, kseg)
            run_full = full & uniform
            run_masked = needed & overlap & jnp.logical_not(run_full)
        else:
            run_full = full
            run_masked = needed & jnp.logical_not(full)
        pl.when(run_full)(functools.partial(body, False))
        pl.when(run_masked)(functools.partial(body, True))

    @pl.when(ik == pl.num_programs(3) - 1)
    def _flush():
        for t in range(hp):
            dq = dq_scrs[t][...]
            if fuse_rope:
                dq = _unrotate_grad(dq, cos_ref[...], sin_ref[...])
            dq_ref[0, :, pl.ds(t * d, d)] = dq.astype(dq_ref.dtype)


def _flash_backward(q3, k3, v3, o3, lse, do3, seed_f, seg_f, rope, *,
                    num_heads, head_dim, num_kv_heads, causal, block_q,
                    block_k, interpret, dropout_rate, dlse=None,
                    f32_kv_grads=False, backward=None, segmented=False):
    # Folded operands throughout (see _flash_forward). The backward runs
    # its own block sizes: measured on v5e the backward is MXU/FLOP-bound
    # (5 dots per block, no online-softmax rescan), so causal block
    # skipping beats the forward's single-block fast path — 512x512 blocks
    # compute 3/4 of the score square instead of all of it.
    # ``num_kv_heads`` here is the KERNEL-level kv-head count: the caller
    # (_make_flash) expands grouped K/V to per-query-head copies before
    # pairing heads, and performs the dk/dv group-sum afterwards.
    b, s, _ = q3.shape
    h, d = num_heads, head_dim
    kvh = num_kv_heads
    group = h // kvh
    scale = 1.0 / math.sqrt(d)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term.
    delta = jnp.moveaxis(
        (do3.astype(jnp.float32) * o3.astype(jnp.float32))
        .reshape(b, s, h, d).sum(axis=-1), 1, 2
    )[:, :, None, :]
    if dlse is not None:
        # lse is an exposed output (return_lse path): its cotangent enters
        # the score gradient as ds += p * dlse, i.e. exactly a -dlse shift
        # of the delta row — no kernel change needed.
        delta = delta - dlse.astype(jnp.float32)[:, :, None, :]

    from jax.experimental.pallas import tpu as pltpu

    hp = _heads_per_program(d, interpret)
    assert group == 1 or hp == 1, "caller expands K/V before pairing heads"
    seed_f = jax.lax.bitcast_convert_type(seed_f, jnp.uint32)
    blk = lambda n: pl.BlockSpec((1, n, hp * d),
                                 lambda ib, ip, i: (ib, i, ip))
    kv_blk = lambda n: pl.BlockSpec(
        (1, n, hp * d),
        (lambda ib, ip, i: (ib, i, ip)) if hp > 1 or group == 1
        else (lambda ib, ip, i: (ib, i, ip // group)),
    )
    full = pl.BlockSpec((1, s, hp * d), lambda ib, ip, i: (ib, 0, ip))
    row = pl.BlockSpec((1, hp, 1, s), lambda ib, ip, i: (ib, ip, 0, 0))
    fuse_rope = rope is not None
    rope_args = tuple(rope) if fuse_rope else ()

    # Under GQA (hp == 1 path) each query head writes per-head dk/dv
    # partials ([b, s, h*d], the same size MHA's dk/dv would be). The
    # partials leave the kernel in f32 so the caller's group-sum
    # accumulates at full precision and rounds to the storage dtype
    # exactly once, after the reduction — not once per partial (the
    # [b, s, h*d] f32 footprint is the same one the MHA dq already pays).
    kv_grad_dtype = (jnp.float32 if group > 1 or f32_kv_grads
                     else k3.dtype)

    # Backward dispatch: the fused single pass wins while its full-row
    # q/do/dq residency is cheap (s <= _FUSED_BWD_MAX_SEQ — one score
    # evaluation feeds dk, dv, and dq); past that it would overflow the
    # 16 MB default scope, so the split two-kernel path (s-independent
    # VMEM) takes over. ``backward`` in {"fused", "split"} overrides for
    # the sweep (benchmarks/longseq_block_sweep.py) and the parity tests.
    # Segmented instances always take the split path — segments were only
    # taught to the split pair (the fused kernel's one-pass dq residency
    # buys nothing once segment skipping fragments the block walk).
    if segmented:
        if backward == "fused":
            raise NotImplementedError(
                "segment_ids require the split backward (the fused kernel "
                "has no segment masking)"
            )
        impl = "split"
    else:
        impl = backward or ("fused" if s <= _FUSED_BWD_MAX_SEQ else "split")
    if impl == "fused":
        # The fused pass takes its preferred 512 blocks (FLOP-bound, 5
        # dots per block pair; causal block-skipping computes 3/4 of the
        # score square, and the paired program's f32 [bq, bk] working set
        # stays inside the 16 MB scope — single 1024x1024 blocks blow
        # it). The split kernels keep the caller's blocks: their
        # residency is s-independent, so larger blocks just mean fewer
        # grid steps.
        if block_q % _BWD_BLOCK == 0:
            block_q = _BWD_BLOCK
        if block_k % _BWD_BLOCK == 0:
            block_k = _BWD_BLOCK
    if impl == "split":
        kernel_kw = dict(scale=scale, causal=causal,
                         dropout_rate=dropout_rate, fuse_rope=fuse_rope,
                         hw_prng=not interpret, hp=hp, seq=s,
                         segmented=segmented)
        gqa_map = not (hp > 1 or group == 1)
        seg_args = ()
        if segmented:
            seg = jax.lax.bitcast_convert_type(seg_f, jnp.int32)
            seg_args = (seg, seg)
        # dkv pass: grid (b, h/hp, k blocks, q blocks) — dk/dv block
        # indices are constant in the innermost (q) dimension, so they
        # stay VMEM-resident accumulating across the q walk.
        q_blk = pl.BlockSpec((1, block_q, hp * d),
                             lambda ib, ip, ik, iq: (ib, iq, ip))
        kv_in = pl.BlockSpec(
            (1, block_k, hp * d),
            (lambda ib, ip, ik, iq: (ib, ik, ip // group)) if gqa_map
            else (lambda ib, ip, ik, iq: (ib, ik, ip)),
        )
        kv_out = pl.BlockSpec((1, block_k, hp * d),
                              lambda ib, ip, ik, iq: (ib, ik, ip))
        row_q = pl.BlockSpec((1, hp, 1, block_q),
                             lambda ib, ip, ik, iq: (ib, ip, 0, iq))
        rope_k = [pl.BlockSpec((block_k, d),
                               lambda ib, ip, ik, iq: (ik, 0))] * 2
        seg_dkv = [
            pl.BlockSpec((1, block_q), lambda ib, ip, ik, iq: (ib, iq)),
            pl.BlockSpec((1, block_k), lambda ib, ip, ik, iq: (ib, ik)),
        ] if segmented else []
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, **kernel_kw),
            grid=(b, h // hp, s // block_k, s // block_q),
            in_specs=[_seed_spec(), q_blk, kv_in, kv_in, q_blk, row_q,
                      row_q] + (rope_k if fuse_rope else []) + seg_dkv,
            out_specs=[kv_out, kv_out],
            out_shape=[
                jax.ShapeDtypeStruct((b, s, h * d), kv_grad_dtype),
                jax.ShapeDtypeStruct((b, s, h * d), kv_grad_dtype),
            ],
            scratch_shapes=(
                [pltpu.VMEM((block_k, d), jnp.float32)] * (2 * hp)
            ),
            interpret=interpret,
        )(seed_f, q3, k3, v3, do3, lse, delta, *rope_args, *seg_args)
        # dq pass: grid (b, h/hp, q blocks, k blocks) — the q/do/dq blocks
        # are constant in the innermost (k) dimension.
        q_blk2 = pl.BlockSpec((1, block_q, hp * d),
                              lambda ib, ip, iq, ik: (ib, iq, ip))
        kv_in2 = pl.BlockSpec(
            (1, block_k, hp * d),
            (lambda ib, ip, iq, ik: (ib, ik, ip // group)) if gqa_map
            else (lambda ib, ip, iq, ik: (ib, ik, ip)),
        )
        row_q2 = pl.BlockSpec((1, hp, 1, block_q),
                              lambda ib, ip, iq, ik: (ib, ip, 0, iq))
        rope_q = [pl.BlockSpec((block_q, d),
                               lambda ib, ip, iq, ik: (iq, 0))] * 2
        seg_dq = [
            pl.BlockSpec((1, block_q), lambda ib, ip, iq, ik: (ib, iq)),
            pl.BlockSpec((1, block_k), lambda ib, ip, iq, ik: (ib, ik)),
        ] if segmented else []
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, **kernel_kw),
            grid=(b, h // hp, s // block_q, s // block_k),
            in_specs=[_seed_spec(), q_blk2, kv_in2, kv_in2, q_blk2, row_q2,
                      row_q2] + (rope_q if fuse_rope else []) + seg_dq,
            out_specs=q_blk2,
            out_shape=jax.ShapeDtypeStruct((b, s, h * d), jnp.float32),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)] * hp,
            interpret=interpret,
        )(seed_f, q3, k3, v3, do3, lse, delta, *rope_args, *seg_args)
        if group > 1:
            dk = dk.reshape(b, s, kvh, group, d).sum(axis=3).reshape(
                b, s, kvh * d).astype(k3.dtype)
            dv = dv.reshape(b, s, kvh, group, d).sum(axis=3).reshape(
                b, s, kvh * d).astype(v3.dtype)
        return dq.astype(q3.dtype), dk, dv

    # Fused single pass; dq accumulates in f32 across kv-block grid steps
    # (its block index is constant in that dimension, so it stays in VMEM).
    # Under fused rope, dq and dk are un-rotated *inside* the kernel (VMEM)
    # before they are written — no external pass over the gradients.
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, block_q=block_q, scale=scale,
                          causal=causal, dropout_rate=dropout_rate,
                          fuse_rope=fuse_rope, hw_prng=not interpret, hp=hp),
        grid=(b, h // hp, s // block_k),
        in_specs=[_seed_spec(), full, kv_blk(block_k), kv_blk(block_k), full,
                  row, row]
        + (_rope_specs(s, d) if fuse_rope else []),
        out_specs=[full, blk(block_k), blk(block_k)],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), jnp.float32),
            jax.ShapeDtypeStruct((b, s, h * d), kv_grad_dtype),
            jax.ShapeDtypeStruct((b, s, h * d), kv_grad_dtype),
        ],
        scratch_shapes=(
            [pltpu.VMEM((block_k, d), jnp.float32)] * (2 * hp)
        ),
        interpret=interpret,
    )(seed_f, q3, k3, v3, do3, lse, delta, *rope_args)
    if group > 1:
        # hp == 1 GQA-by-index-map: reduce per-query-head partials here.
        dk = dk.reshape(b, s, kvh, group, d).sum(axis=3).reshape(
            b, s, kvh * d).astype(k3.dtype)
        dv = dv.reshape(b, s, kvh, group, d).sum(axis=3).reshape(
            b, s, kvh * d).astype(v3.dtype)
    return dq.astype(q3.dtype), dk, dv


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_flash(causal: bool, block_q: int, block_k: int, interpret: bool,
                dropout_rate: float, num_heads: int, head_dim: int,
                fuse_rope: bool, return_lse: bool = False,
                num_kv_heads: Optional[int] = None,
                backward: Optional[str] = None,
                segmented: bool = False):
    """custom_vjp'd kernel entry over *folded* ``[b, s, h*d]`` operands.

    The fold matters twice. Memory: with head_dim 64, BSHD/BHSD tensors
    pad their minor dim to the 128-lane tile (2x expansion on every saved
    activation — q/k/v/o per layer); saving residuals as ``[b, s, h*d]``
    keeps the minor dim at hidden size, so the autodiff-saved buffers are
    unpadded. Copies: the kernels' BlockSpecs slice per-head ``[*, d]``
    column blocks straight out of the folded layout, so no BSHD transpose
    ever materializes in HBM (round 2 paid a layout copy per operand per
    layer around every pallas call). With ``fuse_rope``, residuals are
    additionally *pre-rotation* — the rotated q/k never exist outside
    VMEM.

    The backward uses its own block sizes (``_BWD_BLOCK``): it is
    FLOP-bound (5 dots per block pair, no online-softmax rescan), so
    causal block-skipping at 512 beats the forward's single-block layout.
    """
    h, d = num_heads, head_dim
    kvh = num_kv_heads if num_kv_heads is not None else h
    group = h // kvh
    hp = _heads_per_program(d, interpret)
    # A paired program's two query heads may straddle a K/V head boundary,
    # so under hp > 1 grouped K/V expands to per-query-head copies (the
    # repeated-KV-MHA identity) before the kernels, and dk/dv group-sum
    # back afterwards (in f32 — one rounding after the reduction).
    expand_kv = group > 1 and hp > 1
    kernel_kvh = h if expand_kv else kvh
    kw = dict(causal=causal, block_q=block_q, block_k=block_k,
              interpret=interpret, dropout_rate=dropout_rate,
              num_heads=h, head_dim=d, num_kv_heads=kernel_kvh,
              segmented=segmented)
    bwd_kw = dict(kw, f32_kv_grads=expand_kv, backward=backward)
    # Backward block shapes are chosen per-path inside _flash_backward
    # (the fused pass prefers 512 blocks, the split kernels keep the
    # caller's). Safe under dropout either way: hardware-PRNG masks
    # generate in fixed 512x512 tiles keyed by absolute coordinates (see
    # _keep), so any pair of 512-divisible (or equal) fwd/bwd block
    # shapes sees identical masks.

    def _expand(x3):
        if not expand_kv:
            return x3
        b, s, _ = x3.shape
        return jnp.broadcast_to(
            x3.reshape(b, s, kvh, 1, d), (b, s, kvh, group, d)
        ).reshape(b, s, h * d)

    def _group_sum(g3, like):
        if not expand_kv:
            return g3
        b, s, _ = g3.shape
        return g3.reshape(b, s, kvh, group, d).sum(axis=3).reshape(
            b, s, kvh * d).astype(like.dtype)

    def _fwd(q3, k3, v3, seed_f, seg_f, cos, sin):
        # Returns (o3, lse, qr3, kr3): under fuse_rope the kernel emits the
        # rotated-scaled q and rotated k, which replace the raw q3/k3 in
        # the autodiff residuals so the backward never re-rotates per
        # block; without rope qr3/kr3 are None. seg_f is the [b, s] f32
        # bit-carrier of the int32 segment ids (a (1, 1) placeholder when
        # not segmented — the same dance as seed_f).
        rope = (cos, sin) if fuse_rope else None
        return _flash_forward(q3, _expand(k3), _expand(v3), seed_f, seg_f,
                              rope, **kw)

    def _save(q3, k3, v3, o3, lse, qr3, kr3, seed_f, seg_f, cos, sin):
        if fuse_rope:
            return (qr3, kr3, v3, o3, lse, seed_f, seg_f, cos, sin)
        return (q3, k3, v3, o3, lse, seed_f, seg_f, cos, sin)

    def _bwd_impl(res, do3, dlse=None):
        qs3, ks3, v3, o3, lse, seed_f, seg_f, cos, sin = res
        rope = (cos, sin) if fuse_rope else None
        # Under fuse_rope, ks3 is the kernel-width rotated k the forward
        # wrote (already expanded for GQA); otherwise expand the raw k3.
        kx3 = ks3 if fuse_rope else _expand(ks3)
        dq, dk, dv = _flash_backward(
            qs3, kx3, _expand(v3), o3, lse, do3, seed_f, seg_f, rope,
            dlse=dlse, **bwd_kw
        )
        return (dq, _group_sum(dk, v3), _group_sum(dv, v3),
                jnp.zeros_like(seed_f), jnp.zeros_like(seg_f),
                jnp.zeros_like(cos), jnp.zeros_like(sin))

    if return_lse:
        # (o, lse [b, h, s]) variant for blockwise composition (ring
        # attention combines per-chunk outputs by their logsumexps, so the
        # lse is a *differentiated* output — its cotangent folds into the
        # backward's delta row, see _flash_backward).
        @jax.custom_vjp
        def flash(q3, k3, v3, seed_f, seg_f, cos, sin):
            o3, lse = _fwd(q3, k3, v3, seed_f, seg_f, cos, sin)[:2]
            return o3, lse[:, :, 0, :]

        def fwd(q3, k3, v3, seed_f, seg_f, cos, sin):
            o3, lse, qr3, kr3 = _fwd(q3, k3, v3, seed_f, seg_f, cos, sin)
            return ((o3, lse[:, :, 0, :]),
                    _save(q3, k3, v3, o3, lse, qr3, kr3, seed_f, seg_f,
                          cos, sin))

        def bwd(res, cot):
            do3, dlse = cot
            return _bwd_impl(res, do3, dlse=dlse)

        flash.defvjp(fwd, bwd)
        return flash

    @jax.custom_vjp
    def flash(q3, k3, v3, seed_f, seg_f, cos, sin):
        return _fwd(q3, k3, v3, seed_f, seg_f, cos, sin)[0]

    def fwd(q3, k3, v3, seed_f, seg_f, cos, sin):
        o3, lse, qr3, kr3 = _fwd(q3, k3, v3, seed_f, seg_f, cos, sin)
        return o3, _save(q3, k3, v3, o3, lse, qr3, kr3, seed_f, seg_f,
                         cos, sin)

    def bwd(res, do3):
        return _bwd_impl(res, do3)

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    rope: Optional[tuple] = None,
    return_lse: bool = False,
    backward: Optional[str] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Blockwise causal flash attention; BSHD in, BSHD out.

    ``segment_ids`` ([batch, seq] int) isolates attention within packed
    documents: position i attends position j only when
    ``segment_ids[b, i] == segment_ids[b, j]`` (on top of causality).
    Blocks whose q-rows and k-columns share no segment are skipped at
    block granularity — the generalization of the causal below-diagonal
    skip — and only boundary blocks pay an elementwise mask. The packing
    convention is 0 = padding (pad attends pad; mask those targets in the
    loss) and documents 1..K. Segmented backward always runs the split
    two-kernel path.

    ``dropout_rate > 0`` (with a PRNG key) applies attention-weight dropout
    *inside* the kernel via a counter-based mask — no [seq, seq] mask array
    ever exists, and training with the reference's default attention dropout
    keeps the flash memory profile. ``rope=(cos, sin)`` ([seq, head_dim]
    f32 tables) fuses the rotary embedding into the kernel: q/k rotate in
    VMEM, never materializing rotated copies in HBM. Falls back to XLA's
    fused attention when the sequence length doesn't tile (the kernel
    requires ``seq % block == 0``) — e.g. odd-length generate windows —
    applying rope externally there.

    ``backward`` selects the backward kernel: ``"fused"`` (single pass,
    full-row dq residency), ``"split"`` (two-kernel dkv + dq passes,
    s-independent VMEM), or ``None``/``"auto"`` — fused for
    s <= ``_FUSED_BWD_MAX_SEQ``, split beyond, overridable via the
    ``TPU_TRAINER_FLASH_BWD`` env var (the sweep's knob).
    """
    b, s, h, d = q.shape
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    if backward is None:
        backward = (os.environ.get("TPU_TRAINER_FLASH_BWD", "").lower()
                    or None)
    if backward == "auto":
        backward = None
    if backward not in (None, "fused", "split"):
        raise ValueError(
            f"backward must be 'fused', 'split' or 'auto'; got {backward!r}"
        )
    segmented = segment_ids is not None
    if segmented:
        if segment_ids.shape != (b, s):
            raise ValueError(
                f"segment_ids must be [batch, seq] = {(b, s)}; "
                f"got {segment_ids.shape}"
            )
        if backward == "fused":
            raise NotImplementedError(
                "segment_ids require the split backward (the fused kernel "
                "has no segment masking)"
            )
    if h % k.shape[2] != 0:
        raise ValueError(
            f"num_heads {h} not divisible by num_kv_heads {k.shape[2]}"
        )
    if return_lse and (s % 128 != 0 or s < 128
                       or not (interpret or d == 64 or d % 128 == 0)):
        # The lse variant exists for blockwise composition (ring attention);
        # its callers check tiling first, so this is a programming error.
        raise NotImplementedError(
            f"return_lse requires a kernel-tileable sequence/head_dim "
            f"(s={s}, d={d})"
        )
    # Largest block <= the requested size that divides the sequence, so e.g.
    # seq=768 runs the kernel with 256-blocks rather than falling back to
    # the O(seq^2) path. (Dropout masks generate in fixed 512x512 tiles
    # keyed by absolute coordinates — see _keep — so the backward's
    # different block shape still sees the identical mask.)
    explicit_q, explicit_k = block_q is not None, block_k is not None
    block_q = block_q if explicit_q else DEFAULT_BLOCK_Q
    block_k = block_k if explicit_k else DEFAULT_BLOCK_K
    block_q = next((blk for blk in (block_q, 512, 256, 128)
                    if blk <= s and s % blk == 0), block_q)
    block_k = next((blk for blk in (block_k, 512, 256, 128)
                    if blk <= s and s % blk == 0), block_k)
    # Multi-block STREAMING (s > block): the [block_q, block_k] f32 score
    # block plus its exp/rotation/dropout temporaries must fit Mosaic's
    # 16 MB scoped VMEM per software-pipelined iteration; 1024x1024 fits
    # only as the single-block layout (s == block — no pipelining across
    # k blocks). Measured on v5e at s=2048: the 1024-block streaming
    # forward needs 18.9 MB and OOMs the scope, so DEFAULT streaming caps
    # at the 512 shape (the round-2 default; the backward already runs
    # 512s) — UNLESS the caller raised the scoped-VMEM limit via
    # ``LIBTPU_INIT_ARGS=--xla_tpu_scoped_vmem_limit_kib=...``: under a
    # raised scope the 1024 blocks fit and measure ~18% faster at s=4096
    # (benchmarks/longseq_block_sweep.py). Nothing in this repo raises the
    # flag anymore — the split backward made long sequences fit the
    # default scope, and bench.py dropped its raise — but an explicit
    # user raise is still honored. Explicitly-passed block sizes are
    # always honored.
    import re as _re

    _m = _re.search(r"scoped_vmem_limit_kib=(\d+)",
                    os.environ.get("LIBTPU_INIT_ARGS", ""))
    # 1024-block streaming needs ~19 MB of scope: only an explicit limit
    # comfortably above that counts as "raised" (a pinned 16 MB default
    # must still get the 512 cap).
    scope_raised = _m is not None and int(_m.group(1)) >= 20 * 1024
    if (not explicit_q and not scope_raised and s > block_q
            and block_q > 512 and s % 512 == 0):
        block_q = 512
    if (not explicit_k and not scope_raised and s > block_k
            and block_k > 512 and s % 512 == 0):
        block_k = 512
    # Compiled Mosaic lowering supports d=64 (two heads per program, lane
    # width 128) and d multiples of 128; other head dims take the XLA
    # fallback below (interpret mode has no lane constraint).
    kernel_ok = interpret or d == 64 or d % 128 == 0
    if s % block_q != 0 or s % block_k != 0 or s < 8 or not kernel_ok:
        if rope is not None:
            from tpu_trainer.ops.rope import apply_rotary_pos_emb

            q, k = apply_rotary_pos_emb(q, k, rope[0], rope[1])
        if segmented:
            # Dense segment-aware fallback (reference_attention builds the
            # combined causal x segment mask); it is unconditionally
            # causal, like the dropout fallback below.
            if not causal:
                raise NotImplementedError(
                    "non-causal segmented attention has no fallback path"
                )
            from tpu_trainer.ops.attention import reference_attention

            return reference_attention(
                q, k, v, dropout_rate=dropout_rate,
                deterministic=dropout_rate <= 0.0, dropout_rng=dropout_rng,
                segment_ids=segment_ids,
            )
        if dropout_rate > 0.0:
            # The XLA fused path has no attention dropout; keep the
            # configured semantics via the jnp reference path. That path is
            # unconditionally causal — fail loudly rather than silently
            # masking a non-causal caller.
            if not causal:
                raise NotImplementedError(
                    "non-causal attention with dropout on a non-tiling "
                    "sequence length has no kernel or fallback path"
                )
            from tpu_trainer.ops.attention import reference_attention

            return reference_attention(
                q, k, v, dropout_rate=dropout_rate, deterministic=False,
                dropout_rng=dropout_rng,
            )
        # jax.nn.dot_product_attention handles grouped K/V natively.
        return jax.nn.dot_product_attention(q, k, v, is_causal=causal)
    if dropout_rate > 0.0:
        if s >= 2**16:
            raise NotImplementedError(
                "kernel dropout counters are uint32: seq must be < 65536"
            )
        seed_bits = jax.random.bits(dropout_rng, dtype=jnp.uint32)
    else:
        seed_bits = jnp.uint32(0)
    seed_f = jax.lax.bitcast_convert_type(seed_bits, jnp.float32).reshape(1, 1)
    if segmented:
        seg_f = jax.lax.bitcast_convert_type(
            segment_ids.astype(jnp.int32), jnp.float32)
    else:
        seg_f = jnp.zeros((1, 1), jnp.float32)  # unused placeholder
    fuse_rope = rope is not None
    if fuse_rope:
        cos, sin = rope[0].astype(jnp.float32), rope[1].astype(jnp.float32)
    else:
        cos = sin = jnp.zeros((1, 1), jnp.float32)  # unused placeholder
    kvh = k.shape[2]
    h_k = h
    if not interpret and d == 64 and h % 2 == 1:
        # Head pairing needs an even head count (e.g. gpt2-xl's 25 heads):
        # expand grouped K/V to per-query-head copies, then append one
        # all-zero head. Zero q/k give uniform scores (finite lse, finite
        # backward); zero dO upstream keeps its gradients zero. The pad and
        # the expansion sit outside the custom_vjp, so their VJPs
        # (slice/group-sum) are ordinary autodiff.
        if kvh != h:
            k = jnp.broadcast_to(k[:, :, :, None, :],
                                 (b, s, kvh, h // kvh, d)).reshape(b, s, h, d)
            v = jnp.broadcast_to(v[:, :, :, None, :],
                                 (b, s, kvh, h // kvh, d)).reshape(b, s, h, d)
        zpad = jnp.zeros((b, s, 1, d), q.dtype)
        q = jnp.concatenate([q, zpad], axis=2)
        k = jnp.concatenate([k, zpad.astype(k.dtype)], axis=2)
        v = jnp.concatenate([v, zpad.astype(v.dtype)], axis=2)
        h_k = h + 1
        kvh = h_k
    fn = _make_flash(
        causal, block_q, block_k, interpret, float(dropout_rate), h_k, d,
        fuse_rope, return_lse, kvh, backward, segmented,
    )
    # Folded [b, s, h*d] at the custom_vjp boundary (unpadded residuals).
    out = fn(
        q.reshape(b, s, h_k * d), k.reshape(b, s, kvh * d),
        v.reshape(b, s, kvh * d), seed_f, seg_f, cos, sin,
    )
    if return_lse:
        o3, lse = out
        return o3.reshape(b, s, h_k, d)[:, :, :h], lse[:, :h]
    return out.reshape(b, s, h_k, d)[:, :, :h]


# --- flash-decode: single-query attention over a PAGED KV cache -------------
#
# The serving engine's decode step (tpu_trainer/serving/): each request's
# KV history lives in fixed-size blocks scattered through a preallocated
# pool, addressed by a per-request block table. The kernel is the
# split-KV sibling of the split dkv/dq backward above — grid
# ``(batch, heads, n_splits, blocks_per_split)`` where each (batch, head,
# split) program walks its share of the request's cache blocks with an
# online softmax in VMEM scratch and flushes a partial (m, l, acc)
# triple; the per-split partials merge in plain jnp (the standard
# flash-decoding recombination: ``o = sum_s exp(m_s - m*) acc_s /
# sum_s exp(m_s - m*) l_s``). The block gather rides the BlockSpec index
# maps via scalar prefetch: the block table and lengths are
# ``num_scalar_prefetch`` operands, so ``tables[b, split*bps + j]``
# *indexes the k/v pool block to DMA* — the gather costs nothing beyond
# the reads the attention needed anyway.
#
# An int8 cache mode dequantizes gathered blocks in VMEM: the pools carry
# ``int8 [nblk, bs, kvh, d]`` plus blockwise absmax scales
# ``f32 [nblk, bs, kvh, d // quant_block_len(d)]`` (utils/quant.py — the
# same scheme as the quantized optimizer state).
#
# ``paged_attention_reference`` is the pure-jnp path: identical math via
# a full-table gather, used as the CPU serving path and the parity oracle
# tier-1 pins the kernel against (interpret=True).


def _decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, *rest,
                   block_size, bps, int8, nbq):
    """One (batch row, head, split) program; grid dim 3 walks the split's
    cache blocks sequentially with (m, l, acc) online-softmax state in
    VMEM scratch."""
    if int8:
        ks_ref, vs_ref, m_ref, l_ref, acc_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_ref, l_ref, acc_ref, m_scr, l_scr, acc_scr = rest
    ib = pl.program_id(0)
    isp = pl.program_id(2)
    jb = pl.program_id(3)
    d = q_ref.shape[2]

    @pl.when(jb == 0)
    def _zero():
        m_scr[...] = jnp.full((1, 1), _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros((1, 1), jnp.float32)
        acc_scr[...] = jnp.zeros((1, d), jnp.float32)

    length = lengths_ref[ib]
    start = (isp * bps + jb) * block_size

    # Static body, predicated off for blocks wholly past this row's length
    # (same structure as the causal block skip in the training kernels).
    @pl.when(start < length)
    def _body():
        q = q_ref[0]                                    # [1, d] (pre-scaled)
        if int8:
            blkq = d // nbq
            k = (k_ref[0].astype(jnp.float32)
                 .reshape(block_size, nbq, blkq)
                 * ks_ref[0][:, :, None]).reshape(block_size, d)
            v = (v_ref[0].astype(jnp.float32)
                 .reshape(block_size, nbq, blkq)
                 * vs_ref[0][:, :, None]).reshape(block_size, d)
        else:
            k = k_ref[0]                                # [block_size, d]
            v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        pos = start + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_old = m_scr[...]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_old - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(jb == pl.num_programs(3) - 1)
    def _flush():
        m_ref[0, 0, 0, 0] = m_scr[0, 0]
        l_ref[0, 0, 0, 0] = l_scr[0, 0]
        acc_ref[0, 0, 0, :] = acc_scr[0, :]


def _auto_splits(max_blocks: int) -> int:
    """Largest divisor of the table width <= 4 (the split-KV parallelism
    knob; mb must split evenly so every program walks a static count)."""
    for ns in (4, 3, 2):
        if max_blocks % ns == 0:
            return ns
    return 1


def flash_decode(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    n_splits: int = 0,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-query attention over a paged KV cache (flash-decoding).

    - ``q``: ``[batch, heads, head_dim]`` — ONE query token per row.
    - ``pool_k/pool_v``: ``[num_blocks, block_size, kv_heads, head_dim]``
      block pool (float; or int8 with ``k_scale``/``v_scale``
      ``[num_blocks, block_size, kv_heads, d // quant_block_len(d)]``).
    - ``tables``: ``[batch, max_blocks]`` int32 — block ids per row, in
      position order; entries past a row's allocation should point at the
      reserved null block 0.
    - ``lengths``: ``[batch]`` int32 — valid tokens per row, INCLUDING the
      current one (so >= 1 for live rows; a length-0 row yields NaN).

    Returns f32 ``[batch, heads, head_dim]``. GQA: query head ``ih`` reads
    kv head ``ih // (heads // kv_heads)``. Compiled-mode tiling needs
    ``head_dim`` lane-compatible (64/128-multiples); interpret mode (the
    CPU serving and tier-1 path) has no constraint.
    """
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    nblk, bsz, kvh, dk = pool_k.shape
    assert dk == d and h % kvh == 0, (q.shape, pool_k.shape)
    group = h // kvh
    mb = tables.shape[1]
    int8 = pool_k.dtype == jnp.int8
    if int8 and (k_scale is None or v_scale is None):
        raise ValueError("int8 pools need k_scale/v_scale")
    nbq = k_scale.shape[-1] if int8 else 1
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not n_splits:
        n_splits = _auto_splits(mb)
    if mb % n_splits != 0:
        raise ValueError(f"max_blocks {mb} % n_splits {n_splits} != 0")
    bps = mb // n_splits

    qf = (q.astype(jnp.float32) * (1.0 / math.sqrt(d)))
    # Folded pool layouts so the BlockSpecs slice per kv head on the last
    # dim (same no-transpose trick as the training kernels' [b, s, h*d]).
    k3 = pool_k.reshape(nblk, bsz, kvh * d)
    v3 = pool_v.reshape(nblk, bsz, kvh * d)

    def _blk(width, col_scale):
        return pl.BlockSpec(
            (1, bsz, width),
            lambda ib, ih, isp, jb, tr, lr, _w=width, _c=col_scale:
            (tr[ib, isp * bps + jb], 0, ih // group),
        )

    in_specs = [
        pl.BlockSpec((1, 1, d), lambda ib, ih, isp, jb, tr, lr: (ib, ih, 0)),
        _blk(d, 1),
        _blk(d, 1),
    ]
    ops = [qf, k3, v3]
    if int8:
        in_specs += [_blk(nbq, 1), _blk(nbq, 1)]
        ops += [k_scale.reshape(nblk, bsz, kvh * nbq),
                v_scale.reshape(nblk, bsz, kvh * nbq)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, n_splits, bps),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, 1, 1),
                         lambda ib, ih, isp, jb, tr, lr: (ib, ih, 0, isp)),
            pl.BlockSpec((1, 1, 1, 1),
                         lambda ib, ih, isp, jb, tr, lr: (ib, ih, 0, isp)),
            pl.BlockSpec((1, 1, 1, d),
                         lambda ib, ih, isp, jb, tr, lr: (ib, ih, isp, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    m, l, acc = pl.pallas_call(
        functools.partial(_decode_kernel, block_size=bsz, bps=bps,
                          int8=int8, nbq=nbq),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, 1, n_splits), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, n_splits), jnp.float32),
            jax.ShapeDtypeStruct((b, h, n_splits, d), jnp.float32),
        ],
        interpret=interpret,
    )(tables, lengths, *ops)
    # Split merge: renormalize each split's accumulator by the global max
    # and combine (empty splits carry m = -inf -> weight exp(-inf) = 0).
    m_star = jnp.max(m, axis=-1, keepdims=True)              # [b, h, 1, 1]
    w = jnp.exp(m - m_star)[:, :, 0, :]                      # [b, h, S]
    l_tot = jnp.sum(l[:, :, 0, :] * w, axis=-1)              # [b, h]
    return jnp.einsum("bhs,bhsd->bhd", w, acc) / l_tot[:, :, None]


def paged_attention_reference(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Pure-jnp ``flash_decode``: gather the whole table view, mask past
    each row's length, plain f32 softmax. Same operands/result contract.
    The CPU serving path (a [b, mb*bsz] gather beats an interpreted grid
    walk by orders of magnitude) and the oracle the kernel tests pin
    against."""
    b, h, d = q.shape
    nblk, bsz, kvh, _ = pool_k.shape
    group = h // kvh
    mb = tables.shape[1]
    if pool_k.dtype == jnp.int8:
        nbq = k_scale.shape[-1]
        blkq = d // nbq
        deq = lambda p, s: (  # noqa: E731
            p.astype(jnp.float32).reshape(nblk, bsz, kvh, nbq, blkq)
            * s[..., None]).reshape(nblk, bsz, kvh, d)
        pool_k = deq(pool_k, k_scale)
        pool_v = deq(pool_v, v_scale)
    k = pool_k[tables].reshape(b, mb * bsz, kvh, d).astype(jnp.float32)
    v = pool_v[tables].reshape(b, mb * bsz, kvh, d).astype(jnp.float32)
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), k)
    s = s * (1.0 / math.sqrt(d))
    pos = jnp.arange(mb * bsz)[None, None]
    s = jnp.where(pos < lengths[:, None, None], s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", w, v)


def paged_attention_sharded(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    mesh,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Tensor-parallel paged decode dispatch: ``flash_decode`` (or the
    reference) under a full-manual ``shard_map`` over a single-axis
    device mesh, Q heads split along the mesh axis.

    Two KV layouts, matching the pool placement the serving engine
    commits (serving/sharding.py):

    - ``kv_heads % tp == 0``: pools arrive sharded on their kv-heads
      axis; each device runs the stock kernel on its ``h/tp`` Q heads x
      ``kvh/tp`` kv heads slice (the per-device GQA group size is
      unchanged, so the kernel's ``ih // group`` indexing needs no
      adjustment).
    - ``tp % kv_heads == 0`` (GQA, kv_heads < tp): pools arrive
      replicated; each device's contiguous Q-head slice falls inside ONE
      kv group, so the body slices kv head ``axis_index // (tp // kvh)``
      and runs the kernel with a single kv head.

    Either way each device owns a disjoint contiguous slice of the
    output's heads axis; the final ``psum`` all-reduce of zero-padded
    slices is therefore an exact concatenation (every output element has
    exactly one non-zero contributor — no floating-point reassociation),
    which is what keeps the sharded engine bit-identical to the
    single-device one. Returns f32 ``[batch, heads, head_dim]``, same
    contract as ``flash_decode``.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_trainer.utils.jax_compat import shard_map

    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "reference"
    fn = (functools.partial(flash_decode, interpret=interpret)
          if impl == "kernel" else paged_attention_reference)

    axis = mesh.axis_names[0]
    tp = int(mesh.devices.size)
    b, h, d = q.shape
    kvh = pool_k.shape[2]
    scales = () if k_scale is None else (k_scale, v_scale)
    if tp == 1:
        kw = ({"k_scale": k_scale, "v_scale": v_scale} if scales else {})
        return fn(q, pool_k, pool_v, tables, lengths, **kw)
    if h % tp:
        raise ValueError(f"heads {h} % tp {tp} != 0")
    hl = h // tp
    kv_shard = kvh % tp == 0
    if not kv_shard and tp % kvh:
        raise ValueError(f"kv_heads {kvh} vs tp {tp}: neither divides")

    pool_spec = P(None, None, axis, None) if kv_shard else P()
    in_specs = [P(None, axis, None), pool_spec, pool_spec, P(), P()]
    in_specs += [pool_spec] * len(scales)

    def body(q_l, pk, pv, tb, ln, *sc):
        i = jax.lax.axis_index(axis)
        if not kv_shard:
            def one_kv(x):
                return jax.lax.dynamic_slice_in_dim(
                    x, i // (tp // kvh), 1, axis=2)
            pk, pv = one_kv(pk), one_kv(pv)
            sc = tuple(one_kv(s) for s in sc)
        kw = {"k_scale": sc[0], "v_scale": sc[1]} if sc else {}
        out_l = fn(q_l, pk, pv, tb, ln, **kw)            # [b, h/tp, d]
        full = jnp.zeros((b, h, d), out_l.dtype)
        full = jax.lax.dynamic_update_slice(full, out_l, (0, i * hl, 0))
        return jax.lax.psum(full, axis)

    return shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=P(), check_vma=False)(
        q, pool_k, pool_v, tables, lengths, *scales)
