"""Pallas TPU causal flash attention (forward + backward).

The framework's native compute kernel (SURVEY.md C4): the TPU counterpart of
the reference's call into torch's fused ``scaled_dot_product_attention``
(``/root/reference/src/models/gpt.py:199-206``) — except implemented here as a
blockwise-streaming kernel rather than a library call.

Design (standard flash-attention-2 structure, written for the TPU memory
hierarchy):

- Grid ``(batch, heads, seq // block_q)``; each program owns one query block
  in VMEM and streams key/value blocks through the MXU with an online
  (running max / running sum) softmax. The ``[seq, seq]`` score matrix is
  never materialized in HBM — this is what removes the O(S^2) activation
  memory of the XLA fallback path.
- Causality skips whole key blocks above the diagonal (the inner
  ``fori_loop`` upper bound is the diagonal block), halving the FLOPs.
- Backward is one fused kernel (grid over key blocks): a single
  score/probability evaluation per block pair feeds dk, dv, and dq — dq
  accumulates in f32 in a VMEM-resident full-row block across sequential
  grid steps — using the saved per-row logsumexp and the precomputed
  ``delta = rowsum(dO * O)``.
- Attention-weight dropout runs in-kernel from a counter-based hash mask
  (regenerated bit-identically in the backward); RoPE optionally fuses in
  (q/k rotate in VMEM against [seq, head_dim] tables).
- All accumulation in float32 regardless of input dtype (bf16 in, bf16 out).

The public API is BSHD ``[batch, seq, heads, head_dim]`` (the model's
layout); internally the kernel uses BHSD so the (seq, head_dim) pair lands in
the last two dims, as the TPU (sublane, lane) tiling requires. Sequence
lengths must be multiples of the block size; the wrapper falls back to XLA
fused attention otherwise.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# 512-blocks keep the MXU busy (a [512,64]x[64,512] dot per inner step);
# 128-blocks measure ~2.3x slower end to end on v5e (pipeline bubbles
# dominate the small dots). The wrapper clamps to the sequence length.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = float("-inf")
_GOLDEN = 0x9E3779B9  # Weyl increment for the per-(batch,head) salt


def _keep_mask(seed_u32, salt_u32, q_start, k_start, bq: int, bk: int,
               seq: int, rate: float):
    """Deterministic counter-based dropout mask for one score block.

    A multiply-xorshift hash of the *global* (q, k) position plus a
    per-(batch, head) salt — recomputable bit-for-bit in the backward
    kernels (the flash-attention equivalent of storing the mask, at zero
    memory). Pure jnp bitwise ops, so it runs identically compiled on TPU
    and interpreted on CPU (``pltpu.prng_*`` has no interpret lowering).
    Positions must fit uint32: seq < 2**16.

    The hash is deliberately small — 6 VPU ops per element on the
    [block_q, block_k] score block (the kernel's hot elementwise chain):
    two multiply+xorshift rounds. One round is not enough: consecutive
    positions along a row make the pre-mix values a Weyl progression with
    stride 0xC2B2AE35, and a single xorshift only partially breaks that
    lattice (keep decisions stay equidistributed but spatially
    correlated). The second round restores per-element independence to
    statistical quality (verified by the autocorrelation test in
    tests/test_flash.py); the full murmur3 finalizer beyond that buys
    nothing for a Bernoulli threshold.
    """
    # Per-row base on a [bq, 1] column (cheap) broadcast against the column
    # iota: one add per element instead of full 2-D index arithmetic.
    rows = (q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
            ).astype(jnp.uint32)
    cols = (k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            ).astype(jnp.uint32)
    x = rows * jnp.uint32(seq) + cols
    x = x ^ (seed_u32 + salt_u32 * jnp.uint32(_GOLDEN))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    threshold = jnp.uint32(min(int(rate * 2**32), 2**32 - 1))
    return x >= threshold  # keep with probability 1 - rate


def _block_salt():
    """Per-(batch, head) hash salt from the grid position."""
    return (pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
            ).astype(jnp.uint32)


def _seed_from_ref(seed_ref):
    """uint32 seed scalar from the (1,1) SMEM input."""
    return seed_ref[0, 0]


def _rotate(x, cos, sin, out_dtype, scale=1.0):
    """RoPE rotation of one block (``x [n, d]``, ``cos/sin [n, d]`` f32):
    ``(x*cos + rotate_half(x)*sin) * scale``, f32 math, cast to ``out_dtype``.

    ``scale`` folds the attention's ``1/sqrt(d)`` into the (cheap) per-block
    q rotation so the [block_q, block_k] score matrix needs no per-element
    multiply. For even powers of two (d=16, 64, 256) the scale is itself a
    power of two, so the fold only adjusts exponents and is exact in bf16;
    for d=32/128 the scale is irrational and the folded q rounds once in
    the bf16 cast — one extra bf16-level rounding per q element relative
    to scaling the f32 score matrix, inside the tolerance the kernel tests
    already allow for bf16 inputs (tests/test_flash.py oracle comparison).
    """
    half = x.shape[-1] // 2
    x32 = x.astype(jnp.float32)
    rx = jnp.concatenate([-x32[..., half:], x32[..., :half]], axis=-1)
    out = x32 * cos + rx * sin
    if scale != 1.0:
        out = out * scale
    return out.astype(out_dtype)


def _unrotate_grad(g, cos, sin):
    """VJP of ``_rotate`` w.r.t. x applied to cotangent ``g`` (f32):
    ``g*cos + rotate_half^T(g*sin)`` where ``rotate_half^T([a,b]) = [b,-a]``."""
    half = g.shape[-1] // 2
    gs = g * sin
    rt = jnp.concatenate([gs[..., half:], -gs[..., :half]], axis=-1)
    return g * cos + rt


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, *rest,
                block_k, scale, causal, dropout_rate, fuse_rope):
    # q_ref: [1, 1, block_q, d]; k_ref/v_ref: [1, 1, seq, d];
    # lse_ref: [1, 1, 1, seq] (full row, written blockwise).
    # With fuse_rope, cos/sin [seq, d] ride along and q/k blocks rotate in
    # VMEM — no rotated copies ever hit HBM.
    if fuse_rope:
        cos_ref, sin_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    block_q = q_ref.shape[2]
    d = q_ref.shape[3]
    seq = k_ref.shape[2]
    iq = pl.program_id(2)
    q_start = iq * block_q
    seed = _seed_from_ref(seed_ref)
    salt = _block_salt()

    # Inputs stay in their storage dtype (bf16 in training): the MXU runs
    # bf16 x bf16 -> f32 at full rate, while f32 x f32 matmuls cost ~8x.
    # All softmax state is f32 via preferred_element_type. The 1/sqrt(d)
    # scale is folded into q once per program ([bq, d]) rather than into
    # every [bq, bk] score block.
    q = q_ref[0, 0, :, :]  # [bq, d]
    if fuse_rope:
        q = _rotate(q, cos_ref[pl.ds(q_start, block_q), :],
                    sin_ref[pl.ds(q_start, block_q), :], q_ref.dtype,
                    scale=scale)
    else:
        q = (q.astype(jnp.float32) * scale).astype(q_ref.dtype)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ik, carry, masked):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(ik * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(ik * block_k, block_k), :]
        if fuse_rope:
            k = _rotate(k, cos_ref[pl.ds(ik * block_k, block_k), :],
                        sin_ref[pl.ds(ik * block_k, block_k), :], k_ref.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk] f32 (already scaled via q)
        if masked:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # The softmax normalizer sums the *undropped* weights (dropout acts
        # on normalized weights in the reference, gpt.py:230-234 semantics).
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            # Survivors keep their raw weight here; the 1/(1-rate) inverted-
            # dropout scale folds into the final acc/l division (one [bq, 1]
            # multiply) instead of a per-element multiply per block.
            keep = _keep_mask(seed, salt, q_start, ik * block_k,
                              block_q, block_k, seq, dropout_rate)
            p = jnp.where(keep, p, 0.0)
        acc_new = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    carry = (m0, l0, acc0)
    if causal:
        # Key blocks strictly below the diagonal need no mask; only blocks
        # straddling it do. Splitting the loop keeps the iota/compare/select
        # chain off the interior blocks.
        num_full = q_start // block_k
        num_k = (q_start + block_q + block_k - 1) // block_k
        carry = jax.lax.fori_loop(
            0, num_full, functools.partial(body, masked=False), carry
        )
        carry = jax.lax.fori_loop(
            num_full, num_k, functools.partial(body, masked=True), carry
        )
    else:
        num_k = seq // block_k
        carry = jax.lax.fori_loop(
            0, num_k, functools.partial(body, masked=False), carry
        )
    m, l, acc = carry

    denom = l * (1.0 - dropout_rate) if dropout_rate > 0.0 else l
    o_ref[0, 0, :, :] = (acc / denom).astype(o_ref.dtype)
    lse_ref[0, 0, 0, pl.ds(q_start, block_q)] = m[:, 0] + jnp.log(l[:, 0])


def _seed_spec():
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _rope_specs(s, d):
    return [pl.BlockSpec((s, d), lambda ib, ih, i: (0, 0))] * 2


def _flash_forward(q, k, v, seed_f, rope, *, causal, block_q, block_k,
                   interpret, dropout_rate):
    # q: BHSD [b, h, s, d]; k, v: [b, kvh, s, d] (kvh <= h: grouped-query
    # attention shares one K/V head per group of h//kvh query heads — the
    # kernel's K/V BlockSpec maps grid head ih to K/V head ih // group, so
    # GQA costs nothing but the index map). seed_f: (1,1) float32
    # bit-carrier (floats so custom_vjp has a well-defined cotangent;
    # re-bitcast to uint32 here, outside the kernel — Mosaic can't bitcast
    # scalars in-kernel). rope: None or (cos, sin) [s, d] f32.
    seed_f = jax.lax.bitcast_convert_type(seed_f, jnp.uint32)
    b, h, s, d = q.shape
    group = h // k.shape[1]
    scale = 1.0 / math.sqrt(d)
    grid = (b, h, s // block_q)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda ib, ih, iq: (ib, ih, iq, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, s, d), lambda ib, ih, iq: (ib, ih // group, 0, 0)
    )
    row_spec = pl.BlockSpec((1, 1, 1, s), lambda ib, ih, iq: (ib, ih, 0, 0))
    fuse_rope = rope is not None
    rope_args = tuple(rope) if fuse_rope else ()
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, scale=scale, causal=causal,
            dropout_rate=dropout_rate, fuse_rope=fuse_rope,
        ),
        grid=grid,
        in_specs=[_seed_spec(), q_spec, kv_spec, kv_spec]
        + (_rope_specs(s, d) if fuse_rope else []),
        out_specs=[q_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(seed_f, q, k, v, *rope_args)
    return o, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _bwd_fused_kernel(
    seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q, scale, causal, dropout_rate, fuse_rope,
):
    """Single-pass backward: grid ``(b, h, seq // block_k)``.

    Each program owns one K/V block, streams the (causally relevant) query
    blocks once, and from a single score/probability computation produces
    its dk/dv block *and* the partial dq contributions. dq's BlockSpec index
    is constant in the kv grid dimension, so the full-row dq block stays
    resident in VMEM and accumulates across sequential grid steps (zeroed at
    the first kv block). Compared to separate dq and dk/dv kernels this
    halves the backward's score matmuls and q/do reads.

    With ``fuse_rope``, q/k blocks are re-rotated in VMEM for the score
    recomputation; dq/dk leave the kernel in *rotated* space and the caller
    applies the rotation's transpose (``_unrotate_grad``).
    """
    if fuse_rope:
        cos_ref, sin_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        dq_ref, dk_ref, dv_ref = rest
    block_k = k_ref.shape[2]
    d = k_ref.shape[3]
    seq = q_ref.shape[2]
    ik = pl.program_id(2)
    k_start = ik * block_k
    seed = _seed_from_ref(seed_ref)
    salt = _block_salt()

    @pl.when(ik == 0)
    def _zero_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    if fuse_rope:
        k = _rotate(k, cos_ref[pl.ds(k_start, block_k), :],
                    sin_ref[pl.ds(k_start, block_k), :], k_ref.dtype)

    def body(iq, carry, masked):
        dk, dv = carry
        # q is loaded pre-scaled by 1/sqrt(d) (folded into the [bq, d] load /
        # rotation): the score recompute then needs no per-element scale, and
        # dk = sum ds^T @ q_scaled IS the correctly-scaled dk (chain rule
        # puts one factor of `scale` on each of dq and dk).
        q = q_ref[0, 0, pl.ds(iq * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(iq * block_q, block_q), :]
        if fuse_rope:
            q = _rotate(q, cos_ref[pl.ds(iq * block_q, block_q), :],
                        sin_ref[pl.ds(iq * block_q, block_q), :], q_ref.dtype,
                        scale=scale)
        else:
            q = (q.astype(jnp.float32) * scale).astype(q_ref.dtype)
        lse = lse_ref[0, 0, 0, pl.ds(iq * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, 0, pl.ds(iq * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk] (scaled via q)
        if masked:
            row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] (normalized)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            # p_drop stays unscaled; the 1/(1-rate) folds into dv once at
            # the end ([bk, d] multiply instead of per-element per block).
            keep = _keep_mask(seed, salt, iq * block_q, k_start,
                              block_q, block_k, seq, dropout_rate)
            p_drop = jnp.where(keep, p, 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_drop = p
        dv_new = dv + jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)                      # [bq, bk]
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dq_part = jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        ) * scale
        sl = pl.ds(iq * block_q, block_q)
        dq_ref[0, 0, sl, :] += dq_part.astype(dq_ref.dtype)
        return dk_new, dv_new

    num_q = seq // block_q
    zeros = (jnp.zeros((block_k, d), jnp.float32),
             jnp.zeros((block_k, d), jnp.float32))
    if causal:
        # q blocks straddling the diagonal need the mask; q blocks strictly
        # below it (q_start >= k_end - 1) do not.
        start = k_start // block_q
        clear_from = (k_start + block_k - 1 + block_q - 1) // block_q
        carry = jax.lax.fori_loop(
            start, jnp.minimum(clear_from, num_q),
            functools.partial(body, masked=True), zeros,
        )
        dk, dv = jax.lax.fori_loop(
            jnp.minimum(clear_from, num_q), num_q,
            functools.partial(body, masked=False), carry,
        )
    else:
        dk, dv = jax.lax.fori_loop(
            0, num_q, functools.partial(body, masked=False), zeros
        )
    if fuse_rope:
        # dk leaves the kernel already un-rotated (the rotation's transpose
        # applied in VMEM) — no external f32 read-modify-write pass.
        cos_k = cos_ref[pl.ds(k_start, block_k), :]
        sin_k = sin_ref[pl.ds(k_start, block_k), :]
        dk = _unrotate_grad(dk, cos_k, sin_k)
    if dropout_rate > 0.0:
        dv = dv / (1.0 - dropout_rate)
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)

    if fuse_rope:
        # dq finishes accumulating at the last kv grid step (its block index
        # is constant in this grid dimension, so the full-row block is still
        # VMEM-resident): un-rotate it in place before it is written back.
        @pl.when(ik == pl.num_programs(2) - 1)
        def _unrotate_dq():
            dq = dq_ref[0, 0, :, :]
            dq_ref[0, 0, :, :] = _unrotate_grad(
                dq, cos_ref[...], sin_ref[...]
            ).astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, seed_f, rope, *, causal, block_q,
                    block_k, interpret, dropout_rate, dlse=None):
    b, h, s, d = q.shape
    kvh = k.shape[1]
    group = h // kvh
    scale = 1.0 / math.sqrt(d)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term.
    delta = jnp.einsum(
        "bhsd,bhsd->bhs", do.astype(jnp.float32), o.astype(jnp.float32)
    )[:, :, None, :]
    if dlse is not None:
        # lse is an exposed output (return_lse path): its cotangent enters
        # the score gradient as ds += p * dlse, i.e. exactly a -dlse shift
        # of the delta row — no kernel change needed.
        delta = delta - dlse.astype(jnp.float32)[:, :, None, :]

    seed_f = jax.lax.bitcast_convert_type(seed_f, jnp.uint32)
    blk = lambda n: pl.BlockSpec((1, 1, n, d), lambda ib, ih, i: (ib, ih, i, 0))
    kv_blk = lambda n: pl.BlockSpec(
        (1, 1, n, d), lambda ib, ih, i: (ib, ih // group, i, 0)
    )
    full = pl.BlockSpec((1, 1, s, d), lambda ib, ih, i: (ib, ih, 0, 0))
    row = pl.BlockSpec((1, 1, 1, s), lambda ib, ih, i: (ib, ih, 0, 0))
    fuse_rope = rope is not None
    rope_args = tuple(rope) if fuse_rope else ()

    # Fused single pass; dq accumulates in f32 across kv-block grid steps
    # (its block index is constant in that dimension, so it stays in VMEM).
    # Under fused rope, dq and dk are un-rotated *inside* the kernel (VMEM)
    # before they are written — no external pass over the gradients.
    # Under GQA each query head writes per-head dk/dv partials ([b, h, ...],
    # the same size MHA's dk/dv would be). The partials leave the kernel in
    # f32 so the group-sum accumulates at full precision and rounds to the
    # storage dtype exactly once, after the reduction — not once per
    # partial (the [b, h, s, d] f32 footprint is the same one the MHA dq
    # already pays).
    kv_grad_dtype = jnp.float32 if group > 1 else k.dtype
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, block_q=block_q, scale=scale,
                          causal=causal, dropout_rate=dropout_rate,
                          fuse_rope=fuse_rope),
        grid=(b, h, s // block_k),
        in_specs=[_seed_spec(), full, kv_blk(block_k), kv_blk(block_k), full,
                  row, row]
        + (_rope_specs(s, d) if fuse_rope else []),
        out_specs=[full, blk(block_k), blk(block_k)],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, d), kv_grad_dtype),
            jax.ShapeDtypeStruct((b, h, s, d), kv_grad_dtype),
        ],
        interpret=interpret,
    )(seed_f, q, k, v, do, lse, delta, *rope_args)
    if group > 1:
        dk = dk.reshape(b, kvh, group, s, d).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(b, kvh, group, s, d).sum(axis=2).astype(v.dtype)
    return dq.astype(q.dtype), dk, dv


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_flash(causal: bool, block_q: int, block_k: int, interpret: bool,
                dropout_rate: float, num_heads: int, head_dim: int,
                fuse_rope: bool, return_lse: bool = False,
                num_kv_heads: Optional[int] = None):
    """custom_vjp'd kernel entry over *folded* ``[b, s, h*d]`` operands.

    The fold matters for memory: with head_dim 64, BSHD/BHSD tensors pad
    their minor dim to the 128-lane tile (2x expansion on every saved
    activation — q/k/v/o per layer). Saving residuals as ``[b, s, h*d]``
    keeps the minor dim at hidden size, so the autodiff-saved buffers are
    unpadded; the BHSD form the kernels need exists only transiently around
    the pallas calls. With ``fuse_rope``, residuals are additionally
    *pre-rotation* — the rotated q/k never exist outside VMEM.
    """
    kw = dict(causal=causal, block_q=block_q, block_k=block_k,
              interpret=interpret, dropout_rate=dropout_rate)
    h, d = num_heads, head_dim
    kvh = num_kv_heads if num_kv_heads is not None else h

    def to_bhsd(x3, heads=h):
        b, s, _ = x3.shape
        return x3.reshape(b, s, heads, d).transpose(0, 2, 1, 3)

    def to_flat(x4):
        b, nh, s, _ = x4.shape
        return x4.transpose(0, 2, 1, 3).reshape(b, s, nh * d)

    def _fwd(q3, k3, v3, seed_f, cos, sin):
        rope = (cos, sin) if fuse_rope else None
        o, lse = _flash_forward(
            to_bhsd(q3), to_bhsd(k3, kvh), to_bhsd(v3, kvh), seed_f, rope,
            **kw
        )
        return to_flat(o), lse

    if return_lse:
        # (o, lse [b, h, s]) variant for blockwise composition (ring
        # attention combines per-chunk outputs by their logsumexps, so the
        # lse is a *differentiated* output — its cotangent folds into the
        # backward's delta row, see _flash_backward).
        @jax.custom_vjp
        def flash(q3, k3, v3, seed_f, cos, sin):
            o3, lse = _fwd(q3, k3, v3, seed_f, cos, sin)
            return o3, lse[:, :, 0, :]

        def fwd(q3, k3, v3, seed_f, cos, sin):
            o3, lse = _fwd(q3, k3, v3, seed_f, cos, sin)
            return (o3, lse[:, :, 0, :]), (q3, k3, v3, o3, lse, seed_f, cos, sin)

        def bwd(res, cot):
            do3, dlse = cot
            q3, k3, v3, o3, lse, seed_f, cos, sin = res
            rope = (cos, sin) if fuse_rope else None
            dq, dk, dv = _flash_backward(
                to_bhsd(q3), to_bhsd(k3, kvh), to_bhsd(v3, kvh),
                to_bhsd(o3), lse, to_bhsd(do3), seed_f, rope, dlse=dlse,
                **kw
            )
            return (to_flat(dq), to_flat(dk), to_flat(dv),
                    jnp.zeros_like(seed_f), jnp.zeros_like(cos),
                    jnp.zeros_like(sin))

        flash.defvjp(fwd, bwd)
        return flash

    @jax.custom_vjp
    def flash(q3, k3, v3, seed_f, cos, sin):
        return _fwd(q3, k3, v3, seed_f, cos, sin)[0]

    def fwd(q3, k3, v3, seed_f, cos, sin):
        o3, lse = _fwd(q3, k3, v3, seed_f, cos, sin)
        return o3, (q3, k3, v3, o3, lse, seed_f, cos, sin)

    def bwd(res, do3):
        q3, k3, v3, o3, lse, seed_f, cos, sin = res
        rope = (cos, sin) if fuse_rope else None
        dq, dk, dv = _flash_backward(
            to_bhsd(q3), to_bhsd(k3, kvh), to_bhsd(v3, kvh), to_bhsd(o3),
            lse, to_bhsd(do3), seed_f, rope, **kw
        )
        return (to_flat(dq), to_flat(dk), to_flat(dv),
                jnp.zeros_like(seed_f), jnp.zeros_like(cos),
                jnp.zeros_like(sin))

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    rope: Optional[tuple] = None,
    return_lse: bool = False,
) -> jax.Array:
    """Blockwise causal flash attention; BSHD in, BSHD out.

    ``dropout_rate > 0`` (with a PRNG key) applies attention-weight dropout
    *inside* the kernel via a counter-based mask — no [seq, seq] mask array
    ever exists, and training with the reference's default attention dropout
    keeps the flash memory profile. ``rope=(cos, sin)`` ([seq, head_dim]
    f32 tables) fuses the rotary embedding into the kernel: q/k rotate in
    VMEM, never materializing rotated copies in HBM. Falls back to XLA's
    fused attention when the sequence length doesn't tile (the kernel
    requires ``seq % block == 0``) — e.g. odd-length generate windows —
    applying rope externally there.
    """
    b, s, h, d = q.shape
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    if h % k.shape[2] != 0:
        raise ValueError(
            f"num_heads {h} not divisible by num_kv_heads {k.shape[2]}"
        )
    if return_lse and (s % 128 != 0 or s < 128):
        # The lse variant exists for blockwise composition (ring attention);
        # its callers check tiling first, so this is a programming error.
        raise NotImplementedError(
            f"return_lse requires a kernel-tileable sequence (s={s})"
        )
    # Largest block <= the requested size that divides the sequence, so e.g.
    # seq=768 runs the kernel with 256-blocks rather than falling back to
    # the O(seq^2) path.
    block_q = next((blk for blk in (block_q, 256, 128) if blk <= s and s % blk == 0),
                   block_q)
    block_k = next((blk for blk in (block_k, 256, 128) if blk <= s and s % blk == 0),
                   block_k)
    if s % block_q != 0 or s % block_k != 0 or s < 8:
        if rope is not None:
            from tpu_trainer.ops.rope import apply_rotary_pos_emb

            q, k = apply_rotary_pos_emb(q, k, rope[0], rope[1])
        if dropout_rate > 0.0:
            # The XLA fused path has no attention dropout; keep the
            # configured semantics via the jnp reference path. That path is
            # unconditionally causal — fail loudly rather than silently
            # masking a non-causal caller.
            if not causal:
                raise NotImplementedError(
                    "non-causal attention with dropout on a non-tiling "
                    "sequence length has no kernel or fallback path"
                )
            from tpu_trainer.ops.attention import reference_attention

            return reference_attention(
                q, k, v, dropout_rate=dropout_rate, deterministic=False,
                dropout_rng=dropout_rng,
            )
        # jax.nn.dot_product_attention handles grouped K/V natively.
        return jax.nn.dot_product_attention(q, k, v, is_causal=causal)
    if dropout_rate > 0.0:
        if s >= 2**16:
            raise NotImplementedError(
                "kernel dropout counters are uint32: seq must be < 65536"
            )
        seed_bits = jax.random.bits(dropout_rng, dtype=jnp.uint32)
    else:
        seed_bits = jnp.uint32(0)
    seed_f = jax.lax.bitcast_convert_type(seed_bits, jnp.float32).reshape(1, 1)
    fuse_rope = rope is not None
    if fuse_rope:
        cos, sin = rope[0].astype(jnp.float32), rope[1].astype(jnp.float32)
    else:
        cos = sin = jnp.zeros((1, 1), jnp.float32)  # unused placeholder
    kvh = k.shape[2]
    fn = _make_flash(
        causal, block_q, block_k, interpret, float(dropout_rate), h, d,
        fuse_rope, return_lse, kvh,
    )
    # Folded [b, s, h*d] at the custom_vjp boundary (unpadded residuals);
    # the kernel-internal layout is BHSD for the (seq, head_dim) tiling.
    out = fn(
        q.reshape(b, s, h * d), k.reshape(b, s, kvh * d),
        v.reshape(b, s, kvh * d), seed_f, cos, sin,
    )
    if return_lse:
        o3, lse = out
        return o3.reshape(b, s, h, d), lse
    return out.reshape(b, s, h, d)
