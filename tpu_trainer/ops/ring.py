"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context sequence/context parallelism — a capability the reference lacks
entirely (SURVEY.md §5.7: no ring/blockwise/Ulysses anywhere) but that shapes
a TPU-native design from the start: sequences longer than one chip's memory
are sharded over a ``sequence`` mesh axis, and K/V shards rotate around the
ring over ICI while each device accumulates its queries' attention with an
online (running max / running sum) softmax — the same math as the flash
kernel (``ops/flash.py``), lifted one level up the memory hierarchy
(HBM-of-one-chip → HBM-of-the-ring).

Mechanics:

- Executed under ``shard_map`` over the ``sequence`` axis: each device holds
  ``[b, seq/sp, h, d]`` of q, k, v.
- ``sp`` steps; at step t a device holds the K/V chunk of device
  ``(i - t) % sp``, combines it into its partial (m, l, acc), then sends the
  chunk to its right neighbor with ``lax.ppermute`` (XLA overlaps the
  transfer with the next step's compute).
- Causality by *global* position: chunk offsets ``i*sl`` (queries) and
  ``src*sl`` (keys). Fully-future chunks contribute zero through the mask —
  every device runs the same step count (uniform SPMD control flow).
- **Zigzag (balanced-causal) layout, on by default** for even local
  lengths: each device is re-assigned the stripe pair ``(i, 2sp-1-i)``
  (two half-stripe ppermutes in, one pair out), after which every ring
  step carries exactly half a stripe-square of real work on *every*
  device — the contiguous layout computes the full score square because
  the synchronous ring makes everyone pay the worst device's bill
  (device 0 erases sp-1 of its sp chunks; device sp-1 needs them all).
  FLOP accounting per device: contiguous ring = sp chunk-squares; zigzag
  = 1 causal local block + (sp-1) half-blocks ≈ (sp+1)/2 — a 2x saving
  at large sp, load-balanced exactly.
- Differentiable by construction (pure jnp + ppermute, which has a
  well-defined transpose), so the backward pass needs no custom VJP.

The reference's only long-sequence levers are gradient checkpointing and a
fixed 1024 context (SURVEY.md §5.7); this module is the headroom beyond.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_trainer.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

SEQ_AXIS = "sequence"
_NEG_INF = float("-inf")


@dataclasses.dataclass(frozen=True)
class SequenceParallelContext:
    mesh: Mesh
    axis_name: str = SEQ_AXIS


_ACTIVE: Optional[SequenceParallelContext] = None


@contextlib.contextmanager
def sequence_parallel(mesh: Mesh, axis_name: str = SEQ_AXIS):
    """Trace-time context: while active, the model's attention dispatch routes
    through ``ring_attention`` over ``mesh``'s ``axis_name`` axis. (Static —
    consumed during jit tracing, not at runtime.)"""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = SequenceParallelContext(mesh, axis_name)
    try:
        yield
    finally:
        _ACTIVE = prev


def current_context() -> Optional[SequenceParallelContext]:
    return _ACTIVE


_MANUAL: Optional[SequenceParallelContext] = None


@contextlib.contextmanager
def sequence_parallel_manual(mesh: Mesh, axis_name: str = SEQ_AXIS):
    """Trace-time context for code already INSIDE a manual region bound to
    the sequence axis (the pipeline's jointly-manual shard_map over
    {stage, sequence}): the attention dispatch then runs the ring body
    directly — axis_index/ppermute against the bound axis — instead of
    opening a nested shard_map, which is exactly what tripped Shardy's
    nested manual-region axis binding (the round-2 SP x PP blocker)."""
    global _MANUAL
    prev = _MANUAL
    _MANUAL = SequenceParallelContext(mesh, axis_name)
    try:
        yield
    finally:
        _MANUAL = prev


def current_manual_context() -> Optional[SequenceParallelContext]:
    return _MANUAL


def ring_attention_manual(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    sp: int,
    axis_name: str = SEQ_AXIS,
    *,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Ring attention for callers already inside a manual region bound to
    ``axis_name`` (see ``sequence_parallel_manual``). ``q/k/v`` are the
    LOCAL ``[b, sl, h, d]`` shards (rope already applied at global
    positions); same zigzag-by-default selection as ``ring_attention``.

    The ring steps are unrolled statically here (``sp`` is a mesh
    constant): a ``fori_loop``-carried ppermute inside a *partial*-manual
    region is the construct Shardy cannot bind, while unrolled ppermutes
    bind fine.
    """
    b, sl, h, d = q.shape
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    scale = 1.0 / math.sqrt(d)
    zigzag = sp > 1 and sl % 2 == 0
    use_kernel, interpret = _kernel_mode(
        (sl // 2) if zigzag else sl, d
    )
    body = _zigzag_ring_local if zigzag else _ring_attention_local
    if dropout_rng is None:
        dropout_rng = jax.random.PRNGKey(0)  # unused when rate == 0
    return body(
        q, k, v, dropout_rng, axis_name=axis_name, sp=sp, scale=scale,
        dropout_rate=dropout_rate, use_kernel=use_kernel,
        interpret=interpret, unroll=True,
    )


def _kernel_mode(sl: int, head_dim: int):
    """``(use_kernel, interpret)`` for a chunk: the kernel runs when the
    chunk tiles the Pallas blocks, the head dim has a compiled lowering
    (64 or a multiple of 128 — flash.py's folded-layout constraint; any
    head dim works interpreted), and either a TPU is present or interpret
    mode is forced (the CPU test hook shared with the attention
    dispatch)."""
    import os

    from tpu_trainer.ops.attention import _INTERPRET_ENV

    interpret = os.environ.get(_INTERPRET_ENV, "0") == "1"
    if sl % 128 != 0:
        return False, interpret
    if not interpret and not (head_dim == 64 or head_dim % 128 == 0):
        return False, interpret
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    return (on_tpu or interpret), interpret


def _chunk_attention_jnp(q, k, v, causal, scale, dropout_rate, rng):
    """jnp fallback for one chunk: normalized attention + per-row lse.

    Same contract as the kernel path: returns ``(o [b,sl,h,d], lse
    [b,h,sl])`` where ``o`` is softmax-within-chunk (dropout applied to the
    normalized weights) and ``lse`` the *undropped* log-normalizer. Inputs
    stay in their storage dtype (bf16 x bf16 -> f32 runs at full MXU rate)
    with f32 accumulation — the flash kernel's dtype discipline.
    """
    b, sl, h, d = q.shape
    if k.shape[2] != h:  # GQA: the ring carries compact K/V; expand locally
        from tpu_trainer.ops.attention import repeat_kv

        k, v = repeat_kv(k, v, h)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)
        s = jnp.where((rows >= cols)[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                               # [b,h,sl]
    lse = m + jnp.log(jnp.sum(jnp.exp(s - m[..., None]), axis=-1))
    p = jnp.exp(s - lse[..., None])                       # normalized
    if dropout_rate > 0.0:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return o, lse


def _ring_attention_local(q, k, v, rng, *, axis_name: str, sp: int,
                          scale: float, dropout_rate: float,
                          use_kernel: bool, interpret: bool,
                          unroll: bool = False):
    """Per-device body under shard_map. q, k, v: local ``[b, sl, h, d]``.

    Each arriving K/V chunk is attended with the *flash kernel* (the chunk
    is one chip's worth — exactly the granularity the kernel is tuned for),
    returning per-chunk normalized outputs and logsumexps; chunks combine by
    the standard lse recombination ``out = Σ o_t·exp(lse_t − M) / Σ
    exp(lse_t − M)``. Only the t=0 chunk is the causal diagonal (a device
    always starts holding its own K/V), so the kernel's static ``causal``
    flag needs no dynamic dispatch: t=0 runs causal, every later chunk runs
    non-causal and fully-future chunks (src > idx) are erased by setting
    their lse to −inf before combining.

    Attention-weight dropout is supported (in-kernel counter-based mask, or
    bernoulli in the jnp fallback), decorrelated across (device, chunk)
    pairs by folding ``idx·sp + src`` into the key.
    """
    b, sl, h, d = q.shape
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def chunk(k_t, v_t, causal, rng_t):
        if use_kernel:
            from tpu_trainer.ops import flash

            return flash.flash_attention(
                q, k_t, v_t, causal=causal, dropout_rate=dropout_rate,
                dropout_rng=rng_t, interpret=interpret, return_lse=True,
            )
        return _chunk_attention_jnp(
            q, k_t, v_t, causal, scale, dropout_rate, rng_t
        )

    def fold(t_src):
        if dropout_rate > 0.0:
            return jax.random.fold_in(rng, t_src)
        return None

    # t = 0: own chunk — the causal diagonal.
    o0, lse0 = chunk(k, v, True, fold(idx * sp + idx))
    acc = o0.astype(jnp.float32)
    den = jnp.ones((b, h, sl), jnp.float32)
    m = lse0

    def step(t, carry):
        m, den, acc, k_t, v_t = carry
        k_t, v_t = lax.ppermute((k_t, v_t), axis_name, perm=perm)
        src = (idx - t) % sp            # global chunk id of the K/V now held
        o_t, lse_t = chunk(k_t, v_t, False, fold(idx * sp + src))
        # Fully-future chunk (src > idx): no key precedes any query here —
        # erase its contribution through the lse.
        lse_t = jnp.where(src > idx, _NEG_INF, lse_t)
        m_new = jnp.maximum(m, lse_t)
        alpha = jnp.exp(m - m_new)                        # [b,h,sl]
        w = jnp.exp(lse_t - m_new)
        to_bshd = lambda x: x.transpose(0, 2, 1)[..., None]
        acc = acc * to_bshd(alpha) + o_t.astype(jnp.float32) * to_bshd(w)
        den = den * alpha + w
        return m_new, den, acc, k_t, v_t

    if sp > 1:
        carry = (m, den, acc, k, v)
        if unroll:
            # Static unroll: a fori_loop-carried ppermute inside a
            # partial-manual region (the SP x PP joint shard_map) trips
            # Shardy's axis binding; unrolled ppermutes bind fine.
            for t in range(1, sp):
                carry = step(t, carry)
        else:
            carry = lax.fori_loop(1, sp, step, carry)
        m, den, acc, _, _ = carry
    norm = den.transpose(0, 2, 1)[..., None]              # [b, sl, h, 1]
    return (acc / norm).astype(q.dtype)


def _to_zigzag(x, idx, axis_name: str, sp: int):
    """Contiguous chunk -> zigzag stripe pair, inside the ring's shard_map.

    Global layout in half-stripes of ``sl/2``: device ``i`` holds stripes
    ``(2i, 2i+1)`` contiguously; zigzag wants ``(i, 2sp-1-i)`` — the classic
    balanced-causal assignment where every device owns one "early" and one
    "late" stripe. Each pair ``(i, 2sp-1-i)`` has exactly one even and one
    odd member (their sum is odd), so two half-stripe ppermutes — one
    routing all even stripes, one all odd — deliver both, and a select on
    the device parity orders them (early first). Positions stay ascending
    across the concat, which is what lets the t=0 local block run a plain
    causal kernel.
    """
    half = x.shape[1] // 2
    lo_h, hi_h = x[:, :half], x[:, half:]
    owner = lambda j: j if j < sp else 2 * sp - 1 - j  # zigzag owner of stripe j
    perm_even = [(i, owner(2 * i)) for i in range(sp)]
    perm_odd = [(i, owner(2 * i + 1)) for i in range(sp)]
    a = lax.ppermute(lo_h, axis_name, perm=perm_even)   # the pair's even stripe
    c = lax.ppermute(hi_h, axis_name, perm=perm_odd)    # the pair's odd stripe
    even = (idx % 2) == 0
    lo = jnp.where(even, a, c)
    hi = jnp.where(even, c, a)
    return jnp.concatenate([lo, hi], axis=1)


def _from_zigzag(x, idx, axis_name: str, sp: int):
    """Inverse of ``_to_zigzag`` (applied to the attention output)."""
    half = x.shape[1] // 2
    lo, hi = x[:, :half], x[:, half:]        # stripes (idx, 2sp-1-idx)
    even = (idx % 2) == 0
    a = jnp.where(even, lo, hi)              # the even stripe of the pair
    c = jnp.where(even, hi, lo)              # the odd stripe
    owner = lambda j: j if j < sp else 2 * sp - 1 - j
    perm_even = [(owner(2 * i), i) for i in range(sp)]
    perm_odd = [(owner(2 * i + 1), i) for i in range(sp)]
    lo_h = lax.ppermute(a, axis_name, perm=perm_even)
    hi_h = lax.ppermute(c, axis_name, perm=perm_odd)
    return jnp.concatenate([lo_h, hi_h], axis=1)


def _zigzag_ring_local(q, k, v, rng, *, axis_name: str, sp: int,
                       scale: float, dropout_rate: float,
                       use_kernel: bool, interpret: bool,
                       unroll: bool = False):
    """Balanced (zigzag) ring body: every device does the same causal work.

    With contiguous chunks, device 0's queries precede every rotated K/V
    chunk, so it erases ``sp-1`` of its ``sp`` computations while device
    ``sp-1`` needs all of them — and since ring steps synchronize on
    ppermute, everyone pays the worst case: the ring computes the full
    score square (2x the causal FLOPs). In stripe space, at ring step
    ``t >= 1`` a device holding query stripes ``(i, 2sp-1-i)`` and K/V
    stripes ``(src, 2sp-1-src)`` needs exactly TWO of the four stripe
    pairs:

    - ``q_hi x k_lo`` — always (the late query stripe follows every early
      key stripe);
    - ``q_lo x k_lo`` if ``src < i``, else ``q_hi x k_hi`` — same shape
      either way, so the branch is two input *selects* feeding one kernel
      call: uniform SPMD control flow, no lax.cond.

    That is half the naive ring's compute, identical on every device. The
    t=0 local block is ascending-position (early stripe first), so it runs
    the plain causal kernel.
    """
    b, sl, h, d = q.shape
    half = sl // 2
    idx = lax.axis_index(axis_name)
    qz = _to_zigzag(q, idx, axis_name, sp)
    kz = _to_zigzag(k, idx, axis_name, sp)
    vz = _to_zigzag(v, idx, axis_name, sp)

    def chunk(qq, kk, vv, causal, rng_t):
        if use_kernel:
            from tpu_trainer.ops import flash

            return flash.flash_attention(
                qq, kk, vv, causal=causal, dropout_rate=dropout_rate,
                dropout_rng=rng_t, interpret=interpret, return_lse=True,
            )
        return _chunk_attention_jnp(
            qq, kk, vv, causal, scale, dropout_rate, rng_t
        )

    def fold(tag):
        if dropout_rate > 0.0:
            return jax.random.fold_in(rng, tag)
        return None

    def combine(carry, o_t, lse_t):
        m, den, acc = carry
        m_new = jnp.maximum(m, lse_t)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lse_t - m_new)
        to_bshd = lambda x: x.transpose(0, 2, 1)[..., None]
        acc = acc * to_bshd(alpha) + o_t.astype(jnp.float32) * to_bshd(w)
        den = den * alpha + w
        return m_new, den, acc

    neg = jnp.full((b, h, half), _NEG_INF, jnp.float32)
    zero = jnp.zeros((b, half, h, d), jnp.float32)

    # t = 0: the ordered local stripe pair — one causal block.
    o0, lse0 = chunk(qz, kz, vz, True, fold(idx))
    carry = (lse0, jnp.ones((b, h, sl), jnp.float32), o0.astype(jnp.float32))

    def step(t, state):
        carry, k_t, v_t = state
        k_t, v_t = lax.ppermute((k_t, v_t), axis_name, perm=[
            (i, (i + 1) % sp) for i in range(sp)
        ])
        src = (idx - t) % sp
        # call 1: late queries x early keys — needed at every step.
        o1, lse1 = chunk(qz[:, half:], k_t[:, :half], v_t[:, :half], False,
                         fold((t * 2 + 1) * sp + idx))
        # call 2: early-x-early when the arriving pair is older, else
        # late-x-late — selected by input, one kernel call either way.
        low_case = src < idx
        q2 = jnp.where(low_case, qz[:, :half], qz[:, half:])
        k2 = jnp.where(low_case, k_t[:, :half], k_t[:, half:])
        v2 = jnp.where(low_case, v_t[:, :half], v_t[:, half:])
        o2, lse2 = chunk(q2, k2, v2, False, fold((t * 2 + 2) * sp + idx))
        # Assemble full-row contributions and recombine by logsumexp.
        carry_new = combine(
            carry,
            jnp.concatenate([zero, o1.astype(jnp.float32)], axis=1),
            jnp.concatenate([neg, lse1], axis=2),
        )
        o2f = o2.astype(jnp.float32)
        carry_new = combine(
            carry_new,
            jnp.concatenate([jnp.where(low_case, o2f, 0.0),
                             jnp.where(low_case, 0.0, o2f)], axis=1),
            jnp.concatenate([jnp.where(low_case, lse2, _NEG_INF),
                             jnp.where(low_case, _NEG_INF, lse2)], axis=2),
        )
        return carry_new, k_t, v_t

    if sp > 1:
        state = (carry, kz, vz)
        if unroll:
            # See _ring_attention_local: static unroll for partial-manual
            # regions (SP x PP).
            for t in range(1, sp):
                state = step(t, state)
        else:
            state = lax.fori_loop(1, sp, step, state)
        carry, _, _ = state
    m, den, acc = carry
    norm = den.transpose(0, 2, 1)[..., None]
    out = (acc / norm).astype(q.dtype)
    return _from_zigzag(out, idx, axis_name, sp)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    *,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    zigzag: Optional[bool] = None,
) -> jax.Array:
    """Causal ring attention; global BSHD in/out, seq sharded over ``axis_name``.

    Requires ``seq % axis_size == 0``. With ``axis_size == 1`` this is plain
    blockwise attention (one step, no communication). On TPU (or with
    ``TPU_TRAINER_FLASH_INTERPRET=1``) each chunk runs through the Pallas
    flash kernel — chunk results recombine by logsumexp — so the
    long-context path keeps the kernel's memory profile and MXU efficiency
    instead of materializing [b, h, s/sp, s/sp] score blocks.
    ``dropout_rate > 0`` applies attention-weight dropout per chunk (the
    reference's semantics), decorrelated across devices and chunks.
    """
    b, s, h, d = q.shape
    sp = mesh.shape[axis_name]
    if s % sp != 0:
        raise ValueError(f"seq {s} not divisible by {axis_name} axis size {sp}")
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    scale = 1.0 / math.sqrt(d)
    # Keep the surrounding activation sharding across the shard_map boundary:
    # batch stays split over data x fsdp and heads over tensor (attention is
    # independent across both), so no all-gather is forced on entry
    # (parallel/mesh.py:attention_shard_spec).
    from tpu_trainer.parallel.mesh import (
        attention_shard_coord, attention_shard_spec,
    )

    b_spec, h_spec = attention_shard_spec(mesh, b, h, k.shape[2])
    spec = P(b_spec, axis_name, h_spec, None)
    import functools

    sl = s // sp
    if zigzag is None:
        # Balanced-causal stripes need an even local length; with one
        # device there is nothing to balance.
        zigzag = sp > 1 and sl % 2 == 0
    elif zigzag and sl % 2 != 0:
        raise ValueError(f"zigzag ring needs an even local length, got {sl}")
    if zigzag and sp > 1:
        # Kernel calls run at both sl (t=0) and sl/2 (ring steps).
        use_kernel, interpret = _kernel_mode(sl // 2, d)
        body = functools.partial(
            _zigzag_ring_local, axis_name=axis_name, sp=sp, scale=scale,
            dropout_rate=dropout_rate, use_kernel=use_kernel,
            interpret=interpret,
        )
    else:
        use_kernel, interpret = _kernel_mode(sl, d)
        body = functools.partial(
            _ring_attention_local, axis_name=axis_name, sp=sp, scale=scale,
            dropout_rate=dropout_rate, use_kernel=use_kernel,
            interpret=interpret,
        )
    if dropout_rng is None:
        dropout_rng = jax.random.PRNGKey(0)  # unused when rate == 0

    def local(q, k, v, rng):
        if dropout_rate > 0.0:
            # Distinct masks per batch/head shard too (chunk-level folding
            # happens inside the body).
            rng = jax.random.fold_in(
                rng, attention_shard_coord(mesh, b_spec, h_spec)
            )
        return body(q, k, v, rng)

    # Full-manual over the mesh (axes the specs don't mention are
    # replicated). Inside the pipeline's jointly-manual region, callers use
    # ring_attention_manual instead — the SP x PP composition path.
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, dropout_rng)
