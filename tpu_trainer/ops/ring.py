"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context sequence/context parallelism — a capability the reference lacks
entirely (SURVEY.md §5.7: no ring/blockwise/Ulysses anywhere) but that shapes
a TPU-native design from the start: sequences longer than one chip's memory
are sharded over a ``sequence`` mesh axis, and K/V shards rotate around the
ring over ICI while each device accumulates its queries' attention with an
online (running max / running sum) softmax — the same math as the flash
kernel (``ops/flash.py``), lifted one level up the memory hierarchy
(HBM-of-one-chip → HBM-of-the-ring).

Mechanics:

- Executed under ``shard_map`` over the ``sequence`` axis: each device holds
  ``[b, seq/sp, h, d]`` of q, k, v.
- ``sp`` steps; at step t a device holds the K/V chunk of device
  ``(i - t) % sp``, combines it into its partial (m, l, acc), then sends the
  chunk to its right neighbor with ``lax.ppermute`` (XLA overlaps the
  transfer with the next step's compute).
- Causality by *global* position: chunk offsets ``i*sl`` (queries) and
  ``src*sl`` (keys). Fully-future chunks contribute zero through the mask —
  every device runs the same step count (uniform SPMD control flow).
- Differentiable by construction (pure jnp + ppermute, which has a
  well-defined transpose), so the backward pass needs no custom VJP.

The reference's only long-sequence levers are gradient checkpointing and a
fixed 1024 context (SURVEY.md §5.7); this module is the headroom beyond.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

SEQ_AXIS = "sequence"
_NEG_INF = float("-inf")


@dataclasses.dataclass(frozen=True)
class SequenceParallelContext:
    mesh: Mesh
    axis_name: str = SEQ_AXIS


_ACTIVE: Optional[SequenceParallelContext] = None


@contextlib.contextmanager
def sequence_parallel(mesh: Mesh, axis_name: str = SEQ_AXIS):
    """Trace-time context: while active, the model's attention dispatch routes
    through ``ring_attention`` over ``mesh``'s ``axis_name`` axis. (Static —
    consumed during jit tracing, not at runtime.)"""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = SequenceParallelContext(mesh, axis_name)
    try:
        yield
    finally:
        _ACTIVE = prev


def current_context() -> Optional[SequenceParallelContext]:
    return _ACTIVE


def _ring_attention_local(q, k, v, *, axis_name: str, sp: int, scale: float):
    """Per-device body under shard_map. q, k, v: local ``[b, sl, h, d]``."""
    b, sl, h, d = q.shape
    idx = lax.axis_index(axis_name)

    rows = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)

    m0 = jnp.full((b, h, sl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc0 = jnp.zeros((b, sl, h, d), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(t, carry):
        m, l, acc, k_t, v_t = carry
        src = (idx - t) % sp  # global chunk id of the K/V currently held
        # Inputs stay in their storage dtype (bf16 x bf16 -> f32 runs at full
        # MXU rate; f32 matmuls cost ~8x) with f32 accumulation — the same
        # dtype discipline as the flash kernel (ops/flash.py).
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_t, preferred_element_type=jnp.float32
        ) * scale
        # Global causal mask: query position idx*sl + r, key src*sl + c.
        allowed = (idx * sl + rows) >= (src * sl + cols)
        s = jnp.where(allowed[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [b,h,q,k]; 0 where masked
        alpha = jnp.exp(m - m_new)                 # [b,h,q,1]
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        contrib = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_t.dtype), v_t,
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * alpha[:, :, :, 0].transpose(0, 2, 1)[..., None] + contrib
        k_n, v_n = lax.ppermute((k_t, v_t), axis_name, perm=perm)
        return m_new, l_new, acc_new, k_n, v_n

    m, l, acc, _, _ = lax.fori_loop(0, sp, step, (m0, l0, acc0, k, v))
    norm = l[:, :, :, 0].transpose(0, 2, 1)[..., None]   # [b, sl, h, 1]
    return (acc / norm).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
) -> jax.Array:
    """Causal ring attention; global BSHD in/out, seq sharded over ``axis_name``.

    Requires ``seq % axis_size == 0``. With ``axis_size == 1`` this is plain
    blockwise attention (one step, no communication).
    """
    b, s, h, d = q.shape
    sp = mesh.shape[axis_name]
    if s % sp != 0:
        raise ValueError(f"seq {s} not divisible by {axis_name} axis size {sp}")
    scale = 1.0 / math.sqrt(d)
    # Keep the surrounding activation sharding across the shard_map boundary:
    # batch stays split over data x fsdp and heads over tensor (attention is
    # independent across both), so no all-gather is forced on entry. Axes
    # that don't divide the dim (tiny test batches) fall back to replicated.
    from tpu_trainer.parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS

    batch_axes = (DATA_AXIS, FSDP_AXIS)
    dp = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    b_spec = batch_axes if (dp > 1 and b % dp == 0) else None
    tp = mesh.shape[TENSOR_AXIS]
    h_spec = TENSOR_AXIS if (tp > 1 and h % tp == 0) else None
    spec = P(b_spec, axis_name, h_spec, None)
    fn = shard_map(
        lambda q, k, v: _ring_attention_local(
            q, k, v, axis_name=axis_name, sp=sp, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
