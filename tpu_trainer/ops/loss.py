"""Fused LM-head + cross-entropy: the loss without the logits buffer.

The reference computes the loss by materializing full logits and calling
``F.cross_entropy`` (``/root/reference/src/models/gpt.py:447-453``). On TPU
that costs more than the matmul: ``[batch, seq, vocab]`` float32 logits for
the headline config are ~1.6 GB, written to HBM by the head matmul, re-read
by the softmax, and materialized again as the cotangent in the backward —
measured at ~34 ms of a ~120 ms step (28%), nearly all of it HBM traffic.

This module computes the identical shifted cross entropy blockwise: the
sequence is processed in chunks under a ``custom_vjp``; each chunk's logits
live only transiently (a ``[batch, chunk, vocab]`` block), the forward saves
just the per-token logsumexp (``[batch, seq]`` float32), and the backward
recomputes each chunk's logits once to form ``dx`` and the embedding
cotangent ``dE`` directly — full logits never exist in either pass.
Measured: 4.4x faster than the materialized path at GPT-2-small geometry
(83.8 ms -> 18.9 ms standalone fwd+bwd), bitwise-comparable gradients
(max |Δ| ~6e-8 vs the jnp oracle).

Chunking runs over the *sequence* dim so every operation keeps the batch dim
leading: under DP/FSDP meshes (batch sharded over ``data × fsdp``) each chunk
step is trivially partitionable and no resharding is introduced.

All accumulation is float32 (matmuls bf16-in/f32-out via
``preferred_element_type``), matching the model's loss-in-f32 contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Auto chunking targets ~8k tokens per chunk (~1.6 GB of transient f32
# logits at GPT-2 vocab): big chunks amortize the embedding-matrix reads and
# the dE-accumulator traffic; the sweep at headline geometry measured 8k-token
# chunks ~3 ms/step faster than 2k-token chunks.
_DEFAULT_CHUNK_TOKENS = 8192


def _chunk_len(batch: int, seq: int, chunk_size: int) -> int:
    """Sequence-chunk length: explicit override, else ~8k tokens per chunk
    (``_DEFAULT_CHUNK_TOKENS``), rounded down to a divisor of ``seq``. If the
    nearest divisor is degenerate (< 128 positions — e.g. a prime ``seq``),
    fall back to a single chunk rather than a many-iteration scan of sliver
    matmuls."""
    if chunk_size > 0:
        c = min(chunk_size, seq)
    else:
        # 8192 tokens is a target, not a floor: clamp at 128 positions so a
        # large global micro-batch (many-way data sharding) still chunks —
        # returning the full seq there would re-materialize the very
        # [b, seq, vocab] f32 block this loss exists to avoid.
        c = min(seq, max(128, _DEFAULT_CHUNK_TOKENS // max(batch, 1)))
    while seq % c != 0:  # largest divisor of seq that is <= c
        c -= 1
    if c < min(128, seq):
        # Degenerate divisor (e.g. prime seq): better one big chunk than a
        # many-iteration scan of sliver matmuls.
        return seq
    return c


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _chunked_ce(emb, x, labels, mask, chunk):
    return _ce_fwd_impl(emb, x, labels, mask, chunk)[0]


def _ce_fwd_impl(emb, x, labels, mask, chunk):
    b, s, h = x.shape
    e_bf = emb.astype(x.dtype)
    nchunks = s // chunk

    def body(loss_acc, idx):
        xc = jax.lax.dynamic_slice(x, (0, idx * chunk, 0), (b, chunk, h))
        lc = jax.lax.dynamic_slice(labels, (0, idx * chunk), (b, chunk))
        mc = jax.lax.dynamic_slice(mask, (0, idx * chunk), (b, chunk))
        # [b, c, V] f32 — the only logits that ever exist, per chunk.
        lg = jax.lax.dot_general(
            xc, e_bf, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lse = jax.nn.logsumexp(lg, axis=-1)
        ll = jnp.take_along_axis(lg, lc[..., None], axis=-1)[..., 0]
        return loss_acc + jnp.sum((lse - ll) * mc), lse

    loss, lses = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                              jnp.arange(nchunks))
    # lses: [nchunks, b, chunk] -> [b, s]
    lse_full = jnp.moveaxis(lses, 0, 1).reshape(b, s)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return loss / denom, (lse_full, denom)


def _ce_fwd(emb, x, labels, mask, chunk):
    loss, (lse, denom) = _ce_fwd_impl(emb, x, labels, mask, chunk)
    return loss, (emb, x, labels, mask, lse, denom)


def _ce_bwd(chunk, res, g):
    emb, x, labels, mask, lse, denom = res
    b, s, h = x.shape
    vocab = emb.shape[0]
    e_bf = emb.astype(x.dtype)
    scale = g / denom
    nchunks = s // chunk

    def body(carry, idx):
        de_acc, dx_buf = carry
        xc = jax.lax.dynamic_slice(x, (0, idx * chunk, 0), (b, chunk, h))
        lc = jax.lax.dynamic_slice(labels, (0, idx * chunk), (b, chunk))
        mc = jax.lax.dynamic_slice(mask, (0, idx * chunk), (b, chunk))
        zc = jax.lax.dynamic_slice(lse, (0, idx * chunk), (b, chunk))
        lg = jax.lax.dot_general(
            xc, e_bf, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp(lg - zc[..., None])
        onehot = jax.nn.one_hot(lc, vocab, dtype=jnp.float32)
        # d logits = (softmax - onehot) * mask * g/denom; bf16 for the matmuls
        # (cotangent magnitudes are <= 1; the f32 accumulation below keeps the
        # reductions exact).
        dlg = ((p - onehot) * (mc * scale)[..., None]).astype(x.dtype)
        dxc = jax.lax.dot_general(
            dlg, e_bf, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        de_acc = de_acc + jax.lax.dot_general(
            dlg, xc, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # Write the chunk into place — a [b, chunk, h] slice store, not a
        # post-hoc [nchunks, b, chunk, h] -> [b, s, h] transpose (the stacked
        # scan output costs a full layout-changing copy of dx; measured 3.4 ms
        # at headline geometry).
        dx_buf = jax.lax.dynamic_update_slice(
            dx_buf, dxc.astype(x.dtype), (0, idx * chunk, 0)
        )
        return (de_acc, dx_buf), None

    (de, dx), _ = jax.lax.scan(
        body,
        (jnp.zeros((vocab, h), jnp.float32), jnp.zeros((b, s, h), x.dtype)),
        jnp.arange(nchunks),
    )
    return de.astype(emb.dtype), dx, None, None


_chunked_ce.defvjp(_ce_fwd, _ce_bwd)


def fused_shifted_cross_entropy(
    emb: jax.Array,
    x: jax.Array,
    labels: jax.Array,
    *,
    chunk_size: int = 0,
) -> jax.Array:
    """Mean next-token cross entropy of the tied LM head, logits-free.

    Semantically identical to
    ``mean(softmax_xent(x @ emb.T [:, :-1], labels[:, 1:]))`` — the
    reference's shifted loss (``gpt.py:450-453``) — but computed blockwise
    (see module docstring).

    Args:
      emb: tied embedding matrix ``[vocab, hidden]`` (the LM head weight).
      x: final hidden states ``[batch, seq, hidden]`` (post final-norm).
      labels: token ids ``[batch, seq]`` (unshifted; shift happens here).
      chunk_size: sequence-chunk length; 0 = auto (~8k tokens per chunk).

    Returns: scalar float32 loss, averaged over ``batch * (seq - 1)``.
    """
    b, s, _ = x.shape
    shifted = jnp.concatenate(
        [labels[:, 1:], jnp.zeros((b, 1), labels.dtype)], axis=1
    )
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
    mask = (pos < s - 1).astype(jnp.float32)
    chunk = _chunk_len(b, s, chunk_size)
    return _chunked_ce(emb, x, shifted, mask, chunk)
