"""Fused LM-head + cross-entropy: the loss without the logits buffer.

The reference computes the loss by materializing full logits and calling
``F.cross_entropy`` (``/root/reference/src/models/gpt.py:447-453``). On TPU
that costs more than the matmul: ``[batch, seq, vocab]`` float32 logits for
the headline config are ~1.6 GB, written to HBM by the head matmul, re-read
by the softmax, and materialized again as the cotangent in the backward —
measured at ~34 ms of a ~120 ms step (28%), nearly all of it HBM traffic.

This module computes the identical shifted cross entropy blockwise: the
sequence is processed in chunks under a ``custom_vjp``; each chunk's logits
live only transiently (a ``[batch, chunk, vocab]`` block), the forward saves
just the per-token logsumexp (``[batch, seq]`` float32), and the backward
recomputes each chunk's logits once to form ``dx`` and the embedding
cotangent ``dE`` directly. Measured: 4.4x faster than the materialized path
at GPT-2-small geometry (83.8 ms -> 18.9 ms standalone fwd+bwd),
bitwise-comparable gradients (max |Δ| ~6e-8 vs the jnp oracle).

Two compiled-reality notes (round-4 xplane traces at headline geometry,
where ~8k tokens/step means ONE chunk):

- At nchunks == 1 the trip-1 scan unrolls and one ``[tokens, vocab]`` f32
  block DOES materialize transiently (1.54 GB at bs=8/seq=1024): the head
  matmul is hidden behind its own compute (write bandwidth ~495 GB/s
  against the 190 TFLOP/s dot), and XLA CSEs the backward body's
  "recompute" against the still-live forward logits — the backward re-runs
  nothing. An explicit save-compute-dtype-logits residual was measured
  2.3% SLOWER end-to-end than trusting this CSE (it adds a bf16 copy the
  compiler otherwise never builds). At nchunks > 1 (large global batches)
  the scans stay rolled, blocks stay ``[batch, chunk, vocab]``, and the
  backward genuinely recomputes — the memory-bound regime this blockwise
  design exists for.
- The remaining separable cost is the logsumexp pass re-reading the f32
  block (~2.2 ms at headline geometry, pure HBM) — the target of the
  Pallas fused head kernel (``ops/head_ce.py``) which carries the softmax
  statistics through the matmul online, flash-attention-style.

Chunking runs over the *sequence* dim so every operation keeps the batch dim
leading: under DP/FSDP meshes (batch sharded over ``data × fsdp``) each chunk
step is trivially partitionable and no resharding is introduced.

All accumulation is float32 (matmuls bf16-in/f32-out via
``preferred_element_type``), matching the model's loss-in-f32 contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Auto chunking targets ~8k tokens per chunk (~1.6 GB of transient f32
# logits at GPT-2 vocab): big chunks amortize the embedding-matrix reads and
# the dE-accumulator traffic; the sweep at headline geometry measured 8k-token
# chunks ~3 ms/step faster than 2k-token chunks.
_DEFAULT_CHUNK_TOKENS = 8192


def _chunk_len(batch: int, seq: int, chunk_size: int) -> int:
    """Sequence-chunk length: explicit override, else ~8k tokens per chunk
    (``_DEFAULT_CHUNK_TOKENS``), rounded down to a divisor of ``seq``. If the
    nearest divisor is degenerate (< 128 positions — e.g. a prime ``seq``),
    fall back to a single chunk rather than a many-iteration scan of sliver
    matmuls."""
    if chunk_size > 0:
        c = min(chunk_size, seq)
    else:
        # 8192 tokens is a target, not a floor: clamp at 128 positions so a
        # large global micro-batch (many-way data sharding) still chunks —
        # returning the full seq there would re-materialize the very
        # [b, seq, vocab] f32 block this loss exists to avoid.
        c = min(seq, max(128, _DEFAULT_CHUNK_TOKENS // max(batch, 1)))
    while seq % c != 0:  # largest divisor of seq that is <= c
        c -= 1
    if c < min(128, seq):
        # Degenerate divisor (e.g. prime seq): better one big chunk than a
        # many-iteration scan of sliver matmuls.
        return seq
    return c


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _chunked_ce(emb, x, labels, mask, chunk):
    return _ce_fwd_impl(emb, x, labels, mask, chunk)[0]


def _ce_fwd_impl(emb, x, labels, mask, chunk):
    b, s, h = x.shape
    e_bf = emb.astype(x.dtype)
    nchunks = s // chunk

    def body(loss_acc, idx):
        xc = jax.lax.dynamic_slice(x, (0, idx * chunk, 0), (b, chunk, h))
        lc = jax.lax.dynamic_slice(labels, (0, idx * chunk), (b, chunk))
        mc = jax.lax.dynamic_slice(mask, (0, idx * chunk), (b, chunk))
        # [b, c, V] f32 — the only logits that ever exist, per chunk.
        lg = jax.lax.dot_general(
            xc, e_bf, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lse = jax.nn.logsumexp(lg, axis=-1)
        ll = jnp.take_along_axis(lg, lc[..., None], axis=-1)[..., 0]
        return loss_acc + jnp.sum((lse - ll) * mc), lse

    loss, lses = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                              jnp.arange(nchunks))
    # lses: [nchunks, b, chunk] -> [b, s]
    lse_full = jnp.moveaxis(lses, 0, 1).reshape(b, s)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return loss / denom, (lse_full, denom)


def _ce_fwd(emb, x, labels, mask, chunk):
    loss, (lse, denom) = _ce_fwd_impl(emb, x, labels, mask, chunk)
    return loss, (emb, x, labels, mask, lse, denom)


def _ce_bwd(chunk, res, g):
    emb, x, labels, mask, lse, denom = res
    b, s, h = x.shape
    vocab = emb.shape[0]
    e_bf = emb.astype(x.dtype)
    scale = g / denom
    nchunks = s // chunk

    def body(carry, idx):
        de_acc, dx_buf = carry
        xc = jax.lax.dynamic_slice(x, (0, idx * chunk, 0), (b, chunk, h))
        lc = jax.lax.dynamic_slice(labels, (0, idx * chunk), (b, chunk))
        mc = jax.lax.dynamic_slice(mask, (0, idx * chunk), (b, chunk))
        zc = jax.lax.dynamic_slice(lse, (0, idx * chunk), (b, chunk))
        lg = jax.lax.dot_general(
            xc, e_bf, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp(lg - zc[..., None])
        onehot = jax.nn.one_hot(lc, vocab, dtype=jnp.float32)
        # d logits = (softmax - onehot) * mask * g/denom; bf16 for the matmuls
        # (cotangent magnitudes are <= 1; the f32 accumulation below keeps the
        # reductions exact).
        dlg = ((p - onehot) * (mc * scale)[..., None]).astype(x.dtype)
        dxc = jax.lax.dot_general(
            dlg, e_bf, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        de_acc = de_acc + jax.lax.dot_general(
            dlg, xc, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # Write the chunk into place — a [b, chunk, h] slice store, not a
        # post-hoc [nchunks, b, chunk, h] -> [b, s, h] transpose (the stacked
        # scan output costs a full layout-changing copy of dx; measured 3.4 ms
        # at headline geometry).
        dx_buf = jax.lax.dynamic_update_slice(
            dx_buf, dxc.astype(x.dtype), (0, idx * chunk, 0)
        )
        return (de_acc, dx_buf), None

    (de, dx), _ = jax.lax.scan(
        body,
        (jnp.zeros((vocab, h), jnp.float32), jnp.zeros((b, s, h), x.dtype)),
        jnp.arange(nchunks),
    )
    return de.astype(emb.dtype), dx, None, None


_chunked_ce.defvjp(_ce_fwd, _ce_bwd)


# --- vocab-sharded variant (the 1F1B pipeline head) -------------------------
#
# Inside the pipeline's manual region every stage holds a [ceil(V/S), h]
# slice of the LM head and computes ONLY its slice's logits; the softmax
# statistics are assembled with explicit collectives over the stage axis
# (pmax for the stabilizer, psum for the exp-sum and the label logit).
# Total head FLOPs across stages = one full head evaluation, split S ways —
# the fix for the masked-replicated head that ran S x (VERDICT r3 weak #1).
#
# A custom_vjp is load-bearing here, not an optimization: under
# ``shard_map(..., check_vma=False)`` the AD transpose of ``lax.psum`` is
# another psum, which would scale gradients by the axis size. Both passes
# below place their collectives explicitly; nothing differentiates through
# them.
#
# Contract: the returned loss is REPLICATED over ``axis_name``; the bwd's
# ``dx`` is this stage's PARTIAL contribution (the caller psums it once,
# after also pulling back through any ops outside this function — linearity
# makes one late psum equivalent to psumming here), and ``d e_slice`` is
# slice-local.

# -inf without the inf-inf => NaN hazard. A numpy scalar, NOT jnp: in
# current JAX ``jnp.float32(...)`` builds a device array, which would
# initialize the backend at import time and pin the platform before a CLI
# ``--device cpu`` / test-harness ``jax.config.update`` can choose it.
_NEG = np.float32(-1e30)


def _vshard_cols(vs: int, vocab: int, axis_name: str):
    """This stage's global column offset and intra-slice validity mask
    (the last slice may overhang a vocab that doesn't divide by S)."""
    off = jax.lax.axis_index(axis_name) * vs
    col = jax.lax.broadcasted_iota(jnp.int32, (vs,), 0)
    return off, (off + col) < vocab


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _chunked_ce_vshard(e_slice, x, labels, mask, chunk, axis_name, vocab,
                       seq_axis=None):
    return _ce_vshard_fwd_impl(e_slice, x, labels, mask, chunk, axis_name,
                               vocab, seq_axis)[0]


def _ce_vshard_fwd_impl(e_slice, x, labels, mask, chunk, axis_name, vocab,
                        seq_axis=None):
    b, s, h = x.shape
    vs = e_slice.shape[0]
    e_bf = e_slice.astype(x.dtype)
    off, col_ok = _vshard_cols(vs, vocab, axis_name)
    nchunks = s // chunk

    def body(loss_acc, idx):
        xc = jax.lax.dynamic_slice(x, (0, idx * chunk, 0), (b, chunk, h))
        lc = jax.lax.dynamic_slice(labels, (0, idx * chunk), (b, chunk))
        mc = jax.lax.dynamic_slice(mask, (0, idx * chunk), (b, chunk))
        lg = jax.lax.dot_general(
            xc, e_bf, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lg = jnp.where(col_ok, lg, _NEG)
        m_loc = jnp.max(lg, axis=-1)
        m_glob = jax.lax.pmax(m_loc, axis_name)
        se = jnp.sum(jnp.exp(lg - m_glob[..., None]), axis=-1)
        lse = m_glob + jnp.log(jax.lax.psum(se, axis_name))
        lcol = lc - off
        in_slice = jnp.logical_and(lcol >= 0, lcol < vs)
        ll_loc = jnp.where(
            in_slice,
            jnp.take_along_axis(
                lg, jnp.clip(lcol, 0, vs - 1)[..., None], axis=-1
            )[..., 0],
            0.0,
        )
        ll = jax.lax.psum(ll_loc, axis_name)
        return loss_acc + jnp.sum((lse - ll) * mc), lse

    loss, lses = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                              jnp.arange(nchunks))
    lse_full = jnp.moveaxis(lses, 0, 1).reshape(b, s)
    tok = jnp.sum(mask)
    if seq_axis is not None:
        # Tokens are split over the sequence axis too: the mean runs over
        # the GLOBAL token count, and the loss sums every shard's part
        # (replicated result; no collective needed in the bwd — the scale
        # g/denom is already per-global-token).
        loss = jax.lax.psum(loss, seq_axis)
        tok = jax.lax.psum(tok, seq_axis)
    denom = jnp.maximum(tok, 1.0)
    return loss / denom, (lse_full, denom)


def _ce_vshard_fwd(e_slice, x, labels, mask, chunk, axis_name, vocab,
                   seq_axis=None):
    loss, (lse, denom) = _ce_vshard_fwd_impl(
        e_slice, x, labels, mask, chunk, axis_name, vocab, seq_axis
    )
    return loss, (e_slice, x, labels, mask, lse, denom)


def _ce_vshard_bwd(chunk, axis_name, vocab, seq_axis, res, g):
    e_slice, x, labels, mask, lse, denom = res
    b, s, h = x.shape
    vs = e_slice.shape[0]
    e_bf = e_slice.astype(x.dtype)
    off, col_ok = _vshard_cols(vs, vocab, axis_name)
    scale = g / denom
    nchunks = s // chunk

    def body(carry, idx):
        de_acc, dx_buf = carry
        xc = jax.lax.dynamic_slice(x, (0, idx * chunk, 0), (b, chunk, h))
        lc = jax.lax.dynamic_slice(labels, (0, idx * chunk), (b, chunk))
        mc = jax.lax.dynamic_slice(mask, (0, idx * chunk), (b, chunk))
        zc = jax.lax.dynamic_slice(lse, (0, idx * chunk), (b, chunk))
        lg = jax.lax.dot_general(
            xc, e_bf, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lg = jnp.where(col_ok, lg, _NEG)
        # Local slice of the GLOBAL softmax (lse already spans the vocab);
        # overhang columns give exp(-1e30 - lse) == 0.
        p = jnp.exp(lg - zc[..., None])
        lcol = lc - off
        in_slice = jnp.logical_and(lcol >= 0, lcol < vs)
        onehot = jax.nn.one_hot(
            jnp.clip(lcol, 0, vs - 1), vs, dtype=jnp.float32
        ) * in_slice[..., None].astype(jnp.float32)
        dlg = ((p - onehot) * (mc * scale)[..., None]).astype(x.dtype)
        dxc = jax.lax.dot_general(
            dlg, e_bf, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        de_acc = de_acc + jax.lax.dot_general(
            dlg, xc, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dx_buf = jax.lax.dynamic_update_slice(
            dx_buf, dxc.astype(x.dtype), (0, idx * chunk, 0)
        )
        return (de_acc, dx_buf), None

    (de, dx), _ = jax.lax.scan(
        body,
        (jnp.zeros((vs, h), jnp.float32), jnp.zeros((b, s, h), x.dtype)),
        jnp.arange(nchunks),
    )
    # dx is this stage's PARTIAL d(hidden): the caller psums over axis_name
    # after its outer pullback (see module comment).
    return de.astype(e_slice.dtype), dx, None, None


_chunked_ce_vshard.defvjp(_ce_vshard_fwd, _ce_vshard_bwd)


def vocab_sharded_shifted_cross_entropy(
    e_slice: jax.Array,
    x: jax.Array,
    labels: jax.Array,
    *,
    vocab: int,
    axis_name: str,
    chunk_size: int = 0,
    seq_axis: str = None,
) -> jax.Array:
    """``fused_shifted_cross_entropy`` with the LM head sharded over a
    manual mesh axis: this device holds rows ``[idx*vs, (idx+1)*vs)`` of the
    embedding (``vs = e_slice.shape[0]``, zero-padded past ``vocab``) and
    the softmax statistics are assembled with pmax/psum over ``axis_name``.

    Must be called inside a ``shard_map`` manual over ``axis_name`` by
    EVERY member of the axis (collectives in both passes). The loss comes
    back replicated; the ``jax.vjp`` cotangent for ``x`` is the local
    partial — psum it over ``axis_name`` exactly once.

    With ``seq_axis`` (the jointly-manual SP x PP region), ``x`` is this
    device's sequence CHUNK while ``labels`` stay GLOBAL ``[b, s_global]``:
    the next-token shift is read from the global labels at the chunk's
    offset (the first token of the next chunk is just ``labels[c0 + s_l]``
    — no neighbor exchange), the mean runs over the global token count,
    and the loss comes back replicated over BOTH axes. The ``x`` cotangent
    stays chunk-local (each shard owns its tokens): psum it over
    ``axis_name`` only.
    """
    b, s, _ = x.shape
    if seq_axis is None:
        shifted = jnp.concatenate(
            [labels[:, 1:], jnp.zeros((b, 1), labels.dtype)], axis=1
        )
        pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
        mask = (pos < s - 1).astype(jnp.float32)
    else:
        s_g = labels.shape[1]
        c0 = jax.lax.axis_index(seq_axis) * s
        lab_pad = jnp.concatenate(
            [labels, jnp.zeros((b, 1), labels.dtype)], axis=1
        )
        shifted = jax.lax.dynamic_slice(lab_pad, (jnp.int32(0), c0 + 1),
                                        (b, s))
        pos = c0 + jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
        mask = (pos < s_g - 1).astype(jnp.float32)
    chunk = _chunk_len(b, s, chunk_size)
    return _chunked_ce_vshard(e_slice, x, shifted, mask, chunk, axis_name,
                              vocab, seq_axis)


def _pallas_head_ok(x: jax.Array, chunk_size: int) -> bool:
    """Route to the Pallas fused head kernel (``ops/head_ce.py``)?

    Compiled-TPU + bf16 compute + enough tokens to amortize the grid (but
    few enough that the kernel's ``[V, b, s]`` compute-dtype saved-logits
    residual stays moderate — it is NOT chunked, so past ~16k tokens the
    memory-bounding blockwise path wins). An explicit ``loss_chunk_size``
    is a memory-bounding request and always keeps the chunked XLA path.

    Sharding (round 5, VERDICT r4 #2 — the fallback list shrank): batch
    axes (data/fsdp) and the ``sequence`` axis are handled by the
    kernel's partial-manual shard_map (the shift/mask are global, so SP
    shards' local label slices are already correct); an ``expert`` axis
    shards only the expert parameters — tokens are replicated over it —
    so it no longer blocks the kernel. A ``stage`` axis means the
    pipeline owns the head (its own vocab-sharded form), and ``tensor``
    routes to the vocab-sharded XLA head (``_tp_loss`` below) — the two
    remaining non-kernel paths.
    """
    b, s, _ = x.shape
    if chunk_size > 0:
        return False
    if x.dtype != jnp.bfloat16 or not 2048 <= b * s <= 16384:
        return False
    if not any(d.platform == "tpu" for d in jax.devices()):
        return False
    from tpu_trainer.parallel.context import current_mesh

    mesh = current_mesh()
    if mesh is not None:
        for axis in ("stage", "tensor"):
            if mesh.shape.get(axis, 1) > 1:
                return False
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scale_grad(x, k):
    """Identity whose backward multiplies the cotangent by ``k``.

    shard_map's transpose seeds a replicated (``P()``) output's cotangent
    as ``g / axis_size`` per shard — the right rule when every shard runs
    the SAME computation on replicated inputs (the replicated-input
    cotangent psum then restores ``g``). The vocab-sharded loss below is
    not that: each shard pulls back through a DIFFERENT vocab slice, its
    ``d e_slice`` is slice-local (no psum benefit), and ``dx`` partials
    must each carry the full seed. Scaling the seed back up by the axis
    size inside the manual region makes both exact (pinned by
    tests/test_head_ce.py::test_tp_loss_matches_oracle at ts=8, and the
    2-device ratio repro that found the /ts: gradients came out
    oracle/ts without this).
    """
    return x


def _scale_grad_fwd(x, k):
    return x, None


def _scale_grad_bwd(k, _, g):
    return (g * k,)


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)


def _tp_loss(emb, x, shifted, mask, mesh, chunk_size):
    """Single-stage TP loss: the 1F1B vocab-sharded head, reused under a
    partial-manual shard_map over the ``tensor`` axis (VERDICT r4 #2).

    Under GSPMD-auto TP the head matmul contracts the h-sharded embedding
    and the compiler's cheapest legal plan materializes partial
    ``[b, chunk, V]`` f32 logits + an all-reduce over them per chunk.
    Here each tensor shard instead converts its ``[V, H/ts]`` hidden
    slice into a ``[ceil(V/ts), H]`` VOCAB slice with one tiled
    all-to-all (77 MB / ts per step at GPT-2 small — parameter-sized, not
    logits-sized), then runs ``_chunked_ce_vshard``: 1/ts of the head
    FLOPs per shard and only softmax *statistics* cross shards
    (pmax/psum over [b, chunk]). Batch axes stay GSPMD-auto; the
    replicated-input cotangent rule psums the partial dx exactly once,
    and the all-to-all transposes back to the h-sharded dE on its own.
    """
    from tpu_trainer.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tpu_trainer.parallel.mesh import TENSOR_AXIS

    ts = mesh.shape[TENSOR_AXIS]
    V, H = emb.shape
    vs = -(-V // ts)
    b, s, _ = x.shape
    chunk = _chunk_len(b, s, chunk_size)
    e_c = emb.astype(x.dtype)
    # Stock-XLA CPU bug (the same family as the documented bf16-PP CPU
    # crash, benchmarks/results.md): AllReducePromotion check-fails on the
    # bf16 all-reduce that shard_map inserts for the replicated x's
    # cotangent ("Invalid binary instruction opcode copy"). Feeding x in
    # f32 and casting inside moves that psum to f32 — CPU only; on TPU
    # the collective stays in compute dtype.
    on_cpu = all(d.platform == "cpu" for d in mesh.devices.flat)
    x_in = x.astype(jnp.float32) if on_cpu else x

    def local(e_l, x_l, lab_l, mask_l):
        e_pad = jnp.pad(e_l, ((0, vs * ts - V), (0, 0)))
        e_slice = jax.lax.all_to_all(
            e_pad, TENSOR_AXIS, split_axis=0, concat_axis=1, tiled=True
        )  # [vs, H]
        return _scale_grad(_chunked_ce_vshard(
            e_slice, x_l.astype(x.dtype), lab_l, mask_l, chunk,
            TENSOR_AXIS, V
        ), ts)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, TENSOR_AXIS), P(), P(), P()),
        out_specs=P(),
        axis_names={TENSOR_AXIS},
        check_vma=False,
    )(e_c, x_in, shifted, mask)


def segment_target_mask(segment_ids: jax.Array) -> jax.Array:
    """Float [batch, seq] mask of valid next-token targets under packing.

    Position t predicts token t+1; that target is trained only when both
    positions sit in the same non-padding document:
    ``seg[t+1] == seg[t] and seg[t] != 0``. Masks the cross-document
    leak (the last token of doc i must not be trained to predict the
    first token of doc i+1) and all padding targets. The final position
    comes out masked too (its shifted neighbor is the zero pad), matching
    the ``pos < s - 1`` mask it composes with.
    """
    b = segment_ids.shape[0]
    nxt = jnp.concatenate(
        [segment_ids[:, 1:], jnp.zeros((b, 1), segment_ids.dtype)], axis=1
    )
    return ((segment_ids == nxt) & (segment_ids != 0)).astype(jnp.float32)


def fused_shifted_cross_entropy(
    emb: jax.Array,
    x: jax.Array,
    labels: jax.Array,
    *,
    chunk_size: int = 0,
    allow_pallas: bool = True,
    segment_ids: jax.Array = None,
) -> jax.Array:
    """Mean next-token cross entropy of the tied LM head, logits-free.

    Semantically identical to
    ``mean(softmax_xent(x @ emb.T [:, :-1], labels[:, 1:]))`` — the
    reference's shifted loss (``gpt.py:450-453``) — but computed blockwise
    (see module docstring), or by the Pallas fused head kernel
    (``ops/head_ce.py``) on compiled TPU where eligible.

    Args:
      emb: tied embedding matrix ``[vocab, hidden]`` (the LM head weight).
      x: final hidden states ``[batch, seq, hidden]`` (post final-norm).
      labels: token ids ``[batch, seq]`` (unshifted; shift happens here).
      chunk_size: sequence-chunk length; 0 = auto (~8k tokens per chunk).
      allow_pallas: permit the Pallas kernel when eligible
        (``GPTConfig.fused_loss_pallas``).
      segment_ids: optional ``[batch, seq]`` packed-document ids
        (0 = padding); masks targets that cross a document boundary and
        shrinks the mean's denominator to the surviving targets.

    Returns: scalar float32 loss, averaged over the unmasked targets
    (``batch * (seq - 1)`` without segments).
    """
    b, s, _ = x.shape
    shifted = jnp.concatenate(
        [labels[:, 1:], jnp.zeros((b, 1), labels.dtype)], axis=1
    )
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
    mask = (pos < s - 1).astype(jnp.float32)
    if segment_ids is not None:
        mask = mask * segment_target_mask(segment_ids)
    from tpu_trainer.parallel.context import current_mesh

    mesh = current_mesh()
    if allow_pallas and _pallas_head_ok(x, chunk_size):
        from tpu_trainer.ops.head_ce import pallas_head_ce

        return pallas_head_ce(emb, x, shifted, mask, mesh, False)
    from tpu_trainer.utils.jax_compat import PARTIAL_MANUAL_OK

    if (mesh is not None and mesh.shape.get("tensor", 1) > 1
            and mesh.shape.get("stage", 1) == 1
            # The h-slice -> vocab-slice all_to_all needs H divisible by
            # the axis; indivisible H keeps the embedding replicated under
            # the TP rules (sharding.py _tensor_dim) and the blockwise
            # path below handles it as before.
            and emb.shape[1] % mesh.shape["tensor"] == 0
            # Old-jax ``auto=`` shard_map aborts the SPMD partitioner on
            # this composition; the blockwise path below is the same math
            # under pure GSPMD (partial logits + all-reduce), just without
            # the vocab-slice memory optimization.
            and PARTIAL_MANUAL_OK):
        return _tp_loss(emb, x, shifted, mask, mesh, chunk_size)
    chunk = _chunk_len(b, s, chunk_size)
    return _chunked_ce(emb, x, shifted, mask, chunk)
