"""Weighted multi-source data mixture with deterministic, resumable cursors.

One loader per source (dummy / tinystories / openwebtext / packed — anything
with the loader protocol: ``__iter__`` yielding host batches, plus
``state_dict``/``load_state_dict``), mixed per *global batch*: step ``i``
draws its source from ``np.random.default_rng((seed, i))`` against the
normalized weights. The choice sequence is a pure function of ``(seed, i)``
— no RNG object state to persist — so:

- **Exact resume** (PR-1 contract): the cursor is just ``batch_index`` plus
  each source's own cursor; replaying from it regenerates the identical
  batch sequence.
- **Elastic remap** (PR-7 contract): ``utils/checkpoint.remap_data_state``
  floor-divides the top-level ``batch_index`` onto a resized global batch;
  the per-source cursors are then *re-derived* from it (the number of draws
  source ``s`` received in steps ``[0, n)`` is itself a pure function of
  ``(seed, weights, n)`` — ``source_counts``), rather than trusted from the
  checkpoint, so a remapped top index never leaves a source cursor
  inconsistent with the mixture position.

Sources exhausting mid-run restart transparently (a new pass = the source
loader's next epoch), keeping the mixture an endless stream; ``num_batches``
bounds it for map-style-like use.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


def _normalized(weights: Dict[str, float]) -> Dict[str, float]:
    names = sorted(weights)
    total = float(sum(weights[n] for n in names))
    if total <= 0 or any(weights[n] < 0 for n in names):
        raise ValueError(f"mixture weights must be positive: {weights!r}")
    return {n: weights[n] / total for n in names}


def choose_source(seed: int, step: int, weights: Dict[str, float]) -> str:
    """The source for global batch ``step`` — pure in ``(seed, step)``.
    Names are consumed in sorted order so dict ordering can't skew draws."""
    u = np.random.default_rng((seed, step)).random()
    acc = 0.0
    names = sorted(weights)
    for name in names:
        acc += weights[name]
        if u < acc:
            return name
    return names[-1]  # float-sum slack


def source_counts(
    seed: int, weights: Dict[str, float], n: int
) -> Dict[str, int]:
    """Batches drawn from each source over steps ``[0, n)`` — the pure
    function the elastic resume path uses to rebuild per-source cursors
    after the top-level index was remapped."""
    w = _normalized(weights)
    counts = {name: 0 for name in w}
    for i in range(n):
        counts[choose_source(seed, i, w)] += 1
    return counts


class MixtureDataLoader:
    """Weighted round-per-batch mixture over named source loaders.

    ``sources``: name → loader; ``weights``: name → unnormalized weight.
    All sources must yield batches of identical shape (the trainer compiles
    one step). ``seed`` drives only the source choice; each source keeps its
    own data order and cursor.
    """

    def __init__(
        self,
        sources: Dict[str, object],
        weights: Dict[str, float],
        *,
        seed: int = 0,
        num_batches: Optional[int] = None,
    ):
        if set(sources) != set(weights):
            raise ValueError(
                f"sources {sorted(sources)} != weights {sorted(weights)}"
            )
        self.sources = sources
        self.weights = _normalized(weights)
        self.seed = seed
        self.num_batches = num_batches
        self._cur_batch = 0
        self._resume_skip = 0
        # Telemetry: the source of the batch most recently yielded, and
        # cumulative per-source batch counts this process — the trainer
        # threads last_source into the train JSONL (``data_source``) so
        # per-source loss can be read back out of one mixed run.
        self.last_source: Optional[str] = None
        self.batches_by_source: Dict[str, int] = {n: 0 for n in sources}

    # --- cursor protocol ---------------------------------------------------

    def state_dict(self) -> dict:
        """Top-level ``batch_index`` rides the standard remap path
        (``remap_data_state`` floor-divides it on a global-batch resize);
        per-source cursors are carried for the common same-geometry resume
        and re-derived from ``batch_index`` when they disagree with it."""
        return {
            "kind": "mixture",
            "batch_index": self._cur_batch,
            "seed": self.seed,
            "weights": dict(self.weights),
            "sources": {
                name: src.state_dict() for name, src in self.sources.items()
            },
        }

    def load_state_dict(self, state: dict) -> None:
        if state.get("kind") != "mixture":
            raise ValueError(
                f"data state kind {state.get('kind')!r} does not match this "
                f"'mixture' loader — the resumed run changed the data config"
            )
        n = int(state["batch_index"])
        self._cur_batch = n
        self._resume_skip = n
        saved = state.get("sources", {})
        if set(saved) != set(self.sources):
            raise ValueError(
                f"mixture sources changed across resume: checkpoint has "
                f"{sorted(saved)}, this run has {sorted(self.sources)}"
            )
        counts = source_counts(self.seed, self.weights, n)
        for name, src in self.sources.items():
            sub = dict(saved[name])
            drawn = counts[name]
            # Trust the saved sub-cursor only when it matches the pure
            # derivation (same-geometry resume); otherwise the top index was
            # remapped (elastic restart) and the sub-cursor is rebuilt from
            # the draw count — epoch wraps derived from the source's length
            # when it has one, assumed un-wrapped otherwise (the streaming
            # caveat remap_data_state already documents).
            consumed = self._consumed(sub, src)
            if consumed != drawn:
                per_epoch = None
                try:
                    per_epoch = len(src)
                except TypeError:
                    pass
                if per_epoch:
                    sub["epoch"] = drawn // per_epoch
                    sub["batch_index"] = drawn % per_epoch
                else:
                    sub["epoch"] = 0
                    sub["batch_index"] = drawn
            src.load_state_dict(sub)

    @staticmethod
    def _consumed(sub_state: dict, src) -> int:
        """Total batches a source consumed per its own cursor."""
        epoch = int(sub_state.get("epoch", 0))
        idx = int(sub_state.get("batch_index", 0))
        if epoch == 0:
            return idx
        try:
            return epoch * len(src) + idx
        except TypeError:
            return -1  # unknowable → force re-derivation

    # --- iteration ---------------------------------------------------------

    def __iter__(self) -> Iterator[np.ndarray]:
        start = self._resume_skip
        self._resume_skip = 0
        self._cur_batch = start
        its = {}
        i = start
        while self.num_batches is None or i < self.num_batches:
            name = choose_source(self.seed, i, self.weights)
            if name not in its:
                its[name] = iter(self.sources[name])
            try:
                batch = next(its[name])
            except StopIteration:
                its[name] = iter(self.sources[name])
                try:
                    batch = next(its[name])
                except StopIteration:
                    raise RuntimeError(
                        f"mixture source {name!r} yields no batches"
                    ) from None
            self._cur_batch = i + 1
            self.last_source = name
            self.batches_by_source[name] += 1
            yield batch
            i += 1

    @property
    def non_pad_frac(self) -> float:
        """Weighted padding accounting across sources that track it (packed
        sources); sources without the stat count as fully dense."""
        fracs = []
        for name in sorted(self.sources):
            fracs.append(
                (self.weights[name],
                 getattr(self.sources[name], "non_pad_frac", 1.0))
            )
        total = sum(w for w, _ in fracs)
        return sum(w * f for w, f in fracs) / total if total else 1.0
