"""Synthetic random-token data (SURVEY.md C24).

The reference's ``create_dummy_dataloader`` (``ddp_trainer.py:460-487``)
builds a fixed random-token corpus so the whole training stack runs with no
external data — its de-facto integration test backbone (SURVEY.md §4). Same
here: deterministic per-seed corpus, per-process disjoint slices, numpy on the
host (device placement happens in ``Trainer.put_batch``).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


class DummyDataLoader:
    """Yields ``[local_batch_size, seq_len]`` int32 token batches.

    ``batch_size`` is the *global* loader batch (micro_batch x grad_accum x
    data shards, matching the reference loader-batch semantics,
    ``ddp_trainer.py:538``); each process receives its disjoint
    ``batch_size / process_count`` rows — the analogue of the reference's
    ``DistributedSampler`` striding (C25).
    """

    def __init__(
        self,
        batch_size: int,
        seq_len: int,
        vocab_size: int = 50257,
        num_batches: int = 100,
        seed: int = 1234,
        process_index: int = 0,
        process_count: int = 1,
    ):
        if batch_size % process_count != 0:
            raise ValueError(
                f"global batch {batch_size} not divisible by {process_count} processes"
            )
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.num_batches = num_batches
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count
        self.local_batch_size = batch_size // process_count
        # Consumer-side cursor for exact resume (utils/checkpoint.py persists
        # it into meta.json as "data_state"; see TextDataLoader for the
        # real-data twin of this protocol).
        self._cur_epoch = 0
        self._cur_batch = 0
        self._resume_skip = 0

    def __len__(self) -> int:
        return self.num_batches

    def state_dict(self) -> dict:
        """Exact data-stream position: batches *consumed* this epoch (the
        cursor advances before each yield, so a checkpoint taken after
        training on batch k records k+1 — resuming continues at k+1)."""
        return {
            "kind": "dummy",
            "epoch": self._cur_epoch,
            "batch_index": self._cur_batch,
            "seed": self.seed,
        }

    def load_state_dict(self, state: dict) -> None:
        if state.get("kind", "dummy") != "dummy":
            raise ValueError(
                f"data state kind {state.get('kind')!r} does not match this "
                f"dummy loader — the resumed run changed --dataset"
            )
        self._cur_epoch = int(state["epoch"])
        self._cur_batch = int(state["batch_index"])
        self._resume_skip = self._cur_batch

    def __iter__(self) -> Iterator[np.ndarray]:
        start = self._resume_skip
        self._resume_skip = 0
        self._cur_batch = start
        for i in range(start, self.num_batches):
            # Batch i is a pure function of (seed, i): all processes agree on
            # the global batch and carve out disjoint row ranges — and a
            # resumed run regenerates batch i bit-exactly from the cursor.
            rng = np.random.default_rng((self.seed, i))
            batch = rng.integers(
                0, self.vocab_size, (self.batch_size, self.seq_len), dtype=np.int32
            )
            lo = self.process_index * self.local_batch_size
            self._cur_batch = i + 1
            yield batch[lo : lo + self.local_batch_size]
        self._cur_epoch += 1
        self._cur_batch = 0


def create_dummy_dataloader(
    batch_size: int,
    seq_len: int,
    vocab_size: int = 50257,
    num_batches: int = 100,
    seed: int = 1234,
    process_index: int = 0,
    process_count: int = 1,
) -> DummyDataLoader:
    """Factory, signature-parity with the reference (``ddp_trainer.py:460-487``)."""
    return DummyDataLoader(
        batch_size=batch_size,
        seq_len=seq_len,
        vocab_size=vocab_size,
        num_batches=num_batches,
        seed=seed,
        process_index=process_index,
        process_count=process_count,
    )
