"""Shared text-data engine: map-style + streaming tokenized datasets.

The reference implements this twice with near-identical code
(``/root/reference/src/data/tinystories.py`` and ``.../openwebtext.py`` —
SURVEY.md C20-C23); here the engine is one module and the dataset-specific
factories are thin wrappers (the de-duplication its README promised as
``src/data/dataloader.py`` but never shipped, SURVEY.md §0.1).

Components, with reference parity:

- **LRU token cache** (``tinystories.py:62-82``, ``openwebtext.py:67-93``):
  ``OrderedDict`` keyed by line index with a total-token budget
  (``cache_max_tokens``), evicting from the front.
- **Map-style dataset** (``tinystories.py:22-50``): tokenize the whole file
  up front (optionally capped by ``max_tokens``), concatenate, split into
  fixed ``seq_len`` chunks.
- **Streaming dataset** (``tinystories.py:53-119``, ``openwebtext.py:95-130``):
  line-modulo host sharding (``line_idx % num_shards == shard_id``,
  ``tinystories.py:98``), rolling token buffer emitting ``seq_len`` chunks
  (``:113-116``), ``max_tokens`` budget (``:103-108``). The shard is the JAX
  process (``process_index/process_count``) — the host is the worker on TPU,
  so the reference's ``rank*num_workers + worker_id`` collapses to the
  process index.
- **gzip transparency** (``openwebtext.py:32-37,71-74``) and ``.gz``↔plain
  path fallback (``openwebtext.py:147-155``) — available to every dataset.
- **Distributed sampling** (map-style; ``tinystories.py:150``,
  ``ddp_trainer.py:478``): per-host disjoint index striding with
  ``drop_last`` semantics, reshuffled per epoch with an epoch-seeded
  permutation — the ``set_epoch`` the reference forgets to call
  (SURVEY.md §2.1 b11).

Everything is host-side numpy; device placement happens in
``Trainer.put_batch`` with the batch's NamedSharding.
"""

from __future__ import annotations

import functools
import gzip
import os
from collections import OrderedDict
from typing import Iterator, List, Optional

import numpy as np

from tpu_trainer.utils.tokenizer import ByteTokenizer, get_tokenizer


class LRUTokenCache:
    """Token-budget LRU cache keyed by line index (reference
    ``tinystories.py:62-82``)."""

    def __init__(self, max_tokens: Optional[int]):
        self.max_tokens = max_tokens
        self._cache: OrderedDict[int, List[int]] = OrderedDict()
        self._tokens = 0

    def get(self, key: int) -> Optional[List[int]]:
        if key not in self._cache:
            return None
        self._cache.move_to_end(key)
        return self._cache[key]

    def put(self, key: int, tokens: List[int]) -> None:
        if self.max_tokens is None or self.max_tokens <= 0:
            return
        if key in self._cache:
            return
        self._cache[key] = tokens
        self._tokens += len(tokens)
        while self._tokens > self.max_tokens and self._cache:
            _, evicted = self._cache.popitem(last=False)  # evict oldest
            self._tokens -= len(evicted)

    def __len__(self) -> int:
        return len(self._cache)


def resolve_path(path: str) -> str:
    """``.gz``↔plain fallback (reference ``openwebtext.py:147-155``): if the
    given path is missing but its gz (or ungz) sibling exists, use that."""
    if os.path.exists(path):
        return path
    if path.endswith(".gz") and os.path.exists(path[:-3]):
        return path[:-3]
    if not path.endswith(".gz") and os.path.exists(path + ".gz"):
        return path + ".gz"
    raise FileNotFoundError(path)


def open_text(path: str):
    """Transparent text open for plain or gzip files
    (reference ``openwebtext.py:32-37``)."""
    if path.endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8", errors="replace")
    return open(path, "r", encoding="utf-8", errors="replace")


def read_bytes(path: str, limit: Optional[int] = None) -> bytes:
    """Raw bytes with gzip transparency (native fast path). ``limit`` caps
    the read so a token budget doesn't force loading a huge corpus."""
    if path.endswith(".gz"):
        with gzip.open(path, "rb") as f:
            return f.read() if limit is None else f.read(limit)
    with open(path, "rb") as f:
        return f.read() if limit is None else f.read(limit)


class TextDataset:
    """Map-style: tokenize the whole file, chunk to ``seq_len``
    (reference ``tinystories.py:22-50``).

    ``__getitem__(i)`` returns an int32 ``[seq_len]`` chunk.
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        tokenizer_name: str = "gpt2",
        max_tokens: Optional[int] = None,
        num_workers: int = 0,
        tokenizer_on_fallback: str = "warn",
    ):
        self.path = resolve_path(path)
        self.seq_len = seq_len
        tokenizer = get_tokenizer(tokenizer_name, on_fallback=tokenizer_on_fallback)

        arr: Optional[np.ndarray] = None
        if isinstance(tokenizer, ByteTokenizer):
            # Native one-pass strip/tokenize (tpu_trainer/native); falls
            # through to the Python loop when the library is unavailable or
            # the bytes need Python text semantics. With a token budget,
            # read only a bounded prefix (>= 1 byte/token plus slack); if
            # that prefix can't fill the budget the Python path decides.
            from tpu_trainer import native

            limit = None if max_tokens is None else 4 * max_tokens + 65536
            data = read_bytes(self.path, limit)
            arr = native.byte_tokenize(
                data, tokenizer.eos_token_id, max_tokens=max_tokens,
            )
            if (
                arr is not None
                and max_tokens is not None
                and arr.size < max_tokens
                and limit is not None
                and len(data) == limit  # possibly truncated read
            ):
                arr = None
        if arr is None:
            ids: List[int] = []
            eos = tokenizer.eos_token_id
            if num_workers > 0:
                # Up-front tokenization parallelized over lines (the
                # map-style analogue of streaming num_workers; HF fast
                # tokenizers release the GIL).
                from concurrent.futures import ThreadPoolExecutor

                with open_text(self.path) as f:
                    lines = [l.strip() for l in f if l.strip()]
                with ThreadPoolExecutor(max_workers=num_workers) as pool:
                    for toks in pool.map(tokenizer.encode, lines, chunksize=64):
                        ids.extend(toks)
                        ids.append(eos)
                        if max_tokens is not None and len(ids) >= max_tokens:
                            break
                if max_tokens is not None:
                    ids = ids[:max_tokens]
            else:
                with open_text(self.path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        ids.extend(tokenizer.encode(line))
                        ids.append(eos)
                        if max_tokens is not None and len(ids) >= max_tokens:
                            ids = ids[:max_tokens]
                            break
            arr = np.asarray(ids, dtype=np.int32)

        n_chunks = arr.size // seq_len
        if n_chunks == 0:
            raise ValueError(
                f"{path}: only {arr.size} tokens, need >= seq_len ({seq_len})"
            )
        self.chunks = arr[: n_chunks * seq_len].reshape(n_chunks, seq_len)

    def __len__(self) -> int:
        return self.chunks.shape[0]

    def __getitem__(self, i: int) -> np.ndarray:
        return self.chunks[i]


class ChunkSubset:
    """Contiguous index-range view over a map-style dataset's chunks — the
    held-out split mechanism (train = head, eval = tail; see
    ``create_text_dataloader(eval_split=...)``)."""

    def __init__(self, dataset, start: int, stop: int):
        if not (0 <= start <= stop <= len(dataset)):
            raise ValueError(f"bad subset [{start}, {stop}) of {len(dataset)}")
        self.dataset = dataset
        self.start = start
        self.stop = stop

    def __len__(self) -> int:
        return self.stop - self.start

    def __getitem__(self, i: int) -> np.ndarray:
        if not 0 <= i < len(self):
            raise IndexError(i)
        return self.dataset[self.start + i]


class StreamingTextDataset:
    """Iterable: line-modulo sharded streaming with a rolling token buffer
    (reference ``tinystories.py:53-119``, ``openwebtext.py:95-130``).

    Yields int32 ``[seq_len]`` chunks. Re-iterating starts a new pass over
    the file (the LRU cache persists across passes, which is when it pays —
    reference behavior, SURVEY.md §2.1 b10).
    """

    # Lines per tokenizer-pool submission; large enough to amortize thread
    # handoff, small enough to keep the pipeline responsive.
    _GROUP = 64

    def __init__(
        self,
        path: str,
        seq_len: int,
        tokenizer_name: str = "gpt2",
        max_tokens: Optional[int] = None,
        cache_max_tokens: Optional[int] = None,
        shard_id: int = 0,
        num_shards: int = 1,
        num_workers: int = 0,
        tokenizer_on_fallback: str = "warn",
        holdout=None,
        mask_doc_boundaries: bool = False,
    ):
        """``holdout=(role, N)`` carves an eval split out of the stream:
        every N-th line *of each host's shard* (``(line_idx // num_shards)
        % N == N - 1``) belongs to eval. ``role="train"`` skips those
        lines; ``role="eval"`` yields only them. Keying the filter on the
        within-shard position (not the raw index) keeps it decorrelated
        from host sharding — with ``line_idx % N`` a shared factor between
        N and the host count would give some hosts an empty stream (and a
        multihost run a collective deadlock)."""
        self.path = resolve_path(path)
        self.seq_len = seq_len
        self.tokenizer = get_tokenizer(
            tokenizer_name, on_fallback=tokenizer_on_fallback
        )
        self.max_tokens = max_tokens
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.num_workers = num_workers
        if holdout is not None:
            role, every = holdout
            if role not in ("train", "eval") or every < 2:
                raise ValueError(f"bad holdout {holdout!r}")
        self.holdout = holdout
        # Cross-document loss-leak fix: with the flag on, each yielded chunk
        # carries a segment channel ([seq_len, 2]: tokens, segment ids)
        # derived from the EOS positions inside the window, so attention is
        # isolated per document and the loss skips targets that would cross
        # a boundary. Default OFF for bit-compat with runs checkpointed on
        # the leaky stream (identical batches, identical loss curve).
        self.mask_doc_boundaries = mask_doc_boundaries
        self.cache = LRUTokenCache(cache_max_tokens)

    def _encode(self, line: str) -> List[int]:
        return self.tokenizer.encode(line) + [self.tokenizer.eos_token_id]

    def _sharded_lines(self, f) -> Iterator[tuple]:
        """(line_idx, stripped line) pairs belonging to this shard (and to
        this dataset's side of the train/eval holdout, if any)."""
        role, every = self.holdout if self.holdout else (None, 0)
        for line_idx, line in enumerate(f):
            if role is not None:
                is_eval_line = (
                    (line_idx // self.num_shards) % every == every - 1
                )
                if is_eval_line == (role == "train"):
                    continue
            if line_idx % self.num_shards != self.shard_id:
                continue
            line = line.strip()
            if line:
                yield line_idx, line

    def __iter__(self) -> Iterator[np.ndarray]:
        if not self.mask_doc_boundaries:
            yield from self._iter_tokens()
            return
        eos = self.tokenizer.eos_token_id
        for chunk in self._iter_tokens():
            # Document d's positions are those after the (d-1)-th EOS in the
            # window: seg = 1 + #EOS strictly before. The EOS itself closes
            # its document, so the boundary target (EOS -> next doc's first
            # token) gets seg[t+1] != seg[t] and is loss-masked
            # (ops/loss.segment_target_mask). A doc spanning two windows
            # restarts at seg 1 in the next window — consistent: the window
            # is the attention scope. No padding, so no seg-0 positions.
            segs = 1 + np.cumsum(
                np.concatenate([[0], (chunk[:-1] == eos).astype(np.int32)])
            )
            yield np.stack([chunk, segs.astype(np.int32)], axis=-1)

    def iter_documents(self) -> Iterator[List[int]]:
        """Per-line token lists (EOS appended) under the same shard/holdout/
        budget rules as the chunk stream — the document source the packing
        loader (``data/packing.py``) bins into full rows."""
        tokens_seen = 0
        with open_text(self.path) as f:
            for line_idx, line in self._sharded_lines(f):
                tokens = self.cache.get(line_idx)
                if tokens is None:
                    tokens = self._encode(line)
                    self.cache.put(line_idx, tokens)
                if self.max_tokens is not None:
                    remaining = self.max_tokens - tokens_seen
                    if remaining <= 0:
                        return
                    tokens = tokens[:remaining]
                tokens_seen += len(tokens)
                yield tokens

    def _iter_tokens(self) -> Iterator[np.ndarray]:
        if self.num_workers > 0:
            yield from self._iter_parallel()
            return
        buffer: List[int] = []
        tokens_seen = 0
        with open_text(self.path) as f:
            for line_idx, line in self._sharded_lines(f):
                tokens = self.cache.get(line_idx)
                if tokens is None:
                    tokens = self._encode(line)
                    self.cache.put(line_idx, tokens)
                # max_tokens budget (reference tinystories.py:103-108)
                if self.max_tokens is not None:
                    remaining = self.max_tokens - tokens_seen
                    if remaining <= 0:
                        return
                    tokens = tokens[:remaining]
                tokens_seen += len(tokens)
                buffer.extend(tokens)
                while len(buffer) >= self.seq_len:
                    yield np.asarray(buffer[: self.seq_len], dtype=np.int32)
                    buffer = buffer[self.seq_len :]

    def _iter_parallel(self) -> Iterator[np.ndarray]:
        """Same stream, with uncached lines tokenized by a thread pool in
        groups (the ``num_workers`` knob — reference ``tinystories.py:131``;
        HF fast tokenizers release the GIL, so threads parallelize for
        real). Chunk order, LRU caching, and the ``max_tokens`` budget are
        identical to the serial path.
        """
        from concurrent.futures import ThreadPoolExecutor

        buffer: List[int] = []
        tokens_seen = 0

        with open_text(self.path) as f, ThreadPoolExecutor(
            max_workers=self.num_workers
        ) as pool:
            group: List[tuple] = []  # (line_idx, line, cached | None)

            def resolved(group):
                uncached = [(i, l) for i, l, t in group if t is None]
                encoded = dict(
                    zip(
                        (i for i, _ in uncached),
                        pool.map(self._encode, (l for _, l in uncached)),
                    )
                )
                for i, _, t in group:
                    if t is None:
                        t = encoded[i]
                        self.cache.put(i, t)
                    yield t

            def emit(group):
                nonlocal buffer, tokens_seen
                for tokens in resolved(group):
                    if self.max_tokens is not None:
                        remaining = self.max_tokens - tokens_seen
                        if remaining <= 0:
                            return False
                        tokens = tokens[:remaining]
                    tokens_seen += len(tokens)
                    buffer.extend(tokens)
                    while len(buffer) >= self.seq_len:
                        yield np.asarray(
                            buffer[: self.seq_len], dtype=np.int32
                        )
                        buffer = buffer[self.seq_len :]
                return True

            for line_idx, line in self._sharded_lines(f):
                group.append((line_idx, line, self.cache.get(line_idx)))
                if len(group) >= self._GROUP:
                    done = yield from emit(group)
                    group = []
                    if done is False:
                        return
            if group:
                yield from emit(group)


class TextDataLoader:
    """Batches chunks into ``[rows_per_host, seq_len]`` int32 arrays.

    ``batch_size`` is the per-host row count (= micro_batch x grad_accum x
    local data shards — torch's per-rank DataLoader semantics,
    ``ddp_trainer.py:538``). Map-style epochs reshuffle with an epoch-seeded
    permutation and stride disjoint rows per host (C25 + b11 fix); streaming
    shards lines per host (C22).

    ``prefetch > 0`` assembles batches on a background thread, ``prefetch``
    batches ahead (``data/prefetch.py``) — the torch-DataLoader overlap the
    reference relies on: host tokenization/stacking runs while the device
    executes the current step.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        process_index: int = 0,
        process_count: int = 1,
        seed: int = 0,
        drop_last: bool = True,
        prefetch: int = 2,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.process_index = process_index
        self.process_count = process_count
        self.seed = seed
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.epoch = 0
        self.streaming = not hasattr(dataset, "__len__")
        # Consumer-side cursor for exact resume: which epoch is being
        # iterated and how many batches the *consumer* has pulled from it.
        # Counted here (not in the producer) because with prefetch the
        # background thread runs batches ahead of what training actually
        # consumed — a crash must resume at the consumed position.
        self._cur_epoch = 0
        self._cur_batch = 0
        self._resume_skip = 0

    def state_dict(self) -> dict:
        """Exact data-stream position, persisted into checkpoint meta.json.

        ``batch_index`` counts batches *consumed* in epoch ``epoch`` (the
        cursor advances before each yield, so a checkpoint taken after
        training on batch k records k+1). The shuffle RNG needs no separate
        state: the map-style permutation is a pure function of
        ``(seed, epoch)`` and the streaming line order is the file order.
        """
        return {
            "kind": "streaming" if self.streaming else "map",
            "epoch": self._cur_epoch,
            "batch_index": self._cur_batch,
            "seed": self.seed,
        }

    def load_state_dict(self, state: dict) -> None:
        """Position the next ``__iter__`` at the saved cursor.

        Map-style re-derives the epoch's permutation and jumps straight to
        the batch (index arithmetic, no re-tokenization); streaming
        fast-forwards by re-reading and discarding ``batch_index`` batches —
        exact, because the stream is a deterministic function of the file.
        """
        kind = state.get("kind", "map")
        here = "streaming" if self.streaming else "map"
        if kind != here:
            raise ValueError(
                f"data state kind {kind!r} does not match this {here!r} "
                f"loader — the resumed run changed --dataset/--streaming"
            )
        self.epoch = self._cur_epoch = int(state["epoch"])
        self._cur_batch = int(state["batch_index"])
        self._resume_skip = self._cur_batch

    def __iter__(self) -> Iterator[np.ndarray]:
        # Map-style epoch state advances HERE, on the consumer's thread, not
        # inside the (possibly background-threaded) generator: with prefetch
        # a consumer breaking early would otherwise leave "did the epoch
        # advance?" up to producer-thread timing. Each __iter__ is one epoch.
        epoch = self.epoch
        if not self.streaming:
            self.epoch += 1
        start = self._resume_skip
        self._resume_skip = 0
        self._cur_epoch = epoch
        self._cur_batch = start
        make = functools.partial(self._iter_batches, epoch, start)
        if self.prefetch > 0:
            from tpu_trainer.data.prefetch import Prefetcher

            it = iter(Prefetcher(make, self.prefetch))
        else:
            it = make()
        for batch in it:
            self._cur_batch += 1
            yield batch
        self._cur_epoch = epoch + 1
        self._cur_batch = 0

    def _iter_batches(self, epoch: int, start: int = 0) -> Iterator[np.ndarray]:
        if self.streaming:
            rows = []
            skipped = 0
            for chunk in self.dataset:
                rows.append(chunk)
                if len(rows) == self.batch_size:
                    if skipped < start:
                        skipped += 1  # resume fast-forward: discard
                    else:
                        yield np.stack(rows)
                    rows = []
            if rows and not self.drop_last and skipped >= start:
                yield np.stack(rows)
        else:
            n = len(self.dataset)
            rng = np.random.default_rng((self.seed, epoch))
            order = rng.permutation(n)
            # Disjoint per-host strides; drop the ragged tail so every host
            # sees the same number of full batches (drop_last=True,
            # reference tinystories.py:158).
            stride = self.process_count * self.batch_size
            order = order[: (n // stride) * stride]
            local = order[self.process_index :: self.process_count]
            n_batches = len(local) // self.batch_size
            for b in range(start, n_batches):
                idx = local[b * self.batch_size : (b + 1) * self.batch_size]
                yield np.stack([self.dataset[i] for i in idx])

    def __len__(self) -> int:
        if self.streaming:
            raise TypeError("streaming loader has no length")
        stride = self.process_count * self.batch_size
        return len(self.dataset) // stride


def create_text_dataloader(
    path: str,
    batch_size: int,
    seq_len: int,
    *,
    tokenizer_name: str = "gpt2",
    max_tokens: Optional[int] = None,
    streaming: bool = False,
    cache_max_tokens: Optional[int] = None,
    process_index: int = 0,
    process_count: int = 1,
    seed: int = 0,
    num_workers: int = 0,
    prefetch: int = 2,
    tokenizer_on_fallback: str = "warn",
    eval_split: float = 0.0,
    eval_holdout_every: int = 0,
    mask_doc_boundaries: bool = False,
) -> TextDataLoader:
    """Factory shared by the dataset-specific wrappers (reference factory
    signatures: ``tinystories.py:122-134``, ``openwebtext.py:133-145``).
    ``num_workers`` parallelizes tokenization (streaming and map-style);
    ``prefetch`` overlaps batch assembly with device steps (0 disables).
    ``tokenizer_on_fallback="error"`` is the training guardrail: no silent
    byte-level fallback (utils/tokenizer.py).

    Held-out eval (the loop the reference's dead ``eval_interval`` promised,
    ``ddp_trainer.py:52``): ``eval_split > 0`` (map-style) carves the last
    ``eval_split`` fraction of chunks; ``eval_holdout_every = N > 0``
    (streaming) reserves every N-th line. Either attaches an ``eval_loader``
    (batching over the held-out rows only, prefetch off) to the returned
    train loader; train and eval rows are disjoint by construction. The
    attribute is None when no split is requested.
    """
    eval_loader = None
    if streaming:
        holdout = ("train", eval_holdout_every) if eval_holdout_every else None
        common = dict(
            tokenizer_name=tokenizer_name,
            max_tokens=max_tokens,
            cache_max_tokens=cache_max_tokens,
            shard_id=process_index,
            num_shards=process_count,
            tokenizer_on_fallback=tokenizer_on_fallback,
        )
        dataset = StreamingTextDataset(
            path, seq_len, num_workers=num_workers, holdout=holdout,
            mask_doc_boundaries=mask_doc_boundaries, **common
        )
        if eval_holdout_every:
            eval_ds = StreamingTextDataset(
                path, seq_len, holdout=("eval", eval_holdout_every), **common
            )
            eval_loader = TextDataLoader(
                eval_ds, batch_size,
                process_index=process_index, process_count=process_count,
                seed=seed, prefetch=0,
            )
    else:
        full = TextDataset(
            path, seq_len, tokenizer_name=tokenizer_name,
            max_tokens=max_tokens, num_workers=num_workers,
            tokenizer_on_fallback=tokenizer_on_fallback,
        )
        dataset = full
        if eval_split > 0.0:
            n = len(full)
            n_eval = max(1, int(n * eval_split))
            if n - n_eval < 1:
                # Too small to split (eval_split defaults on): degrade to
                # no-eval with a warning rather than refusing a tiny corpus
                # that would previously train.
                import warnings

                warnings.warn(
                    f"{path}: {n} chunk(s) cannot hold out eval_split="
                    f"{eval_split} and still train; continuing without an "
                    f"eval split"
                )
            else:
                dataset = ChunkSubset(full, 0, n - n_eval)
                eval_loader = TextDataLoader(
                    ChunkSubset(full, n - n_eval, n), batch_size,
                    process_index=process_index, process_count=process_count,
                    seed=seed, prefetch=0,
                )
    loader = TextDataLoader(
        dataset,
        batch_size,
        process_index=process_index,
        process_count=process_count,
        seed=seed,
        prefetch=prefetch,
    )
    loader.eval_loader = eval_loader
    return loader
