"""Sharded device prefetch: keep the next batches already on the chips.

``data/prefetch.py`` overlaps *host* work (tokenization, batch assembly)
with device compute, but the host→device transfer itself still happened
synchronously inside the step (``Trainer.place_batch`` →
``jax.make_array_from_process_local_data``). On TPU that H2D copy is DMA
the device could hide under the previous step's compute — but only if the
transfer is *enqueued* before the step needs it. ``DevicePrefetcher`` pulls
``depth`` batches ahead of the trainer and places each with the batch
sharding immediately; jax's async dispatch returns as soon as the copy is
enqueued, so by the time the trainer asks for batch N it is (or is about
to be) resident, and the goodput ledger's ``data_wait`` drops to ~0.

No thread lives here: placement is async already, and a thread would buy
nothing but reordering hazards. Layering for a streaming run::

    TextDataLoader -> Prefetcher (host thread) -> DevicePrefetcher -> step

Cursor contract (what makes resume/rollback stay bit-exact): the wrapped
loader's ``state_dict()`` cursor advances when a batch leaves the *loader*
— which, with a prefetch buffer, is up to ``depth`` batches ahead of what
the trainer actually consumed. Checkpointing that would over-advance the
cursor and a resumed run would silently skip the buffered batches. So this
class snapshots the loader cursor at each pull and republishes, via its own
``state_dict()``, the snapshot belonging to the batch most recently handed
to the trainer. The checkpoint/rollback paths read the feed's cursor, never
the raw loader's, and "consumed" keeps meaning "consumed by the trainer".
"""

from __future__ import annotations

import collections
from typing import Callable, Optional


class DevicePrefetcher:
    """Pull batches from ``next_fn``, place them on device ahead of use.

    - ``next_fn``: returns the next host batch (may block on the host
      pipeline; ``StopIteration`` ends the stream).
    - ``place``: host batch → sharded device array, enqueued async
      (``Trainer.place_batch``).
    - ``cursor_fn``: the wrapped loader's ``state_dict`` (optional); see
      the module docstring for the republishing contract.
    - ``depth``: batches kept placed ahead of the trainer; ``0`` degrades
      to synchronous place-on-demand (identical to the pre-prefetch loop).
    """

    def __init__(
        self,
        next_fn: Callable[[], object],
        *,
        place: Callable[[object], object],
        cursor_fn: Optional[Callable[[], dict]] = None,
        depth: int = 2,
    ):
        if depth < 0:
            raise ValueError(f"device prefetch depth must be >= 0, got {depth}")
        self._next_fn = next_fn
        self._place = place
        self._cursor_fn = cursor_fn
        self.depth = depth
        self._buf: collections.deque = collections.deque()
        self._exhausted = False
        # Until the trainer consumes a batch, the feed's cursor is the
        # loader's cursor from before anything was pulled.
        self._cursor = cursor_fn() if cursor_fn is not None else None

    def _pull(self) -> bool:
        try:
            batch = self._next_fn()
        except StopIteration:
            self._exhausted = True
            return False
        # Cursor first, then place: the snapshot must describe "this batch
        # consumed", and place() only enqueues a copy anyway.
        cur = self._cursor_fn() if self._cursor_fn is not None else None
        self._buf.append((self._place(batch), cur))
        return True

    def _fill(self) -> None:
        while not self._exhausted and len(self._buf) < max(self.depth, 1):
            self._pull()

    def next(self):
        """The next device-resident batch; advances the published cursor to
        this batch's snapshot. Raises ``StopIteration`` when the stream is
        exhausted and the buffer is drained."""
        if not self._buf:
            self._fill()
        if not self._buf:
            raise StopIteration
        batch, cur = self._buf.popleft()
        self._cursor = cur
        # Top back up now so the next H2D copies run under this step's
        # compute, not in its data_wait.
        self._fill()
        return batch

    def state_dict(self) -> Optional[dict]:
        """Loader cursor of the last batch the *trainer* consumed — buffered
        batches are excluded, so a checkpoint taken now resumes by replaying
        exactly the batches still in flight."""
        return self._cursor

    def buffered(self) -> int:
        return len(self._buf)

    def reset(self) -> None:
        """Drop the buffer and re-base the cursor on the (re-wound) loader —
        call after ``load_state_dict`` on the wrapped loader, e.g. rollback."""
        self._buf.clear()
        self._exhausted = False
        self._cursor = self._cursor_fn() if self._cursor_fn is not None else None
