"""Sequence packing: first-fit binning of ragged documents into full rows.

The corpora are ragged (TinyStories ~200-token stories, OpenWebText
documents) while the trainer consumes fixed ``[batch, seq_len]`` rows. The
two pre-existing strategies both waste something: padding each document to
``seq_len`` burns compute on pad positions, and concatenating the stream
attends (and computes loss) across document boundaries. Packing keeps full
rows AND document isolation: several documents share one row, a per-position
``segment_ids`` channel marks which (0 = padding, documents 1..K), the flash
kernels skip/mask cross-segment blocks (``ops/flash.py``) and the loss masks
targets that would cross a boundary (``ops/loss.segment_target_mask``).

Packed batches travel channel-last: int32 ``[rows, seq_len, 2]`` with
``[..., 0]`` tokens and ``[..., 1]`` segment ids — the shape contract
``Trainer.place_batch`` recognizes (a trailing dim of 2; a real seq dim is
never 2).

Packing efficiency: with mean document length m and first-fit into bins of
size S, the expected non-pad fraction approaches 1 - O(m/S) (the only waste
is the per-bin tail smaller than the shortest open document), versus m/S for
pad-to-seq — the ratio S/m is the effective-throughput headroom bench.py's
``--packed`` lane measures.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np


def synthetic_documents(
    num_docs: int,
    mean_len: int,
    vocab_size: int,
    seed: int = 0,
    min_len: int = 1,
) -> Iterator[List[int]]:
    """Deterministic ragged corpus: doc lengths ~ geometric around
    ``mean_len`` (clipped at ``min_len``), tokens uniform over the vocab.
    The bench's stand-in for a real ragged dataset."""
    rng = np.random.default_rng(seed)
    for _ in range(num_docs):
        n = max(min_len, int(rng.geometric(1.0 / max(mean_len, 1))))
        yield rng.integers(0, vocab_size, n).astype(np.int32).tolist()


def _split_long(doc: List[int], seq_len: int) -> Iterator[List[int]]:
    """Documents longer than a row split at row boundaries; each piece packs
    as its own document (pieces separated into different rows could not
    attend to each other anyway)."""
    for i in range(0, len(doc), seq_len):
        yield doc[i : i + seq_len]


def pack_documents(
    docs: Iterable[List[int]],
    seq_len: int,
    max_open_bins: int = 8,
    strategy: str = "first_fit",
    lookahead: int = 64,
) -> Iterator[np.ndarray]:
    """Bin packing → int32 ``[seq_len, 2]`` rows (tokens, segment ids).

    ``strategy="first_fit"``: each document goes into the first open bin
    with room, in stream order. ``strategy="best_fit"``: best-fit-
    decreasing over a ``lookahead``-piece window — repeatedly place the
    LONGEST buffered piece into the bin with the TIGHTEST remaining space
    that fits (length-aware bin selection; the window is what makes
    "decreasing" possible on a stream), and when a bin must flush to keep
    memory bounded, top it off with the largest windowed pieces that
    still fit its tail. BFD trades a small reorder buffer for fewer
    stranded bin tails, squeezing the last few non-pad points —
    ``bench.py --packed`` carries an A/B row of both.

    Either way a full bin is emitted immediately, and when more than
    ``max_open_bins`` bins are open the oldest is flushed (bounded memory,
    deterministic order — both strategies are pure functions of the
    stream, so resume replays the exact same rows). Pad positions carry
    token 0 and segment 0.
    """
    if strategy not in ("first_fit", "best_fit"):
        raise ValueError(
            f"unknown packing strategy {strategy!r}; "
            f"choose first_fit or best_fit")
    bins: List[Tuple[List[int], List[int], int]] = []  # (tokens, segs, next_id)

    def finish(tokens: List[int], segs: List[int]) -> np.ndarray:
        pad = seq_len - len(tokens)
        row = np.zeros((seq_len, 2), dtype=np.int32)
        row[: len(tokens), 0] = tokens
        row[: len(segs), 1] = segs
        assert pad >= 0
        return row

    pieces = (
        piece for doc in docs
        for piece in _split_long(list(doc), seq_len) if piece
    )
    if strategy == "first_fit":
        for piece in pieces:
            placed = False
            for j, (toks, segs, nxt) in enumerate(bins):
                if seq_len - len(toks) >= len(piece):
                    toks.extend(piece)
                    segs.extend([nxt] * len(piece))
                    if len(toks) == seq_len:
                        yield finish(toks, segs)
                        bins.pop(j)
                    else:
                        bins[j] = (toks, segs, nxt + 1)
                    placed = True
                    break
            if not placed:
                if len(piece) == seq_len:
                    yield finish(piece, [1] * seq_len)
                else:
                    bins.append((list(piece), [1] * len(piece), 2))
                    if len(bins) > max_open_bins:
                        toks, segs, _ = bins.pop(0)
                        yield finish(toks, segs)
    else:
        window: List[List[int]] = []
        it = iter(pieces)
        exhausted = False

        def refill() -> None:
            nonlocal exhausted
            while not exhausted and len(window) < max(1, lookahead):
                try:
                    window.append(next(it))
                except StopIteration:
                    exhausted = True

        def pick(limit: int) -> Optional[List[int]]:
            """Largest windowed piece of length <= limit (ties: oldest)."""
            cands = [i for i in range(len(window))
                     if len(window[i]) <= limit]
            if not cands:
                return None
            j = max(cands, key=lambda i: (len(window[i]), -i))
            return window.pop(j)

        while True:
            refill()
            piece = pick(seq_len)
            if piece is None:
                break
            best, best_rem = None, None
            for j, (toks, _, _) in enumerate(bins):
                rem = seq_len - len(toks)
                if rem >= len(piece) and (best_rem is None or rem < best_rem):
                    best, best_rem = j, rem
            if best is not None:
                toks, segs, nxt = bins[best]
                toks.extend(piece)
                segs.extend([nxt] * len(piece))
                if len(toks) == seq_len:
                    yield finish(toks, segs)
                    bins.pop(best)
                else:
                    bins[best] = (toks, segs, nxt + 1)
                continue
            if len(piece) == seq_len:
                yield finish(piece, [1] * seq_len)
                continue
            bins.append((list(piece), [1] * len(piece), 2))
            while len(bins) > max_open_bins:
                toks, segs, nxt = bins.pop(0)
                # Top off the flushing bin from the window — the
                # length-aware move that earns BFD its tighter tails
                # (without it, a run of long pieces exhausts the open
                # bins and flushes them with their tails stranded).
                while True:
                    refill()
                    extra = pick(seq_len - len(toks))
                    if extra is None:
                        break
                    toks.extend(extra)
                    segs.extend([nxt] * len(extra))
                    nxt += 1
                yield finish(toks, segs)
    for toks, segs, _ in bins:
        yield finish(toks, segs)


def pad_documents(
    docs: Iterable[List[int]], seq_len: int
) -> Iterator[np.ndarray]:
    """One document per row, padded to ``seq_len`` — the baseline packing
    replaces. Same ``[seq_len, 2]`` row format (doc = segment 1, pad = 0) so
    both lanes of ``bench.py --packed`` run the identical trainer path."""
    for doc in docs:
        for piece in _split_long(list(doc), seq_len):
            if not piece:
                continue
            row = np.zeros((seq_len, 2), dtype=np.int32)
            row[: len(piece), 0] = piece
            row[: len(piece), 1] = 1
            yield row


class PackedDataLoader:
    """Batches packed rows into ``[batch_size, seq_len, 2]`` int32 arrays.

    ``doc_fn`` is a re-invocable factory returning a fresh document iterator
    (one pass = one epoch); packing is a deterministic function of that
    stream, so the cursor protocol is the streaming one: ``state_dict``
    records batches consumed and resume fast-forwards by re-packing and
    discarding (``TextDataLoader`` twin). ``pack=False`` switches to the
    pad-to-seq baseline with the same batch format.

    Tracks padding waste: ``non_pad_frac`` is the cumulative non-pad token
    fraction over everything yielded (the goodput ledger / MetricLogger
    input), ``last_non_pad_frac`` the most recent batch's.
    """

    def __init__(
        self,
        doc_fn: Callable[[], Iterable[List[int]]],
        batch_size: int,
        seq_len: int,
        *,
        max_open_bins: int = 8,
        pack: bool = True,
        strategy: str = "first_fit",
        lookahead: int = 64,
        seed: int = 0,
        drop_last: bool = True,
        num_batches: Optional[int] = None,
    ):
        self.doc_fn = doc_fn
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.max_open_bins = max_open_bins
        self.pack = pack
        self.strategy = strategy
        self.lookahead = lookahead
        self.seed = seed
        self.drop_last = drop_last
        self.num_batches = num_batches
        self._cur_epoch = 0
        self._cur_batch = 0
        self._resume_skip = 0
        self._tokens = 0
        self._nonpad = 0
        self.last_non_pad_frac = 1.0

    @property
    def non_pad_frac(self) -> float:
        return 1.0 if self._tokens == 0 else self._nonpad / self._tokens

    def state_dict(self) -> dict:
        return {
            "kind": "packed",
            "epoch": self._cur_epoch,
            "batch_index": self._cur_batch,
            "seed": self.seed,
        }

    def load_state_dict(self, state: dict) -> None:
        if state.get("kind") != "packed":
            raise ValueError(
                f"data state kind {state.get('kind')!r} does not match this "
                f"'packed' loader — the resumed run changed the data config"
            )
        self._cur_epoch = int(state["epoch"])
        self._cur_batch = int(state["batch_index"])
        self._resume_skip = self._cur_batch

    def _rows(self) -> Iterator[np.ndarray]:
        if self.pack:
            return pack_documents(
                self.doc_fn(), self.seq_len, self.max_open_bins,
                strategy=self.strategy, lookahead=self.lookahead,
            )
        return pad_documents(self.doc_fn(), self.seq_len)

    def __iter__(self) -> Iterator[np.ndarray]:
        start = self._resume_skip
        self._resume_skip = 0
        self._cur_batch = start
        rows: List[np.ndarray] = []
        emitted = 0
        skipped = 0
        for row in self._rows():
            rows.append(row)
            if len(rows) < self.batch_size:
                continue
            batch, rows = np.stack(rows), []
            if skipped < start:
                skipped += 1
                continue
            if self.num_batches is not None and emitted >= self.num_batches:
                return
            yield self._account(batch)
            emitted += 1
        if (rows and not self.drop_last and skipped >= start
                and (self.num_batches is None or emitted < self.num_batches)):
            yield self._account(np.stack(rows))
        self._cur_epoch += 1
        self._cur_batch = 0

    def _account(self, batch: np.ndarray) -> np.ndarray:
        nonpad = int((batch[..., 1] != 0).sum())
        total = int(batch[..., 1].size)
        self._nonpad += nonpad
        self._tokens += total
        self.last_non_pad_frac = nonpad / total if total else 1.0
        self._cur_batch += 1
        return batch
