"""OpenWebText dataset (SURVEY.md C21).

Thin wrapper over the shared text engine with the reference factory's
signature (``/root/reference/src/data/openwebtext.py:133-145``). The
OpenWebText-specific behaviors — gzip transparency
(``openwebtext.py:32-37,71-74``) and the ``.gz``↔plain path fallback
(``openwebtext.py:147-155``) — live in the shared engine
(``text.open_text`` / ``text.resolve_path``) and apply automatically.
"""

from __future__ import annotations

from typing import Optional

from tpu_trainer.data.text import TextDataLoader, create_text_dataloader


def create_openwebtext_dataloader(
    path: str,
    batch_size: int,
    seq_len: int,
    *,
    tokenizer_name: str = "gpt2",
    max_tokens: Optional[int] = None,
    streaming: bool = False,
    cache_max_tokens: Optional[int] = None,
    process_index: int = 0,
    process_count: int = 1,
    seed: int = 0,
    num_workers: int = 0,
    prefetch: int = 2,
    tokenizer_on_fallback: str = "warn",
    eval_split: float = 0.0,
    eval_holdout_every: int = 0,
    mask_doc_boundaries: bool = False,
) -> TextDataLoader:
    """Reference-parity factory (``openwebtext.py:133-181``): ``batch_size``
    is rows per host; yields ``[batch_size, seq_len]`` int32 batches."""
    return create_text_dataloader(
        path,
        batch_size,
        seq_len,
        tokenizer_name=tokenizer_name,
        max_tokens=max_tokens,
        streaming=streaming,
        cache_max_tokens=cache_max_tokens,
        process_index=process_index,
        process_count=process_count,
        seed=seed,
        num_workers=num_workers,
        prefetch=prefetch,
        tokenizer_on_fallback=tokenizer_on_fallback,
        eval_split=eval_split,
        eval_holdout_every=eval_holdout_every,
        mask_doc_boundaries=mask_doc_boundaries,
    )
