"""Bounded background prefetch over an iterator.

The reference gets input/compute overlap for free from torch's
``DataLoader(num_workers=4)`` worker processes + prefetching
(``/root/reference/src/data/tinystories.py:131,153-161``). The TPU-native
loaders are plain host-side generators, so without this a streaming text run
serializes host tokenization/batch assembly with device steps — the chip
idles while the host reads lines. ``Prefetcher`` runs the inner iterator on
a daemon thread into a bounded queue (double-buffering by default): the
device consumes batch N while the host builds batch N+1.

Threads (not processes) suffice here: the heavy per-item work — HF fast
tokenizers (Rust) and the native byte-tokenize kernel — releases the GIL,
and device dispatch overlaps regardless.

This layer is host-side only; the host→device copy is overlapped one layer
up by ``data/device_prefetch.py``, which places the next batches with the
batch sharding while the current step computes. Full streaming stack::

    TextDataLoader -> Prefetcher (this, host) -> DevicePrefetcher -> step
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator


class _ProducerError:
    """In-band carrier for a producer-thread exception: queued *after* the
    batches produced before the failure, so the consumer sees every good
    batch and then the error — never a silently-shortened epoch (which a
    resume/rollback loop would misread as dataset exhaustion)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class Prefetcher:
    """Iterate ``make_iter()`` on a background thread, ``depth`` items ahead.

    - Exceptions in the producer re-raise in the consumer with the
      producer's original traceback (the frames below ``__iter__`` are the
      producer's), after all batches produced before the failure.
    - Early termination (consumer breaks / generator closed) signals the
      producer to stop; the thread is a daemon either way.
    - Each ``__iter__`` starts a fresh producer (epoch semantics match the
      wrapped loader's).
    """

    _SENTINEL = object()

    def __init__(self, make_iter: Callable[[], Iterable], depth: int = 2):
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self._make_iter = make_iter
        self._depth = depth

    def __iter__(self) -> Iterator:
        if self._depth == 0:
            # Passthrough: no thread, no buffer — lets call sites treat the
            # depth as a plain knob (0 = synchronous) instead of branching.
            yield from self._make_iter()
            return
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in self._make_iter():
                    if not _put(item):
                        return
                _put(self._SENTINEL)
            except BaseException as e:  # delivered in-band, re-raised below
                _put(_ProducerError(e))

        thread = threading.Thread(
            target=produce, daemon=True, name="tpu-trainer-prefetch"
        )
        thread.start()
        try:
            while True:
                item = q.get()
                if item is self._SENTINEL:
                    return
                if isinstance(item, _ProducerError):
                    # Same exception object: its __traceback__ still points
                    # into the producer's frames, so the re-raise reads like
                    # the failure happened inline.
                    raise item.exc.with_traceback(item.exc.__traceback__)
                yield item
        finally:
            stop.set()
