"""Mesh auto-planner: enumerate, score, and pick the parallelism split.

Users shouldn't hand-pick ``data x fsdp x sequence x tensor x expert x
stage`` for every model/pod/HBM combination (ROADMAP item 2). Alpa
(Zheng et al., OSDI '22) and FlexFlow (Jia et al., MLSys '19) showed an
analytic cost model searched over a constrained plan space matches
hand-tuned parallelization; the analytic half already exists here
(``parallel/comms_model.py`` per-axis collective bytes + ICI roofline).
This module is the search half:

1. **enumerate**: every ordered factorization of the device count over
   the six mesh axes (the divisor lattice);
2. **prune**: divisibility feasibility (:func:`feasibility_error` — the
   same predicate the CLI uses for early mesh validation, so CLI errors
   and planner pruning can never disagree) and a per-device HBM budget
   from an analytic params + optimizer + gradient + activation memory
   estimate (:func:`estimate_memory`);
3. **score**: ``comms_model.build_core`` bytes -> ICI roofline seconds,
   plus the 6N-FLOPs compute estimate, summed serially (the comms model's
   stated no-overlap assumption) into a predicted step time;
4. **rank**: argmin predicted step time, deterministic tiebreak on the
   axis tuple; emit the ``kind:"mesh_plan"`` record with top-k
   alternatives for ``--mesh auto`` and ``tools/plan``.

The search holds the GLOBAL batch fixed (``global_rows`` rows per
micro-step) and derives each candidate's per-shard batch as
``global_rows // (data*fsdp)`` — otherwise a tensor-heavy mesh would
"win" simply by doing less work per step than a data-parallel one.

Everything is pure shape arithmetic on an abstract param tree: nothing
compiles, no mesh is materialized, and plans for a different device kind
(``--hbm_gb`` + ``--device-kind``) cost the same as plans for this host.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_trainer.parallel import comms_model as comms_lib
from tpu_trainer.parallel import mesh as mesh_lib
from tpu_trainer.parallel import sharding as shard_lib
from tpu_trainer.utils.logging import SCHEMA_VERSION, peak_flops_for_kind

GiB = float(2**30)


# --- feasibility (shared with CLI mesh validation) --------------------------

def feasibility_error(
    axis_sizes,
    model_config,
    *,
    n_devices: int,
    global_rows: int,
    max_seq_len: int,
) -> Optional[str]:
    """Why this mesh can't run this model, or ``None`` if it can.

    Mirrors every divisibility check ``Trainer.__init__`` enforces (plus
    the planner's fixed-global-batch row split), so a mesh this predicate
    accepts constructs a Trainer and one it rejects fails there with the
    same arithmetic. The CLI calls it at parse/startup time for explicit
    ``--mesh_*`` splits; the enumerator uses it to prune — one predicate,
    so the two can never disagree.
    """
    d = axis_sizes.get(mesh_lib.DATA_AXIS, 1)
    f = axis_sizes.get(mesh_lib.FSDP_AXIS, 1)
    sp = axis_sizes.get(mesh_lib.SEQUENCE_AXIS, 1)
    tp = axis_sizes.get(mesh_lib.TENSOR_AXIS, 1)
    ep = axis_sizes.get(mesh_lib.EXPERT_AXIS, 1)
    st = axis_sizes.get(mesh_lib.STAGE_AXIS, 1)
    sizes = (d, f, sp, tp, ep, st)
    if any(s < 1 for s in sizes):
        return f"mesh axes must be >= 1, got {sizes}"
    product = int(np.prod(sizes))
    if product != n_devices:
        return (f"mesh {sizes} uses {product} devices but {n_devices} "
                f"are available")
    if sp > 1 and max_seq_len % sp != 0:
        return (f"max_seq_len {max_seq_len} not divisible by sequence "
                f"axis size {sp}")
    if ep > 1:
        if model_config.num_experts <= 0:
            return ("expert mesh axis > 1 requires a MoE model "
                    "(GPTConfig.num_experts > 0)")
        if model_config.num_experts % ep != 0:
            return (f"num_experts {model_config.num_experts} not divisible "
                    f"by expert axis size {ep}")
    if tp > 1:
        if model_config.num_heads % tp != 0:
            return (f"num_heads {model_config.num_heads} not divisible by "
                    f"tensor axis size {tp}")
        if model_config.kv_heads % tp != 0:
            return (f"num_kv_heads {model_config.kv_heads} not divisible by "
                    f"tensor axis size {tp} (each tensor shard must own "
                    f"whole K/V-head groups)")
    dp = d * f
    if global_rows % dp != 0:
        return (f"global batch of {global_rows} rows not divisible by "
                f"{dp} data shards (data {d} x fsdp {f})")
    if st > 1:
        if model_config.num_layers % st != 0:
            return (f"num_layers {model_config.num_layers} not divisible by "
                    f"stage axis size {st}")
        microbatches = model_config.pipeline_microbatches or st
        if model_config.pipeline_schedule == "interleaved":
            vst = model_config.pipeline_virtual_stages
            if model_config.num_layers % (st * vst):
                return (f"num_layers {model_config.num_layers} not divisible "
                        f"by stages*virtual ({st}*{vst})")
            if microbatches % st:
                return (f"interleaved schedule needs pipeline_microbatches "
                        f"({microbatches}) divisible by the stage count "
                        f"({st})")
        if global_rows % microbatches != 0:
            return (f"global batch {global_rows} rows not divisible by "
                    f"pipeline_microbatches {microbatches}")
    return None


def validate_mesh_config(
    mesh_config: mesh_lib.MeshConfig,
    model_config,
    *,
    n_devices: int,
    global_rows: int,
    max_seq_len: int,
) -> Dict[str, int]:
    """Resolve + feasibility-check an explicit MeshConfig; raise ValueError
    with an actionable message on any split the Trainer would reject.

    The CLI's early mesh validation: the same arithmetic errors the Trainer
    raises mid-startup surface at parse time instead, with a pointer to
    ``--mesh auto``. Returns the resolved ``{axis: size}`` dict.
    """
    resolved = mesh_config.resolve(n_devices)  # raises on bad product
    sizes = dict(zip(mesh_lib.MESH_AXES, resolved))
    err = feasibility_error(
        sizes, model_config, n_devices=n_devices,
        global_rows=global_rows, max_seq_len=max_seq_len)
    if err:
        raise ValueError(
            f"infeasible mesh {tuple(resolved)} "
            f"({'x'.join(mesh_lib.MESH_AXES)}): {err} — pick a split whose "
            f"axes divide the model, or let `--mesh auto` choose one")
    return sizes


# --- enumeration ------------------------------------------------------------

def enumerate_meshes(n_devices: int) -> Iterator[Dict[str, int]]:
    """Every ordered factorization of ``n_devices`` over the six mesh axes.

    The full divisor lattice, deterministically ordered (each axis walks
    its divisors ascending, data-axis outermost). For n = 2^k this is
    C(k+5, 5) candidates — 56 at n=8, 462 at n=64 — cheap enough that no
    search heuristics are needed below pod scale.
    """
    def factorize(remaining: int, n_axes: int) -> Iterator[Tuple[int, ...]]:
        if n_axes == 1:
            yield (remaining,)
            return
        for div in range(1, remaining + 1):
            if remaining % div == 0:
                for rest in factorize(remaining // div, n_axes - 1):
                    yield (div,) + rest

    for sizes in factorize(n_devices, len(mesh_lib.MESH_AXES)):
        yield dict(zip(mesh_lib.MESH_AXES, sizes))


# --- per-device memory estimate ---------------------------------------------

def estimate_memory(
    param_shapes,
    axis_sizes,
    strategy: str,
    *,
    model_config,
    batch_size: int,
    max_seq_len: int,
    opt_state_bytes: int = 4,
    carry_cast: bool = True,
) -> Dict[str, float]:
    """Analytic per-device peak-HBM estimate (bytes) for one candidate mesh.

    Exact for the persistent state — every param/grad/optimizer leaf is
    divided by its PartitionSpec's shard factor, the same specs the trainer
    will install — and approximate for activations (flash attention keeps
    the S^2 matrix out of HBM, so the dominant saved-for-backward terms are
    the per-layer residual/MLP streams):

    - master params: f32 / params spec
    - compute-dtype param copy (``carry_cast_params``): only when compute
      dtype is narrower than f32
    - Adam mu+nu: ``opt_state_bytes`` each / grads spec (the optimizer
      moments shard like grads under zero2/zero3)
    - grads: f32 / grads spec (persists across the accumulation loop)
    - activations per micro-batch:
      ``rows * seq_local * layers_local * (4*hidden + 2*inter_local)``
      in compute dtype, plus a 4x-hidden embed/head working set; the MoE
      FFN term scales by ``top_k * capacity_factor`` (capacity routing)
      or by ``top_k`` alone (dropless routing — no slot padding).

    Cross-check the winner against the XLA ``memory_analysis`` numbers in
    the ``cost_analysis`` record — this estimate is for *pruning*
    infeasible plans, not for capacity planning to the last megabyte.
    """
    strategy = shard_lib.canonical_strategy(strategy)
    mc = model_config
    sizes = {ax: axis_sizes.get(ax, 1) for ax in mesh_lib.MESH_AXES}
    sp = sizes[mesh_lib.SEQUENCE_AXIS]
    tp = sizes[mesh_lib.TENSOR_AXIS]
    st = sizes[mesh_lib.STAGE_AXIS]
    act_bytes = jnp.dtype(mc.compute_dtype).itemsize

    p_specs = shard_lib.params_specs_from_sizes(param_shapes, sizes, strategy)
    g_specs = shard_lib.grads_specs_from_sizes(param_shapes, sizes, strategy)

    mem = {"params": 0.0, "opt": 0.0, "grads": 0.0}

    def per_leaf(leaf, pspec, gspec):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        p_shard = size / comms_lib._shard_factor(pspec, sizes)
        g_shard = size / comms_lib._shard_factor(gspec, sizes)
        mem["params"] += p_shard * 4
        if carry_cast and act_bytes < 4:
            mem["params"] += p_shard * act_bytes
        mem["opt"] += 2.0 * g_shard * opt_state_bytes
        mem["grads"] += g_shard * 4

    jax.tree_util.tree_map(per_leaf, param_shapes, p_specs, g_specs)

    seq_local = max_seq_len // sp
    layers_local = mc.num_layers // st if st > 1 else mc.num_layers
    inter_local = (mc.intermediate_size // tp
                   if mc.intermediate_size % tp == 0 else mc.intermediate_size)
    if mc.num_experts > 0:
        # Capacity routing materialises the padded E*C slot buffer;
        # dropless holds exactly the k*T routed rows.
        mlp_scale = (mc.moe_top_k if mc.moe_impl == "dropless"
                     else mc.moe_top_k * mc.expert_capacity_factor)
    else:
        mlp_scale = 1.0
    per_token = 4 * mc.hidden_size + 2 * inter_local * mlp_scale
    activations = act_bytes * batch_size * seq_local * (
        layers_local * per_token + 4 * mc.hidden_size)
    mem["activations"] = activations
    mem["total"] = sum(mem.values())
    return mem


def hbm_budget_bytes(hbm_gb: Optional[float] = None) -> Optional[float]:
    """Per-device HBM budget in bytes: explicit ``--hbm_gb`` override, else
    the local device's ``memory_stats()['bytes_limit']``, else ``None``
    (no budget — CPU hosts planning for themselves don't prune on HBM)."""
    if hbm_gb is not None:
        return float(hbm_gb) * GiB
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return None
    limit = stats.get("bytes_limit")
    return float(limit) if limit else None


# --- scoring ----------------------------------------------------------------

def score_mesh(
    param_shapes,
    axis_sizes,
    strategy: str,
    *,
    model_config,
    global_rows: int,
    max_seq_len: int,
    grad_accum: int,
    device_kind: str = "",
    peak_flops: Optional[float] = None,
    opt_state_bytes: int = 4,
    carry_cast: bool = True,
) -> Dict[str, Any]:
    """One ranked-table entry for one feasible mesh.

    Predicted step time is the comms model's serial roofline — analytic
    compute seconds (6N FLOPs at the device's peak) plus per-device
    collective bytes over the ICI bandwidth, no overlap — so the score
    inherits exactly the assumptions the ``comms_model`` record documents.
    """
    sizes = {ax: axis_sizes.get(ax, 1) for ax in mesh_lib.MESH_AXES}
    dp = sizes[mesh_lib.DATA_AXIS] * sizes[mesh_lib.FSDP_AXIS]
    batch_per_shard = global_rows // dp
    rec = comms_lib.build_core(
        param_shapes, sizes, strategy,
        model_config=model_config, batch_size=batch_per_shard,
        max_seq_len=max_seq_len, grad_accum=grad_accum,
        device_kind=device_kind, peak_flops=peak_flops)
    mem = estimate_memory(
        param_shapes, sizes, strategy,
        model_config=model_config, batch_size=batch_per_shard,
        max_seq_len=max_seq_len,
        opt_state_bytes=opt_state_bytes, carry_cast=carry_cast)
    compute_ms = rec["compute_seconds_est"] * 1e3
    comms_ms = rec["comms_seconds_est"] * 1e3
    # Pipeline bubble: under GPipe, each of the (st-1) ramp-up/down slots
    # idles relative to the m microbatches of useful work — compute
    # stretches by (1 + (st-1)/m). The comms model doesn't see idleness
    # (it counts bytes), so the scorer must, or stage meshes win on cheap
    # boundary transfers alone.
    st = sizes[mesh_lib.STAGE_AXIS]
    bubble = 1.0
    if st > 1:
        micro = model_config.pipeline_microbatches or st
        bubble = 1.0 + (st - 1) / micro
    return {
        "mesh": sizes,
        "batch_per_shard": batch_per_shard,
        "predicted_step_ms": compute_ms * bubble + comms_ms,
        "compute_ms": compute_ms,
        "comms_ms": comms_ms,
        "bubble_factor": bubble,
        "bytes_per_device": rec["total_bytes_per_device_per_step"],
        "peak_hbm_gb": mem["total"] / GiB,
        "bound": rec["bound"],
    }


# --- the planner ------------------------------------------------------------

class NoFeasiblePlanError(ValueError):
    """No mesh factorization of the device count can run this model."""


def plan(
    model_config,
    n_devices: int,
    *,
    global_rows: int,
    max_seq_len: int,
    grad_accum: int,
    strategy: str = "zero3",
    device_kind: str = "",
    hbm_gb: Optional[float] = None,
    peak_flops: Optional[float] = None,
    opt_state_bytes: int = 4,
    carry_cast: bool = True,
    top_k: int = 5,
    exclude_axes: Sequence[str] = (),
) -> Dict[str, Any]:
    """Enumerate, prune, score, rank; return the ``mesh_plan`` record.

    ``device_kind`` drives both the ICI-bandwidth table and (when
    ``peak_flops`` is not given and the kind is non-empty) the peak-FLOPs
    table — so ``--device-kind v5e`` plans consistently for hardware this
    process doesn't own. With neither given, the roofline falls back to
    the local device exactly like the live comms model.

    ``exclude_axes`` drops candidates that split the named axes — for
    platform capability gaps rather than model arithmetic (e.g. the CPU
    SPMD partitioner cannot lower the GPipe stage shard_map, so CPU
    correctness-mode callers exclude ``"stage"``).

    Raises :class:`NoFeasiblePlanError` when every factorization is pruned
    (message includes the per-candidate reasons, capped).
    """
    strategy = shard_lib.canonical_strategy(strategy)
    if peak_flops is None and device_kind:
        peak_flops = peak_flops_for_kind(device_kind)
    param_shapes = comms_lib.abstract_params(model_config)
    budget = hbm_budget_bytes(hbm_gb)

    scored: List[Dict[str, Any]] = []
    reasons: List[str] = []
    hbm_reasons: List[str] = []
    n_enumerated = 0
    pruned = {"divisibility": 0, "hbm": 0}
    if exclude_axes:
        pruned["excluded"] = 0
    for sizes in enumerate_meshes(n_devices):
        n_enumerated += 1
        if any(sizes.get(ax, 1) > 1 for ax in exclude_axes):
            pruned["excluded"] += 1
            if len(reasons) < 8:
                reasons.append(
                    f"mesh {tuple(sizes.values())} splits excluded axis "
                    f"({', '.join(exclude_axes)})")
            continue
        err = feasibility_error(
            sizes, model_config, n_devices=n_devices,
            global_rows=global_rows, max_seq_len=max_seq_len)
        if err:
            pruned["divisibility"] += 1
            if len(reasons) < 8:
                reasons.append(err)
            continue
        entry = score_mesh(
            param_shapes, sizes, strategy,
            model_config=model_config, global_rows=global_rows,
            max_seq_len=max_seq_len, grad_accum=grad_accum,
            device_kind=device_kind, peak_flops=peak_flops,
            opt_state_bytes=opt_state_bytes, carry_cast=carry_cast)
        if budget is not None and entry["peak_hbm_gb"] * GiB > budget:
            pruned["hbm"] += 1
            if len(hbm_reasons) < 4:
                hbm_reasons.append(
                    f"mesh {tuple(sizes.values())} needs "
                    f"{entry['peak_hbm_gb']:.2f} GiB/device "
                    f"> budget {budget / GiB:.2f} GiB")
            continue
        scored.append(entry)

    if not scored:
        # HBM reasons first: "everything divisible got memory-pruned" is the
        # actionable story (raise --hbm_gb / shrink the batch), and the
        # divisibility list alone would bury it under the 8-reason cap.
        raise NoFeasiblePlanError(
            f"no feasible mesh for {n_devices} devices "
            f"(global batch {global_rows}, seq {max_seq_len}): "
            + "; ".join((hbm_reasons + reasons)[:8]))

    # Deterministic rank: predicted step time, then the axis tuple so equal
    # scores (common on symmetric factorizations) break identically across
    # runs and hosts.
    scored.sort(key=lambda e: (e["predicted_step_ms"],
                               tuple(e["mesh"][ax] for ax in
                                     mesh_lib.MESH_AXES)))
    chosen = scored[0]
    return {
        "kind": "mesh_plan",
        "schema_version": SCHEMA_VERSION,
        "devices": n_devices,
        "strategy": strategy,
        "global_rows": global_rows,
        "seq_len": max_seq_len,
        "grad_accum": grad_accum,
        "device_kind": device_kind or "unknown",
        "hbm_budget_gb": (budget / GiB) if budget is not None else None,
        "n_enumerated": n_enumerated,
        "n_feasible": len(scored),
        "pruned": pruned,
        "chosen": chosen,
        "ranked": scored[:max(1, top_k)],
        "predicted_step_ms": chosen["predicted_step_ms"],
        "assumptions": {
            "score": "serial roofline: 6N-FLOPs compute + ring-collective "
                     "bytes / ICI, no overlap (comms_model assumptions)",
            "global_batch_held_fixed": True,
            "memory": "analytic params+opt+grads via PartitionSpec shard "
                      "factors; activations approximate (flash attention, "
                      "per-layer residual+MLP streams)",
        },
    }


def plan_single(
    model_config,
    axis_sizes,
    strategy: str,
    *,
    global_rows: int,
    max_seq_len: int,
    grad_accum: int,
    device_kind: str = "",
    peak_flops: Optional[float] = None,
    hbm_gb: Optional[float] = None,
    opt_state_bytes: int = 4,
    carry_cast: bool = True,
) -> Dict[str, Any]:
    """``mesh_plan`` record for ONE pinned mesh — no search.

    The predicted-vs-measured validation path: ``bench.py`` scores the mesh
    it actually ran (explicit ``--mesh-*`` splits, the DP/zero3 table
    lanes) and writes the record with ``measured_step_ms`` filled, so
    ``tools/analyze.py`` can gate prediction error on real lanes, not just
    on whatever ``auto`` happened to pick. Same record shape as
    :func:`plan` with a one-entry ranking (trivially its own argmin).
    """
    strategy = shard_lib.canonical_strategy(strategy)
    if peak_flops is None and device_kind:
        peak_flops = peak_flops_for_kind(device_kind)
    sizes = {ax: axis_sizes.get(ax, 1) for ax in mesh_lib.MESH_AXES}
    n_devices = int(np.prod(list(sizes.values())))
    param_shapes = comms_lib.abstract_params(model_config)
    entry = score_mesh(
        param_shapes, sizes, strategy,
        model_config=model_config, global_rows=global_rows,
        max_seq_len=max_seq_len, grad_accum=grad_accum,
        device_kind=device_kind, peak_flops=peak_flops,
        opt_state_bytes=opt_state_bytes, carry_cast=carry_cast)
    budget = hbm_budget_bytes(hbm_gb)
    return {
        "kind": "mesh_plan",
        "schema_version": SCHEMA_VERSION,
        "devices": n_devices,
        "strategy": strategy,
        "global_rows": global_rows,
        "seq_len": max_seq_len,
        "grad_accum": grad_accum,
        "device_kind": device_kind or "unknown",
        "hbm_budget_gb": (budget / GiB) if budget is not None else None,
        "n_enumerated": 1,
        "n_feasible": 1,
        "pruned": {"divisibility": 0, "hbm": 0},
        "chosen": entry,
        "ranked": [entry],
        "predicted_step_ms": entry["predicted_step_ms"],
        "assumptions": {
            "score": "serial roofline: 6N-FLOPs compute + ring-collective "
                     "bytes / ICI, no overlap (comms_model assumptions)",
            "global_batch_held_fixed": True,
            "memory": "analytic params+opt+grads via PartitionSpec shard "
                      "factors; activations approximate (flash attention, "
                      "per-layer residual+MLP streams)",
        },
    }


def mesh_config_for(entry: Dict[str, Any]) -> mesh_lib.MeshConfig:
    """A plan entry's mesh as a MeshConfig (for ``make_mesh``)."""
    m = entry["mesh"]
    return mesh_lib.MeshConfig(**{
        field.name: int(m.get(field.name, 1))
        for field in dataclasses.fields(mesh_lib.MeshConfig)
    })


def render_table(record: Dict[str, Any]) -> List[str]:
    """Human-readable ranked plan table for a ``mesh_plan`` record."""
    hdr = "x".join(mesh_lib.MESH_AXES)
    lines = [
        (f"mesh_plan | {record['devices']} devices, strategy "
         f"{record['strategy']}, global batch {record['global_rows']} rows, "
         f"seq {record['seq_len']}, accum {record['grad_accum']}"),
        (f"mesh_plan | {record['n_enumerated']} factorizations -> "
         f"{record['n_feasible']} feasible "
         f"(pruned: {record['pruned']['divisibility']} divisibility, "
         f"{record['pruned']['hbm']} HBM"
         + (f" @ {record['hbm_budget_gb']:.1f} GiB/device"
            if record.get("hbm_budget_gb") else "")
         + (f", {record['pruned']['excluded']} axis-excluded"
            if record["pruned"].get("excluded") else "") + ")"),
        (f"| rank | {hdr} | batch/shard | pred ms | compute ms | comms ms "
         f"| HBM GiB | bound |"),
        "|---|---|---|---|---|---|---|---|",
    ]
    for i, e in enumerate(record["ranked"]):
        m = "x".join(str(e["mesh"][ax]) for ax in mesh_lib.MESH_AXES)
        marker = " *" if i == 0 else ""
        lines.append(
            f"| {i + 1}{marker} | {m} | {e['batch_per_shard']} "
            f"| {e['predicted_step_ms']:.2f} | {e['compute_ms']:.2f} "
            f"| {e['comms_ms']:.2f} | {e['peak_hbm_gb']:.2f} "
            f"| {e['bound']} |")
    return lines
