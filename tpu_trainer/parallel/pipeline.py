"""Pipeline parallelism: a GPipe-style microbatch schedule over a mesh axis.

Pipeline parallelism is an aspirational bullet in the reference
(``README.md:10`` — never implemented; SURVEY.md §2). Here it is a working
SPMD schedule, built the TPU way: no per-stage processes or RPC — one
``shard_map`` over the ``stage`` mesh axis, with activations handed to the
next stage by ``lax.ppermute`` over ICI and the whole schedule expressed as
a ``lax.scan`` (so it jits once and differentiates end-to-end; the backward
pass is the reverse pipeline, derived by AD).

Schedule (classic GPipe):

- The layer stack ``[L, ...]`` is split into ``S`` contiguous stages
  (``L/S`` layers each — the stacked-parameter layout from ``nn.scan`` makes
  this a pure sharding of the leading axis; ``parallel/sharding.py`` pins
  that dim to ``stage``).
- The batch is split into ``M`` microbatches *by striding* (row ``j*M + m``
  → microbatch ``m``): under a ``data``-sharded batch this keeps every
  microbatch evenly spread across data shards, where a contiguous split
  would put each microbatch on a subset of them.
- At step ``t`` of ``M+S-1``, stage ``s`` processes microbatch ``t - s``
  (bubble fraction ``(S-1)/(M+S-1)``). Stage 0 feeds from the microbatch
  queue; stage ``S-1`` stores results; between steps every stage ppermutes
  its output to its right neighbor.

The shard_map is *partial-manual* (``axis_names={stage}``): every other
mesh axis stays under GSPMD, so the batch's ``data`` sharding and the
params' ``fsdp``/``tensor`` shardings ride through untouched and the
schedule composes with DP/ZeRO by construction.

``pipeline_forward`` is deliberately model-agnostic: it takes the stacked
per-layer params and a ``block_fn(layer_params, x[, rng]) -> x``. The
embedding / final-norm / loss stay outside (they are cheap and replicated
over ``stage``). With ``rng`` given, ``block_fn`` receives a key folded per
(global layer, microbatch) — distinct dropout masks everywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpu_trainer.parallel.mesh import STAGE_AXIS


def pipeline_forward(
    stacked_params: Any,
    x: jax.Array,
    block_fn: Callable,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
    rng: Optional[jax.Array] = None,
    with_aux: bool = False,
) -> Any:
    """Run ``x`` through the full layer stack with a GPipe schedule.

    Args:
      stacked_params: pytree whose leaves lead with the layer axis ``[L, ...]``
        (the ``nn.scan`` layout); logically global, sharded over ``axis_name``.
      x: ``[batch, seq, hidden]`` activations; batch must divide into
        ``num_microbatches``.
      block_fn: applies ONE layer: ``block_fn(params_of_layer, x) -> x``, or
        ``block_fn(params_of_layer, x, rng) -> x`` when ``rng`` is given.
        With ``with_aux``, returns ``(x, aux_scalar)`` instead (the MoE
        load-balance term).
      mesh: mesh containing ``axis_name`` (other axes stay GSPMD-auto).
      num_microbatches: M; more microbatches -> smaller pipeline bubble.
      rng: optional dropout key; folded per (global layer, microbatch).
      with_aux: accumulate per-layer scalar aux across real schedule steps
        (bubble steps excluded), summed over layers and averaged over
        microbatches — the per-micro estimator matching grad-accum
        semantics. Returns ``(activations, aux)``.

    Returns activations after all L layers, ``[batch, seq, hidden]``
    (plus the aux scalar when ``with_aux``).
    """
    S = mesh.shape[axis_name]
    b, s, h = x.shape
    M = num_microbatches
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by M={M}")
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(
            f"num_layers {n_layers} not divisible by {S} pipeline stages"
        )
    mb = b // M
    layers_per_stage = n_layers // S

    def staged(local_params, x_local, *rng_arg):
        # local_params: leaves [L/S, ...] (this stage's layers).
        # x_local: full batch [b, s, h], replicated over `stage` (its data
        # sharding, if any, is handled by the surrounding auto axes).
        stage = lax.axis_index(axis_name)
        # Strided microbatching: row j*M + m -> microbatch m (see module
        # docstring for why not contiguous).
        micro = x_local.reshape(mb, M, s, h).transpose(1, 0, 2, 3)

        def run_stage(xm, t):
            micro_idx = t - stage  # valid in [0, M) when the step is real

            def one_layer(carry, scanned):
                xc, aux = carry
                li, p = scanned
                args = (p, xc)
                if rng_arg:
                    g_layer = stage * layers_per_stage + li
                    args = args + (jax.random.fold_in(
                        rng_arg[0], g_layer * M + jnp.clip(micro_idx, 0, M - 1)
                    ),)
                out = block_fn(*args)
                if with_aux:
                    out, layer_aux = out
                    aux = aux + layer_aux
                return (out, aux), None

            (out, aux), _ = lax.scan(
                one_layer, (xm, jnp.zeros((), jnp.float32)),
                (jnp.arange(layers_per_stage), local_params),
            )
            # Bubble steps compute garbage that must not leak into the aux
            # sum; micro_idx validity is decided here, next to where it is
            # defined.
            real = jnp.logical_and(micro_idx >= 0, micro_idx < M)
            return out, jnp.where(real, aux, 0.0)

        perm = [(i, (i + 1) % S) for i in range(S)]
        outputs0 = jnp.zeros((M, mb, s, h), x_local.dtype)
        # `moving` is each stage's current inbound activation slot.
        moving0 = jnp.zeros((mb, s, h), x_local.dtype)

        def step(carry, t):
            moving, outputs, aux_acc = carry
            # Stage 0 ingests microbatch t (when in range); others take the
            # activation that arrived from the left neighbor.
            feed_idx = jnp.clip(t, 0, M - 1)
            x_in = jnp.where(stage == 0, micro[feed_idx], moving)
            y, aux_y = run_stage(x_in, t)  # aux_y already bubble-masked
            aux_acc = aux_acc + aux_y
            # Last stage stores microbatch t - (S-1) when it's real.
            out_idx = t - (S - 1)
            store = jnp.logical_and(stage == S - 1, out_idx >= 0)
            outputs = lax.cond(
                store,
                lambda o: lax.dynamic_update_slice(
                    o, y[None], (jnp.maximum(out_idx, 0), 0, 0, 0)
                ),
                lambda o: o,
                outputs,
            )
            moving = lax.ppermute(y, axis_name, perm)
            return (moving, outputs, aux_acc), None

        (_, outputs, aux_acc), _ = lax.scan(
            step, (moving0, outputs0, jnp.zeros((), jnp.float32)),
            jnp.arange(M + S - 1),
        )
        # Only the last stage holds real outputs; broadcast them to every
        # stage so the result is replicated over the axis (psum of a
        # one-hot-masked buffer).
        mask = (stage == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis_name)
        # Undo the strided microbatch grouping.
        outputs = outputs.transpose(1, 0, 2, 3).reshape(b, s, h)
        if with_aux:
            # Sum over stages = sum over all layers; mean over microbatches.
            aux = lax.psum(aux_acc, axis_name) / M
            return outputs, aux
        return outputs

    layer_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    rng_args = () if rng is None else (rng,)
    rng_specs = () if rng is None else (P(),)
    fn = shard_map(
        staged,
        mesh=mesh,
        in_specs=(layer_specs, P()) + rng_specs,
        out_specs=(P(), P()) if with_aux else P(),
        axis_names={axis_name},
        check_vma=False,
    )
    return fn(stacked_params, x, *rng_args)
