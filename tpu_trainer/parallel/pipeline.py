"""Pipeline parallelism: a GPipe-style microbatch schedule over a mesh axis.

Pipeline parallelism is an aspirational bullet in the reference
(``README.md:10`` — never implemented; SURVEY.md §2). Here it is a working
SPMD schedule, built the TPU way: no per-stage processes or RPC — one
``shard_map`` over a ``stage`` mesh axis, with activations handed to the
next stage by ``lax.ppermute`` over ICI and the whole schedule expressed as
a ``lax.scan`` (so it jits once and differentiates end-to-end; the backward
pass is the reverse pipeline, derived by AD).

Schedule (classic GPipe):

- The layer stack ``[L, ...]`` is split into ``S`` contiguous stages
  (``L/S`` layers each — the stacked-parameter layout from ``nn.scan`` makes
  this a pure sharding of the leading axis).
- The batch is split into ``M`` microbatches. At step ``t`` of ``M+S-1``,
  stage ``s`` processes microbatch ``t - s`` (bubble fraction
  ``(S-1)/(M+S-1)``).
- Stage 0 feeds from the microbatch queue; stage ``S-1`` writes results.
  Between steps every stage ppermutes its output to its right neighbor.

`pipeline_forward` is deliberately model-agnostic: it takes the stacked
per-layer params and a ``block_fn(layer_params, x) -> x``. The embedding /
final-norm / loss stay outside (they are cheap and replicated).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

STAGE_AXIS = "stage"


def pipeline_forward(
    stacked_params: Any,
    x: jax.Array,
    block_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
) -> jax.Array:
    """Run ``x`` through the full layer stack with a GPipe schedule.

    Args:
      stacked_params: pytree whose leaves lead with the layer axis ``[L, ...]``
        (the ``nn.scan`` layout); logically global, sharded over ``axis_name``.
      x: ``[batch, seq, hidden]`` activations; batch must divide into
        ``num_microbatches``.
      block_fn: applies ONE layer: ``block_fn(params_of_layer, x) -> x``.
      mesh: mesh containing ``axis_name``.
      num_microbatches: M; more microbatches -> smaller pipeline bubble.

    Returns activations after all L layers, ``[batch, seq, hidden]``.
    """
    S = mesh.shape[axis_name]
    b, s, h = x.shape
    if b % num_microbatches != 0:
        raise ValueError(f"batch {b} not divisible by M={num_microbatches}")
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(
            f"num_layers {n_layers} not divisible by {S} pipeline stages"
        )
    mb = b // num_microbatches
    M = num_microbatches

    def staged(local_params, x_local):
        # local_params: leaves [L/S, ...] (this stage's layers).
        # x_local: full batch [b, s, h] (batch stays replicated over the
        # stage axis; only the *stage* of processing differs).
        stage = lax.axis_index(axis_name)
        micro = x_local.reshape(M, mb, s, h)

        def run_stage(xm):
            def one_layer(carry, layer_params):
                return block_fn(layer_params, carry), None

            out, _ = lax.scan(one_layer, xm, local_params)
            return out

        perm = [(i, (i + 1) % S) for i in range(S)]
        outputs0 = jnp.zeros((M, mb, s, h), x_local.dtype)
        # `moving` is each stage's current inbound activation slot.
        moving0 = jnp.zeros((mb, s, h), x_local.dtype)

        def step(carry, t):
            moving, outputs = carry
            # Stage 0 ingests microbatch t (when in range); others take the
            # activation that arrived from the left neighbor.
            feed_idx = jnp.clip(t, 0, M - 1)
            x_in = jnp.where(stage == 0, micro[feed_idx], moving)
            y = run_stage(x_in)
            # Last stage stores microbatch t - (S-1) when it's real.
            out_idx = t - (S - 1)
            store = jnp.logical_and(stage == S - 1, out_idx >= 0)
            outputs = lax.cond(
                store,
                lambda o: lax.dynamic_update_slice(
                    o, y[None], (jnp.maximum(out_idx, 0), 0, 0, 0)
                ),
                lambda o: o,
                outputs,
            )
            moving = lax.ppermute(y, axis_name, perm)
            return (moving, outputs), None

        (_, outputs), _ = lax.scan(
            step, (moving0, outputs0), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs; broadcast them to every
        # stage so the result is replicated over the axis (psum of a
        # one-hot-masked buffer).
        mask = (stage == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis_name)
        return outputs.reshape(b, s, h)

    layer_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    fn = shard_map(
        staged,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, x)
