"""Pipeline parallelism: a GPipe-style microbatch schedule over a mesh axis.

Pipeline parallelism is an aspirational bullet in the reference
(``README.md:10`` — never implemented; SURVEY.md §2). Here it is a working
SPMD schedule, built the TPU way: no per-stage processes or RPC — one
``shard_map`` over the ``stage`` mesh axis, with activations handed to the
next stage by ``lax.ppermute`` over ICI and the whole schedule expressed as
a ``lax.scan`` (so it jits once and differentiates end-to-end; the backward
pass is the reverse pipeline, derived by AD).

Schedule (classic GPipe):

- The layer stack ``[L, ...]`` is split into ``S`` contiguous stages
  (``L/S`` layers each — the stacked-parameter layout from ``nn.scan`` makes
  this a pure sharding of the leading axis; ``parallel/sharding.py`` pins
  that dim to ``stage``).
- The batch is split into ``M`` microbatches *by striding* (row ``j*M + m``
  → microbatch ``m``): under a ``data``-sharded batch this keeps every
  microbatch evenly spread across data shards, where a contiguous split
  would put each microbatch on a subset of them.
- At step ``t`` of ``M+S-1``, stage ``s`` processes microbatch ``t - s``
  (bubble fraction ``(S-1)/(M+S-1)``). Stage 0 feeds from the microbatch
  queue; stage ``S-1`` stores results; between steps every stage ppermutes
  its output to its right neighbor.

The shard_map is *partial-manual* (``axis_names={stage}``): every other
mesh axis stays under GSPMD, so the batch's ``data`` sharding and the
params' ``fsdp``/``tensor`` shardings ride through untouched and the
schedule composes with DP/ZeRO by construction.

``pipeline_forward`` is deliberately model-agnostic: it takes the stacked
per-layer params and a ``block_fn(layer_params, x[, rng]) -> x``. The
embedding / final-norm / loss stay outside (they are cheap and replicated
over ``stage``). With ``rng`` given, ``block_fn`` receives a key folded per
(global layer, microbatch) — distinct dropout masks everywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_trainer.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpu_trainer.parallel.mesh import STAGE_AXIS


def pipeline_forward(
    stacked_params: Any,
    x: jax.Array,
    block_fn: Callable,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
    rng: Optional[jax.Array] = None,
    with_aux: bool = False,
    manual_seq_axis: Optional[str] = None,
) -> Any:
    """Run ``x`` through the full layer stack with a GPipe schedule.

    Args:
      stacked_params: pytree whose leaves lead with the layer axis ``[L, ...]``
        (the ``nn.scan`` layout); logically global, sharded over ``axis_name``.
      x: ``[batch, seq, hidden]`` activations; batch must divide into
        ``num_microbatches``.
      block_fn: applies ONE layer: ``block_fn(params_of_layer, x) -> x``, or
        ``block_fn(params_of_layer, x, rng) -> x`` when ``rng`` is given.
        With ``with_aux``, returns ``(x, aux_scalar)`` instead (the MoE
        load-balance term).
      mesh: mesh containing ``axis_name`` (other axes stay GSPMD-auto).
      num_microbatches: M; more microbatches -> smaller pipeline bubble.
      rng: optional dropout key; folded per (global layer, microbatch).
      with_aux: accumulate per-layer scalar aux across real schedule steps
        (bubble steps excluded), summed over layers and averaged over
        microbatches — the per-micro estimator matching grad-accum
        semantics. Returns ``(activations, aux)``.
      manual_seq_axis: when sequence parallelism composes with the
        pipeline, the shard_map goes jointly manual over
        ``{stage, manual_seq_axis}`` and activations enter seq-sharded:
        the attention's ring collectives then bind to the SAME manual
        region instead of opening a nested one (the construct Shardy
        rejects). The model routes its attention through
        ``ring.ring_attention_manual`` under
        ``ring.sequence_parallel_manual``.

    Returns activations after all L layers, ``[batch, seq, hidden]``
    (plus the aux scalar when ``with_aux``).
    """
    S = mesh.shape[axis_name]
    b, s, h = x.shape
    M = num_microbatches
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by M={M}")
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(
            f"num_layers {n_layers} not divisible by {S} pipeline stages"
        )
    mb = b // M
    layers_per_stage = n_layers // S

    def staged(local_params, x_local, *rng_arg):
        # local_params: leaves [L/S, ...] (this stage's layers).
        # x_local: full batch [b, s, h], replicated over `stage` (its data
        # sharding, if any, is handled by the surrounding auto axes).
        stage = lax.axis_index(axis_name)
        # Local shapes: under joint SP the sequence dim entered sharded.
        s_l = x_local.shape[1]
        # Strided microbatching: row j*M + m -> microbatch m (see module
        # docstring for why not contiguous).
        micro = x_local.reshape(mb, M, s_l, h).transpose(1, 0, 2, 3)

        def run_stage(xm, t):
            micro_idx = t - stage  # valid in [0, M) when the step is real

            def one_layer(carry, scanned):
                xc, aux = carry
                li, p = scanned
                args = (p, xc)
                if rng_arg:
                    g_layer = stage * layers_per_stage + li
                    key = jax.random.fold_in(
                        rng_arg[0], g_layer * M + jnp.clip(micro_idx, 0, M - 1)
                    )
                    if manual_seq_axis is not None:
                        # Each sequence shard sees only its local slice, and
                        # hash_dropout keys masks by LOCAL positions — fold
                        # the shard index so chunks don't repeat one mask.
                        key = jax.random.fold_in(
                            key, lax.axis_index(manual_seq_axis)
                        )
                    args = args + (key,)
                out = block_fn(*args)
                if with_aux:
                    out, layer_aux = out
                    aux = aux + layer_aux
                return (out, aux), None

            (out, aux), _ = lax.scan(
                one_layer, (xm, jnp.zeros((), jnp.float32)),
                (jnp.arange(layers_per_stage), local_params),
            )
            # Bubble steps compute garbage that must not leak into the aux
            # sum; micro_idx validity is decided here, next to where it is
            # defined.
            real = jnp.logical_and(micro_idx >= 0, micro_idx < M)
            return out, jnp.where(real, aux, 0.0)

        perm = [(i, (i + 1) % S) for i in range(S)]
        outputs0 = jnp.zeros((M, mb, s_l, h), x_local.dtype)
        # `moving` is each stage's current inbound activation slot.
        moving0 = jnp.zeros((mb, s_l, h), x_local.dtype)

        def step(carry, t):
            moving, outputs, aux_acc = carry
            # Stage 0 ingests microbatch t (when in range); others take the
            # activation that arrived from the left neighbor.
            feed_idx = jnp.clip(t, 0, M - 1)
            x_in = jnp.where(stage == 0, micro[feed_idx], moving)
            y, aux_y = run_stage(x_in, t)  # aux_y already bubble-masked
            aux_acc = aux_acc + aux_y
            # Last stage stores microbatch t - (S-1) when it's real.
            out_idx = t - (S - 1)
            store = jnp.logical_and(stage == S - 1, out_idx >= 0)
            outputs = lax.cond(
                store,
                lambda o: lax.dynamic_update_slice(
                    o, y[None], (jnp.maximum(out_idx, 0), 0, 0, 0)
                ),
                lambda o: o,
                outputs,
            )
            moving = lax.ppermute(y, axis_name, perm)
            return (moving, outputs, aux_acc), None

        (_, outputs, aux_acc), _ = lax.scan(
            step, (moving0, outputs0, jnp.zeros((), jnp.float32)),
            jnp.arange(M + S - 1),
        )
        # Only the last stage holds real outputs; broadcast them to every
        # stage so the result is replicated over the axis (psum of a
        # one-hot-masked buffer).
        mask = (stage == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis_name)
        # Undo the strided microbatch grouping.
        outputs = outputs.transpose(1, 0, 2, 3).reshape(b, s_l, h)
        if with_aux:
            # Sum over stages = sum over all layers; mean over microbatches
            # (and over sequence shards under joint SP — each shard's aux
            # estimates from its local tokens, the same per-shard estimator
            # grad accumulation uses per micro).
            aux = lax.psum(aux_acc, axis_name) / M
            if manual_seq_axis is not None:
                sq = mesh.shape[manual_seq_axis]
                aux = lax.psum(aux, manual_seq_axis) / sq
            return outputs, aux
        return outputs

    layer_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    rng_args = () if rng is None else (rng,)
    rng_specs = () if rng is None else (P(),)
    x_spec = (P(None, manual_seq_axis, None) if manual_seq_axis is not None
              else P())
    manual = ({axis_name} if manual_seq_axis is None
              else {axis_name, manual_seq_axis})
    fn = shard_map(
        staged,
        mesh=mesh,
        in_specs=(layer_specs, x_spec) + rng_specs,
        out_specs=(x_spec, P()) if with_aux else x_spec,
        axis_names=manual,
        check_vma=False,
    )
    return fn(stacked_params, x, *rng_args)


def pipeline_1f1b(
    stacked_params: Any,
    x: jax.Array,
    input_ids: jax.Array,
    labels: jax.Array,
    stage_fwd: Callable,
    head_vjp: Callable,
    head_grad_zeros: Any,
    emb_accum: Callable,
    emb_grad_zeros: Any,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
    head_finalize: Callable = lambda acc: acc,
    manual_seq_axis: Optional[str] = None,
    with_aux: bool = False,
    aux_seed: Optional[jax.Array] = None,
    virtual_stages: int = 1,
) -> Any:
    """Interleaved forward/backward (1F1B-style) pipeline with MANUAL
    backward scheduling — the loss and every gradient come out of ONE scan.

    Why not AD of the GPipe scan: differentiating ``pipeline_forward``
    keeps every scan step's carry (all ``M`` microbatch activations) alive
    until the bubble point, so pipeline activation memory scales with M —
    the thing 1F1B exists to fix. Here each microbatch's backward starts as
    soon as its forward clears the last stage (which computes that micro's
    loss VJP in the SAME tick), so a stage retains at most
    ``min(M, 2(S-1)+1)`` saved stage-inputs — independent of M. Stage
    blocks are recomputed inside ``jax.vjp`` from the saved inputs
    (stage-granular rematerialization), the same total compute as GPipe
    with per-block remat.

    Schedule — expressed through ONE canonical work-item sequence shared by
    every device. Forward item ``k`` covers (chunk ``(k mod Sv) div S``,
    micro ``(k div Sv)*S + k mod S``) and is run by device ``s`` at tick
    ``t = s + k``; backward item ``j`` is the same pairing with the chunk
    order REVERSED, run by device ``s`` at tick ``t = (vS-1) + j +
    (S-1-s)``. Because global stage ``g = c*S + s`` always hands to device
    ``(s+1) mod S`` (chunk boundaries wrap the ring), one canonical
    sequence + a per-device tick shift gives immediate-consume dataflow:
    every activation/cotangent ppermuted at tick ``t`` is consumed at
    ``t+1``. With ``v = 1`` this reduces exactly to classic 1F1B (fwd
    micro ``t - s``, bwd micro ``t - 2(S-1) + s``); with ``v > 1`` it is
    Megatron's interleaved schedule (device-0 warmup ``2(S-1) + (v-1)S``
    chunk-slots), bubble ~``(S-1)/(vM+S-1)`` per tick-latency ``1/v`` of a
    full stage. Per tick (``vM + (v+1)S - 2`` total; every stage runs both
    masked halves — SPMD):

    - forward half: stage ``s`` runs fwd item ``t - s`` when valid.
    - head: on the (static, stage-UNIFORM — they depend only on ``t``, so
      a real ``lax.cond`` is legal around collectives, unlike a
      stage-predicated branch which deadlocks them) ticks where the LAST
      chunk's forward completes at the last stage, that stage's fresh
      output is broadcast over the axis by a masked psum and every stage
      evaluates ``head_vjp`` on it. The head is expected to be SHARDED
      over ``axis_name`` (each stage computes 1/S of the vocab —
      ``ops/loss.py vocab_sharded_shifted_cross_entropy``), so one
      microbatch's head costs one full head evaluation TOTAL, split S
      ways: head compute per step is M x (1/S) per device, strictly less
      than the non-pipelined trainer's.
    - backward half: stage ``s`` runs backward item ``t - (vS-1) - (S-1)
      + s`` when valid (at the last stage, a last-chunk backward item
      coincides with the head's micro: the 1F1B "B right after F",
      consuming this tick's ``dy``; other chunks' items consume the
      cotangent ppermuted from device ``s+1``); cotangents travel left by
      ppermute; layer grads accumulate per chunk; stage 0 folds chunk-0
      ``dx`` into the embedding gradient via ``emb_accum`` (no [M, ...]
      cotangent buffer).

    Args:
      stacked_params: ``[L, ...]`` leaves, sharded over ``axis_name``.
        With ``virtual_stages > 1`` the stack is permuted here so each
        device's shard holds its v chunks contiguously (global stage
        ``g = c*S + s`` owns layers ``[g*Lc, (g+1)*Lc)``); gradients are
        inverse-permuted back to natural layer order before returning.
      x: embedded activations ``[batch, seq, hidden]``.
      labels: ``[batch, seq]`` int labels (microbatched alongside x).
      stage_fwd: ``(chunk_params, x_mb, micro_idx, chunk_idx) -> y_mb`` —
        ONE chunk's layer block (``chunk_params`` leaves lead with
        ``L/(S*v)``); must fold its dropout rngs from (global layer,
        micro) exactly like the GPipe path so the schedules are
        grad-equivalent.
      virtual_stages: v layer chunks per device (``"interleaved"``);
        1 = classic 1F1B. v > 1 requires ``M % S == 0`` (the canonical
        sequence feeds micros in groups of S) and ``L % (S*v) == 0``.
      head_vjp: ``(y_mb, labels_mb, micro_idx) -> (loss, dy, dhead)`` —
        per-micro loss (already scaled by 1/M and any loss scale,
        REPLICATED over the axis), the FULL cotangent wrt y (already
        psummed if computed from vocab shards), and this stage's PARTIAL
        head-parameter grads (slice-local shapes allowed).
      head_grad_zeros / emb_grad_zeros: zero pytrees for the accumulators
        (``head_grad_zeros`` in head_vjp's partial shapes).
      head_finalize: maps the accumulated partial head grads to full-shape
        per-stage contributions (e.g. scatter a vocab slice to its rows);
        runs once inside the manual region, before the final psum.
      emb_accum: ``(acc, dx_mb, ids_mb) -> acc`` — folds a micro's input
        cotangent into the embedding gradient at that micro's token ids
        (runs on stage 0 only).
      manual_seq_axis: jointly-manual SP x PP, as ``pipeline_forward``:
        ``x``/``input_ids`` enter sequence-sharded, the stage body runs the
        ring attention in-region, and ``labels`` stay GLOBAL (the head's
        next-token shift reads across chunk boundaries from them —
        ``ops/loss.py``). Layer/head/embedding grads are additionally
        psummed over the sequence axis at the end.
      with_aux: ``stage_fwd`` returns ``(y, aux_scalar)`` (the MoE
        load-balance + z terms, summed over this stage's layers). The
        forward halves accumulate aux over real ticks, and each backward
        seeds the aux output's cotangent with ``aux_seed`` (the caller
        folds 1/M, 1/num_layers, the loss scale, and any sequence-shard
        mean into it) — so the aux gradient rides the SAME stage vjp, no
        second backward.
      aux_seed: scalar f32 cotangent for the aux output per microbatch
        backward (required when ``with_aux``).

    Returns ``(loss_sum, dlayers_stacked, dhead, demb)`` — loss summed over
    microbatches (caller already folded 1/M into head_vjp) — with the raw
    aux sum appended when ``with_aux``:
    ``(loss_sum, aux_sum, dlayers, dhead, demb)``; ``aux_sum`` is summed
    over microbatches and all layers (psummed over stage and, under SP,
    over sequence shards — divide by M [and sq] to get the estimator the
    GPipe path reports).
    """
    import numpy as np

    S = mesh.shape[axis_name]
    v = virtual_stages
    b, s, h = x.shape
    M = num_microbatches
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by M={M}")
    if with_aux and aux_seed is None:
        raise ValueError("with_aux=True requires aux_seed")
    if v > 1 and M % S != 0:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible by "
            f"stages ({S})"
        )
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % (S * v) != 0:
        raise ValueError(
            f"num_layers {n_layers} not divisible by stages*virtual "
            f"({S}*{v})"
        )
    mb = b // M

    # --- static schedule tables (v=1 reduces to the classic closed forms:
    # fwd micro t-s, bwd micro t-2(S-1)+s, head window [S-1, M+S-1)) ------
    K = v * M                              # work items per device per pass
    ks = np.arange(K)
    g0, rem = np.divmod(ks, S * v)
    fwd_chunk_tab = rem // S
    fwd_micro_tab = g0 * S + rem % S
    bwd_chunk_tab = (v - 1) - fwd_chunk_tab   # reversed chunk order
    bwd_micro_tab = fwd_micro_tab
    off = v * S - 1                        # last stage: B-of-(c=v-1,i=0)
    T = v * M + (v + 1) * S - 2            # ticks in one schedule
    t_all = np.arange(T)
    k_h = t_all - (S - 1)                  # head <- last stage's fwd item
    k_hc = np.clip(k_h, 0, K - 1)
    head_on_tab = (k_h >= 0) & (k_h < K) & (fwd_chunk_tab[k_hc] == v - 1)
    head_micro_tab = np.where(head_on_tab, fwd_micro_tab[k_hc], 0)

    # Saved-input window per (device, chunk): exact max in-flight count
    # from a static timeline simulation (v=1 gives min(M, 2S-1)). Each
    # chunk's items write/consume in micro order, so slot ``i mod W`` is
    # collision-free whenever W bounds the overlap count. The read tick
    # counts as LIVE (``tr >= w``, not ``>``): within a tick the forward
    # half writes its slot BEFORE the backward half reads, so a slot
    # written at some micro's read tick would clobber it first — the
    # off-by-one that shrank W to 2 at (S=2, M>2) and corrupted the
    # gradients the round-3 closed form (2S-1 = 3) got right.
    W = 1
    for s_ in range(S):
        for c_ in range(v):
            tw = s_ + ks[fwd_chunk_tab == c_]                 # write ticks
            tr = off + ks[bwd_chunk_tab == c_] + (S - 1 - s_)  # read ticks
            live = np.array([
                int(np.sum((tw <= w) & (tr >= w))) for w in tw
            ])
            W = max(W, int(live.max()))
    W = min(W, M)

    lc = n_layers // (S * v)               # layers per chunk

    def _permute(tree):
        # Natural [L, ...] -> device-major order: position (s, c, rl) so a
        # `stage` shard holds its v chunks contiguously. Identity at v=1.
        return jax.tree_util.tree_map(
            lambda p: p.reshape((v, S, lc) + p.shape[1:])
            .swapaxes(0, 1).reshape((-1,) + p.shape[1:]),
            tree,
        )

    def _unpermute(tree):
        return jax.tree_util.tree_map(
            lambda p: p.reshape((S, v, lc) + p.shape[1:])
            .swapaxes(0, 1).reshape((-1,) + p.shape[1:]),
            tree,
        )

    if v > 1:
        stacked_params = _permute(stacked_params)

    def staged(local_params, x_local, ids_local, labels_local):
        stage = lax.axis_index(axis_name)
        is_last = stage == S - 1
        is_first = stage == 0
        s_l = x_local.shape[1]
        # Strided microbatching, as pipeline_forward. Labels keep their OWN
        # length: global under SP (the head shift needs the next chunk's
        # first token), == s_l otherwise.
        micro = x_local.reshape(mb, M, s_l, h).transpose(1, 0, 2, 3)
        iid = ids_local.reshape(mb, M, s_l).transpose(1, 0, 2)
        lab = labels_local.reshape(mb, M, labels_local.shape[1]).transpose(
            1, 0, 2)

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        # Canonical-sequence tables (device constants).
        fwd_chunk = jnp.asarray(fwd_chunk_tab, jnp.int32)
        fwd_micro = jnp.asarray(fwd_micro_tab, jnp.int32)
        bwd_chunk = jnp.asarray(bwd_chunk_tab, jnp.int32)
        bwd_micro = jnp.asarray(bwd_micro_tab, jnp.int32)
        head_on_t = jnp.asarray(head_on_tab)
        head_micro_t = jnp.asarray(head_micro_tab, jnp.int32)

        # Local chunk view: [L/S, ...] -> [v, L/(S*v), ...] (the global
        # permutation put this device's chunks contiguously).
        local_v = jax.tree_util.tree_map(
            lambda p: p.reshape((v, lc) + p.shape[1:]), local_params
        )
        dlayers0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), local_v
        )
        carry0 = (
            jnp.zeros((mb, s_l, h), x_local.dtype),   # inbound fwd act
            jnp.zeros((mb, s_l, h), x_local.dtype),   # inbound cotangent
            # saved stage-inputs, one ring buffer per chunk
            jnp.zeros((v, W, mb, s_l, h), x_local.dtype),
            dlayers0,
            head_grad_zeros,
            emb_grad_zeros,
            jnp.zeros((), jnp.float32),               # loss acc
            jnp.zeros((), jnp.float32),               # aux acc
        )

        def chunk_of(tree, c):
            return jax.tree_util.tree_map(
                lambda p: lax.dynamic_index_in_dim(p, c, keepdims=False),
                tree,
            )

        def tick(carry, t):
            (f_mov, b_mov, saved, dlayers, dhead, demb, loss_acc,
             aux_acc) = carry

            # ---- forward half -------------------------------------------
            k_f = t - stage
            f_valid = jnp.logical_and(k_f >= 0, k_f < K)
            k_fc = jnp.clip(k_f, 0, K - 1)
            c_f = fwd_chunk[k_fc]
            i_f = fwd_micro[k_fc]
            # Fresh micros enter at (stage 0, chunk 0); everything else
            # consumes the ppermuted activation (chunk boundaries included
            # — global stage c*S+S-1 -> (c+1)*S+0 rides the same ring hop).
            x_in = jnp.where(jnp.logical_and(is_first, c_f == 0),
                             micro[i_f], f_mov)
            cp_f = chunk_of(local_v, c_f)
            if with_aux:
                y, aux_f = stage_fwd(cp_f, x_in, i_f, c_f)
                aux_acc = aux_acc + jnp.where(f_valid, aux_f, 0.0)
            else:
                y = stage_fwd(cp_f, x_in, i_f, c_f)
            # Ring-buffer the stage input per chunk (guarded: invalid
            # ticks must not clobber a live slot).
            slot = i_f % W
            prev = saved[c_f, slot]
            saved = lax.dynamic_update_slice(
                saved,
                jnp.where(f_valid, x_in, prev)[None, None],
                (c_f, slot, 0, 0, 0),
            )

            # Head loss + cotangent for the micro whose LAST chunk the
            # last stage just forwarded. Gated by a lax.cond whose
            # predicate depends only on t — uniform across every device,
            # so the collectives inside (the manual vocab-shard psums AND
            # the GSPMD auto-axis ones) are entered by all of them
            # together. A stage-index predicate here would deadlock; a
            # uniform one is the ordinary collectives-under-cond pattern
            # the fp16 skip-step already uses.
            head_on = head_on_t[t]
            i_h = head_micro_t[t]

            def do_head(ops):
                y_, dhead_, loss_ = ops
                # Broadcast the last stage's output over the axis (masked
                # psum); each stage then computes its 1/S vocab slice of
                # the loss and returns the psummed full dy.
                y_bc = lax.psum(
                    jnp.where(is_last, y_, jnp.zeros_like(y_)), axis_name
                )
                loss_i, dy_, dhead_i = head_vjp(y_bc, lab[i_h], i_h)
                loss_ = loss_ + jnp.where(is_last, loss_i, 0.0)
                dhead_ = jax.tree_util.tree_map(
                    jnp.add, dhead_, dhead_i
                )
                return loss_, dhead_, dy_.astype(x_local.dtype)

            def skip_head(ops):
                y_, dhead_, loss_ = ops
                return (loss_, dhead_,
                        jnp.zeros(y_.shape, x_local.dtype))

            loss_acc, dhead, dy_h = lax.cond(
                head_on, do_head, skip_head, (y, dhead, loss_acc)
            )

            # ---- backward half ------------------------------------------
            j_b = t - off - (S - 1) + stage
            b_valid = jnp.logical_and(j_b >= 0, j_b < K)
            j_bc = jnp.clip(j_b, 0, K - 1)
            c_b = bwd_chunk[j_bc]
            i_b = bwd_micro[j_bc]
            # A last-chunk item at the last stage consumes this tick's dy
            # (its head fired this very tick); every other item consumes
            # the cotangent ppermuted from the right neighbor. Cotangents
            # travel in the activation dtype — exactly what AD of the bf16
            # forward would propagate between stages.
            dy = jnp.where(jnp.logical_and(is_last, c_b == v - 1),
                           dy_h, b_mov).astype(x_local.dtype)
            x_saved = saved[c_b, i_b % W]
            cp_b = chunk_of(local_v, c_b)
            _, pullback = jax.vjp(
                lambda p, xx: stage_fwd(p, xx, i_b, c_b), cp_b, x_saved
            )
            if with_aux:
                # One pullback carries BOTH cotangents: the activation's
                # and the aux scalar's (pre-scaled by the caller). Invalid
                # ticks are masked below via bgate/fgate, so the seed
                # itself needs no gate.
                dp_j, dx_j = pullback((dy, aux_seed))
            else:
                dp_j, dx_j = pullback(dy)
            bgate = jnp.where(b_valid, 1.0, 0.0)
            dlayers = jax.tree_util.tree_map(
                lambda a, g: lax.dynamic_update_index_in_dim(
                    a,
                    lax.dynamic_index_in_dim(a, c_b, keepdims=False)
                    + bgate * g,
                    c_b, 0,
                ),
                dlayers, dp_j,
            )

            # Same uniformity rule for the embedding-gradient fold: run it
            # everywhere, zero the contribution off (stage 0, chunk 0).
            fgate = jnp.where(
                jnp.logical_and(jnp.logical_and(b_valid, is_first),
                                c_b == 0), 1.0, 0.0)
            demb = emb_accum(demb, dx_j.astype(jnp.float32) * fgate,
                             iid[i_b])

            f_mov_next = lax.ppermute(y, axis_name, fwd_perm)
            b_mov_next = lax.ppermute(
                (dx_j * bgate).astype(x_local.dtype), axis_name, bwd_perm)
            return (f_mov_next, b_mov_next, saved, dlayers, dhead, demb,
                    loss_acc, aux_acc), None

        (_, _, _, dlayers, dhead, demb, loss_acc, aux_acc), _ = lax.scan(
            tick, carry0, jnp.arange(T)
        )
        # [v, lc, ...] chunk grads -> this device's [L/S, ...] shard.
        dlayers = jax.tree_util.tree_map(
            lambda p: p.reshape((v * lc,) + p.shape[2:]), dlayers
        )
        loss = lax.psum(loss_acc, axis_name)
        # Partial (slice-local) head grads -> full-shape contributions,
        # then one psum assembles them (each stage's slice lands in its
        # own rows; every other row is zero).
        dhead = head_finalize(dhead)
        grad_axes = ((axis_name,) if manual_seq_axis is None
                     else (axis_name, manual_seq_axis))

        def _all_reduce(g):
            for ax in grad_axes:
                g = lax.psum(g, ax)
            return g

        # Under joint SP every gradient is additionally a per-token-chunk
        # partial: psum over the sequence axis too (the GPipe path gets
        # this from shard_map's transpose; here it is explicit).
        dhead = jax.tree_util.tree_map(_all_reduce, dhead)
        demb = jax.tree_util.tree_map(_all_reduce, demb)
        if manual_seq_axis is not None:
            dlayers = jax.tree_util.tree_map(
                lambda g: lax.psum(g, manual_seq_axis), dlayers
            )
        if with_aux:
            aux = lax.psum(aux_acc, axis_name)
            if manual_seq_axis is not None:
                aux = lax.psum(aux, manual_seq_axis)
            return loss, aux, dlayers, dhead, demb
        return loss, dlayers, dhead, demb

    layer_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    seq = manual_seq_axis
    x_spec = P() if seq is None else P(None, seq, None)
    ids_spec = P() if seq is None else P(None, seq)
    manual = {axis_name} if seq is None else {axis_name, seq}
    outs = ((P(), layer_specs, P(), P()) if not with_aux
            else (P(), P(), layer_specs, P(), P()))
    fn = shard_map(
        staged,
        mesh=mesh,
        # labels stay REPLICATED over the seq axis (the head's next-token
        # shift reads across chunk boundaries).
        in_specs=(layer_specs, x_spec, ids_spec, P()),
        out_specs=outs,
        axis_names=manual,
        check_vma=False,
    )
    out = fn(stacked_params, x, input_ids, labels)
    if v > 1:
        # Layer grads come back in the schedule's device-major order;
        # restore natural layer order for the optimizer/checkpoint layout.
        out = list(out)
        out[-3] = _unpermute(out[-3])
        out = tuple(out)
    return out
