"""Pipeline parallelism: a GPipe-style microbatch schedule over a mesh axis.

Pipeline parallelism is an aspirational bullet in the reference
(``README.md:10`` — never implemented; SURVEY.md §2). Here it is a working
SPMD schedule, built the TPU way: no per-stage processes or RPC — one
``shard_map`` over the ``stage`` mesh axis, with activations handed to the
next stage by ``lax.ppermute`` over ICI and the whole schedule expressed as
a ``lax.scan`` (so it jits once and differentiates end-to-end; the backward
pass is the reverse pipeline, derived by AD).

Schedule (classic GPipe):

- The layer stack ``[L, ...]`` is split into ``S`` contiguous stages
  (``L/S`` layers each — the stacked-parameter layout from ``nn.scan`` makes
  this a pure sharding of the leading axis; ``parallel/sharding.py`` pins
  that dim to ``stage``).
- The batch is split into ``M`` microbatches *by striding* (row ``j*M + m``
  → microbatch ``m``): under a ``data``-sharded batch this keeps every
  microbatch evenly spread across data shards, where a contiguous split
  would put each microbatch on a subset of them.
- At step ``t`` of ``M+S-1``, stage ``s`` processes microbatch ``t - s``
  (bubble fraction ``(S-1)/(M+S-1)``). Stage 0 feeds from the microbatch
  queue; stage ``S-1`` stores results; between steps every stage ppermutes
  its output to its right neighbor.

The shard_map is *partial-manual* (``axis_names={stage}``): every other
mesh axis stays under GSPMD, so the batch's ``data`` sharding and the
params' ``fsdp``/``tensor`` shardings ride through untouched and the
schedule composes with DP/ZeRO by construction.

``pipeline_forward`` is deliberately model-agnostic: it takes the stacked
per-layer params and a ``block_fn(layer_params, x[, rng]) -> x``. The
embedding / final-norm / loss stay outside (they are cheap and replicated
over ``stage``). With ``rng`` given, ``block_fn`` receives a key folded per
(global layer, microbatch) — distinct dropout masks everywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpu_trainer.parallel.mesh import STAGE_AXIS


def pipeline_forward(
    stacked_params: Any,
    x: jax.Array,
    block_fn: Callable,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
    rng: Optional[jax.Array] = None,
    with_aux: bool = False,
    manual_seq_axis: Optional[str] = None,
) -> Any:
    """Run ``x`` through the full layer stack with a GPipe schedule.

    Args:
      stacked_params: pytree whose leaves lead with the layer axis ``[L, ...]``
        (the ``nn.scan`` layout); logically global, sharded over ``axis_name``.
      x: ``[batch, seq, hidden]`` activations; batch must divide into
        ``num_microbatches``.
      block_fn: applies ONE layer: ``block_fn(params_of_layer, x) -> x``, or
        ``block_fn(params_of_layer, x, rng) -> x`` when ``rng`` is given.
        With ``with_aux``, returns ``(x, aux_scalar)`` instead (the MoE
        load-balance term).
      mesh: mesh containing ``axis_name`` (other axes stay GSPMD-auto).
      num_microbatches: M; more microbatches -> smaller pipeline bubble.
      rng: optional dropout key; folded per (global layer, microbatch).
      with_aux: accumulate per-layer scalar aux across real schedule steps
        (bubble steps excluded), summed over layers and averaged over
        microbatches — the per-micro estimator matching grad-accum
        semantics. Returns ``(activations, aux)``.
      manual_seq_axis: when sequence parallelism composes with the
        pipeline, the shard_map goes jointly manual over
        ``{stage, manual_seq_axis}`` and activations enter seq-sharded:
        the attention's ring collectives then bind to the SAME manual
        region instead of opening a nested one (the construct Shardy
        rejects). The model routes its attention through
        ``ring.ring_attention_manual`` under
        ``ring.sequence_parallel_manual``.

    Returns activations after all L layers, ``[batch, seq, hidden]``
    (plus the aux scalar when ``with_aux``).
    """
    S = mesh.shape[axis_name]
    b, s, h = x.shape
    M = num_microbatches
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by M={M}")
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(
            f"num_layers {n_layers} not divisible by {S} pipeline stages"
        )
    mb = b // M
    layers_per_stage = n_layers // S

    def staged(local_params, x_local, *rng_arg):
        # local_params: leaves [L/S, ...] (this stage's layers).
        # x_local: full batch [b, s, h], replicated over `stage` (its data
        # sharding, if any, is handled by the surrounding auto axes).
        stage = lax.axis_index(axis_name)
        # Local shapes: under joint SP the sequence dim entered sharded.
        s_l = x_local.shape[1]
        # Strided microbatching: row j*M + m -> microbatch m (see module
        # docstring for why not contiguous).
        micro = x_local.reshape(mb, M, s_l, h).transpose(1, 0, 2, 3)

        def run_stage(xm, t):
            micro_idx = t - stage  # valid in [0, M) when the step is real

            def one_layer(carry, scanned):
                xc, aux = carry
                li, p = scanned
                args = (p, xc)
                if rng_arg:
                    g_layer = stage * layers_per_stage + li
                    key = jax.random.fold_in(
                        rng_arg[0], g_layer * M + jnp.clip(micro_idx, 0, M - 1)
                    )
                    if manual_seq_axis is not None:
                        # Each sequence shard sees only its local slice, and
                        # hash_dropout keys masks by LOCAL positions — fold
                        # the shard index so chunks don't repeat one mask.
                        key = jax.random.fold_in(
                            key, lax.axis_index(manual_seq_axis)
                        )
                    args = args + (key,)
                out = block_fn(*args)
                if with_aux:
                    out, layer_aux = out
                    aux = aux + layer_aux
                return (out, aux), None

            (out, aux), _ = lax.scan(
                one_layer, (xm, jnp.zeros((), jnp.float32)),
                (jnp.arange(layers_per_stage), local_params),
            )
            # Bubble steps compute garbage that must not leak into the aux
            # sum; micro_idx validity is decided here, next to where it is
            # defined.
            real = jnp.logical_and(micro_idx >= 0, micro_idx < M)
            return out, jnp.where(real, aux, 0.0)

        perm = [(i, (i + 1) % S) for i in range(S)]
        outputs0 = jnp.zeros((M, mb, s_l, h), x_local.dtype)
        # `moving` is each stage's current inbound activation slot.
        moving0 = jnp.zeros((mb, s_l, h), x_local.dtype)

        def step(carry, t):
            moving, outputs, aux_acc = carry
            # Stage 0 ingests microbatch t (when in range); others take the
            # activation that arrived from the left neighbor.
            feed_idx = jnp.clip(t, 0, M - 1)
            x_in = jnp.where(stage == 0, micro[feed_idx], moving)
            y, aux_y = run_stage(x_in, t)  # aux_y already bubble-masked
            aux_acc = aux_acc + aux_y
            # Last stage stores microbatch t - (S-1) when it's real.
            out_idx = t - (S - 1)
            store = jnp.logical_and(stage == S - 1, out_idx >= 0)
            outputs = lax.cond(
                store,
                lambda o: lax.dynamic_update_slice(
                    o, y[None], (jnp.maximum(out_idx, 0), 0, 0, 0)
                ),
                lambda o: o,
                outputs,
            )
            moving = lax.ppermute(y, axis_name, perm)
            return (moving, outputs, aux_acc), None

        (_, outputs, aux_acc), _ = lax.scan(
            step, (moving0, outputs0, jnp.zeros((), jnp.float32)),
            jnp.arange(M + S - 1),
        )
        # Only the last stage holds real outputs; broadcast them to every
        # stage so the result is replicated over the axis (psum of a
        # one-hot-masked buffer).
        mask = (stage == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis_name)
        # Undo the strided microbatch grouping.
        outputs = outputs.transpose(1, 0, 2, 3).reshape(b, s_l, h)
        if with_aux:
            # Sum over stages = sum over all layers; mean over microbatches
            # (and over sequence shards under joint SP — each shard's aux
            # estimates from its local tokens, the same per-shard estimator
            # grad accumulation uses per micro).
            aux = lax.psum(aux_acc, axis_name) / M
            if manual_seq_axis is not None:
                sq = mesh.shape[manual_seq_axis]
                aux = lax.psum(aux, manual_seq_axis) / sq
            return outputs, aux
        return outputs

    layer_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    rng_args = () if rng is None else (rng,)
    rng_specs = () if rng is None else (P(),)
    x_spec = (P(None, manual_seq_axis, None) if manual_seq_axis is not None
              else P())
    manual = ({axis_name} if manual_seq_axis is None
              else {axis_name, manual_seq_axis})
    fn = shard_map(
        staged,
        mesh=mesh,
        in_specs=(layer_specs, x_spec) + rng_specs,
        out_specs=(x_spec, P()) if with_aux else x_spec,
        axis_names=manual,
        check_vma=False,
    )
    return fn(stacked_params, x, *rng_args)


def pipeline_1f1b(
    stacked_params: Any,
    x: jax.Array,
    input_ids: jax.Array,
    labels: jax.Array,
    stage_fwd: Callable,
    head_vjp: Callable,
    head_grad_zeros: Any,
    emb_accum: Callable,
    emb_grad_zeros: Any,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
) -> Any:
    """Interleaved forward/backward (1F1B-style) pipeline with MANUAL
    backward scheduling — the loss and every gradient come out of ONE scan.

    Why not AD of the GPipe scan: differentiating ``pipeline_forward``
    keeps every scan step's carry (all ``M`` microbatch activations) alive
    until the bubble point, so pipeline activation memory scales with M —
    the thing 1F1B exists to fix. Here each microbatch's backward starts as
    soon as its forward clears the last stage (which computes that micro's
    loss VJP in the SAME tick), so a stage retains at most
    ``min(M, 2(S-1)+1)`` saved stage-inputs — independent of M. Stage
    blocks are recomputed inside ``jax.vjp`` from the saved inputs
    (stage-granular rematerialization), the same total compute as GPipe
    with per-block remat.

    Schedule (per tick ``t`` of ``M + 2(S-1)``; every stage runs both
    masked halves — SPMD):

    - forward half: stage ``s`` runs micro ``i = t - s`` when valid, and
      every stage evaluates the head loss + cotangent for that micro with
      only the LAST stage's result kept (masked, NOT ``lax.cond``: the
      head contains GSPMD collectives over the auto axes, and a
      stage-predicated branch deadlocks them — see the in-body comment.
      The head therefore runs S x (M + 2S - 2) times; acceptable while
      stage counts are small relative to the model/head FLOP ratio).
    - backward half: stage ``s`` runs the backward of micro
      ``j = t - 2(S-1) + s`` when valid (at the last stage ``j == i``: the
      1F1B "B right after F"); cotangents travel left by ppermute; layer
      grads accumulate locally; stage 0 folds ``dx`` into the embedding
      gradient via ``emb_accum`` (no [M, ...] cotangent buffer).

    Args:
      stacked_params: ``[L, ...]`` leaves, sharded over ``axis_name``.
      x: embedded activations ``[batch, seq, hidden]``.
      labels: ``[batch, seq]`` int labels (microbatched alongside x).
      stage_fwd: ``(local_params, x_mb, micro_idx) -> y_mb`` — this stage's
        layer block; must fold its dropout rngs from ``micro_idx`` exactly
        like the GPipe path so the two schedules are grad-equivalent.
      head_vjp: ``(y_mb, labels_mb, micro_idx) -> (loss, dy, dhead)`` —
        per-micro loss (already scaled by 1/M and any loss scale), its
        cotangent wrt y, and the head-parameter grads.
      head_grad_zeros / emb_grad_zeros: zero pytrees for the accumulators.
      emb_accum: ``(acc, dx_mb, ids_mb) -> acc`` — folds a micro's input
        cotangent into the embedding gradient at that micro's token ids
        (runs on stage 0 only).

    Returns ``(loss_sum, dlayers_stacked, dhead, demb)`` — loss summed over
    microbatches (caller already folded 1/M into head_vjp).
    """
    S = mesh.shape[axis_name]
    b, s, h = x.shape
    M = num_microbatches
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by M={M}")
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(
            f"num_layers {n_layers} not divisible by {S} pipeline stages"
        )
    mb = b // M
    W = min(M, 2 * (S - 1) + 1)  # max in-flight stage inputs (M-independent)

    def staged(local_params, x_local, ids_local, labels_local):
        stage = lax.axis_index(axis_name)
        is_last = stage == S - 1
        is_first = stage == 0
        s_l = x_local.shape[1]
        # Strided microbatching, as pipeline_forward.
        micro = x_local.reshape(mb, M, s_l, h).transpose(1, 0, 2, 3)
        iid = ids_local.reshape(mb, M, s_l).transpose(1, 0, 2)
        lab = labels_local.reshape(mb, M, s_l).transpose(1, 0, 2)

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        dlayers0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), local_params
        )
        carry0 = (
            jnp.zeros((mb, s_l, h), x_local.dtype),   # inbound fwd act
            jnp.zeros((mb, s_l, h), x_local.dtype),   # inbound cotangent
            jnp.zeros((W, mb, s_l, h), x_local.dtype),  # saved stage inputs
            dlayers0,
            head_grad_zeros,
            emb_grad_zeros,
            jnp.zeros((), jnp.float32),               # loss acc
        )

        def tick(carry, t):
            f_mov, b_mov, saved, dlayers, dhead, demb, loss_acc = carry

            # ---- forward half -------------------------------------------
            i_f = t - stage
            f_valid = jnp.logical_and(i_f >= 0, i_f < M)
            i_fc = jnp.clip(i_f, 0, M - 1)
            x_in = jnp.where(is_first, micro[i_fc], f_mov)
            y = stage_fwd(local_params, x_in, i_fc)
            # Ring-buffer the stage input (guarded: invalid ticks must not
            # clobber a live slot).
            slot = i_fc % W
            prev = lax.dynamic_index_in_dim(saved, slot, keepdims=False)
            saved = lax.dynamic_update_index_in_dim(
                saved, jnp.where(f_valid, x_in, prev), slot, 0
            )

            # Head loss + cotangent for the micro this stage just
            # forwarded; only the LAST stage's result is real. Computed
            # unconditionally with a mask: the head math contains
            # GSPMD-inserted collectives over the auto (data) axes, and a
            # lax.cond whose predicate is the stage index would make only
            # some devices enter them — a rendezvous deadlock (observed on
            # the CPU mesh). Uniform SPMD control flow or nothing.
            loss_i, dy_i, dhead_i = head_vjp(y, lab[i_fc], i_fc)
            gate = jnp.where(jnp.logical_and(f_valid, is_last), 1.0, 0.0)
            loss_acc = loss_acc + gate * loss_i
            dhead = jax.tree_util.tree_map(
                lambda a, g: a + gate * g, dhead, dhead_i
            )

            # ---- backward half ------------------------------------------
            j_b = t - 2 * (S - 1) + stage
            b_valid = jnp.logical_and(j_b >= 0, j_b < M)
            j_bc = jnp.clip(j_b, 0, M - 1)
            # At the last stage j == i: consume this tick's dy directly.
            # Cotangents travel in the activation dtype — exactly what AD
            # of the bf16 forward would propagate between stages.
            dy = jnp.where(is_last, dy_i, b_mov).astype(x_local.dtype)
            x_saved = lax.dynamic_index_in_dim(saved, j_bc % W,
                                               keepdims=False)
            _, pullback = jax.vjp(
                lambda p, xx: stage_fwd(p, xx, j_bc), local_params, x_saved
            )
            dp_j, dx_j = pullback(dy)
            bgate = jnp.where(b_valid, 1.0, 0.0)
            dlayers = jax.tree_util.tree_map(
                lambda a, g: a + bgate * g, dlayers, dp_j
            )

            # Same uniformity rule for the embedding-gradient fold: run it
            # everywhere, zero the contribution off stage 0.
            fgate = jnp.where(jnp.logical_and(b_valid, is_first), 1.0, 0.0)
            demb = emb_accum(demb, dx_j.astype(jnp.float32) * fgate,
                             iid[j_bc])

            f_mov_next = lax.ppermute(y, axis_name, fwd_perm)
            b_mov_next = lax.ppermute(
                (dx_j * bgate).astype(x_local.dtype), axis_name, bwd_perm)
            return (f_mov_next, b_mov_next, saved, dlayers, dhead, demb,
                    loss_acc), None

        (_, _, _, dlayers, dhead, demb, loss_acc), _ = lax.scan(
            tick, carry0, jnp.arange(M + 2 * (S - 1))
        )
        loss = lax.psum(loss_acc, axis_name)
        dhead = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), dhead
        )
        demb = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), demb
        )
        return loss, dlayers, dhead, demb

    layer_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    fn = shard_map(
        staged,
        mesh=mesh,
        in_specs=(layer_specs, P(), P(), P()),
        out_specs=(P(), layer_specs, P(), P()),
        axis_names={axis_name},
        check_vma=False,
    )
    return fn(stacked_params, x, input_ids, labels)
