"""Device mesh and multi-host communication backend.

The TPU-native replacement for the reference's NCCL/`torch.distributed` layer
(SURVEY.md C12; reference ``ddp_trainer.py:93-113``, ``fsdp_trainer.py:125-138``):

- rendezvous: ``jax.distributed.initialize()`` (↔ ``init_process_group("nccl")``)
- rank/world discovery: ``jax.process_index()/process_count()`` (↔ RANK/WORLD_SIZE env)
- the collective fabric: a ``jax.sharding.Mesh`` over ICI (intra-slice) and DCN
  (inter-slice); gradients/params move via XLA-inserted collectives, not
  explicit NCCL calls
- barrier: ``multihost_utils.sync_global_devices`` (↔ ``dist.barrier()``)
- broadcast: ``multihost_utils.broadcast_one_to_all``
  (↔ ``dist.broadcast_object_list``)

Mesh axes:

- ``data``  — pure data parallelism (DDP replica axis; grads all-reduced).
- ``fsdp``  — parameter/optimizer sharding axis (ZeRO); also carries data
  (batch is sharded over ``data × fsdp`` jointly, exactly like torch FSDP
  where every rank is both a data rank and a shard rank).
- ``tensor`` — tensor-parallel axis (op sharding inside a layer).

``data > 1`` with ``fsdp > 1`` gives HYBRID_SHARD — documented-but-broken in
the reference (docstring-only, ``fsdp_trainer.py:258-261``; SURVEY.md §2) and
a real mode here.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
SEQUENCE_AXIS = "sequence"
TENSOR_AXIS = "tensor"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"
MESH_AXES = (
    DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS, TENSOR_AXIS, EXPERT_AXIS, STAGE_AXIS,
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """How to carve the device fleet into parallelism axes.

    ``-1`` means "all remaining devices" (at most one axis may be -1).
    """

    data: int = -1
    fsdp: int = 1
    sequence: int = 1
    tensor: int = 1
    expert: int = 1
    stage: int = 1

    def resolve(self, n_devices: int) -> tuple:
        sizes = [self.data, self.fsdp, self.sequence, self.tensor,
                 self.expert, self.stage]
        n_auto = sum(1 for s in sizes if s == -1)
        if n_auto > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = int(np.prod([s for s in sizes if s != -1]))
        if n_auto == 1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes = [n_devices // fixed if s == -1 else s for s in sizes]
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} wants {fixed} devices but {n_devices} are available"
            )
        return tuple(sizes)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: Optional[bool] = None,
) -> None:
    """Multi-host rendezvous (↔ reference ``dist.init_process_group``).

    Three modes:

    - explicit: pass coordinator/num_processes/process_id (or set the
      ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID`` env vars);
    - ``auto=True`` (the CLI's ``--multihost`` flag): call the no-arg
      ``jax.distributed.initialize()``, which autodetects the topology on
      Cloud TPU / SLURM / GKE;
    - default: autodetect is attempted only when a Cloud TPU multi-host
      environment is visible (so single-host runs stay zero-config no-ops).
    """
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("PROCESS_ID")
    if coordinator_address or num_processes:
        if (num_processes or 0) > 1:
            # CPU cross-process collectives default to "none" on this jax,
            # which makes any multi-process computation fail with
            # "Multiprocess computations aren't implemented on the CPU
            # backend". Selecting gloo before backend init turns the
            # supervisor's N-process CPU rendezvous (and the elastic chaos
            # tests) into a real collective fabric. Must happen before the
            # first backend instantiation; harmless on TPU (ignored).
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass  # older/newer jax without the knob: leave the default
        kwargs = {}
        timeout_s = _int_env("COORDINATOR_TIMEOUT_S")
        if timeout_s is not None:
            # Bounded rendezvous: a peer that died before reaching
            # initialize() must surface as an error the run supervisor can
            # see, not an indefinite hang of the surviving processes.
            kwargs["initialization_timeout"] = timeout_s
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
        return
    if auto is None:
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        auto = len([h for h in hostnames.split(",") if h]) > 1
    if auto:
        jax.distributed.initialize()


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def make_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the device mesh.

    ``mesh_utils.create_device_mesh`` lays ranks out so that the innermost
    axes map onto physically adjacent devices — collectives on ``tensor`` and
    ``fsdp`` ride ICI; ``data`` (outermost) crosses DCN on multi-slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = config.resolve(len(devices))
    if len(devices) == 1:
        device_array = np.array(devices).reshape(shape)
    else:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(device_array, MESH_AXES)


def batch_spec() -> P:
    """PartitionSpec for a ``[accum, batch, seq]`` micro-batched step input:
    batch is sharded over data × fsdp jointly (every device holds a distinct
    slice of the global batch — the FSDP world is also the data world, as in
    torch FSDP); the sequence dim shards over the ring-attention axis."""
    return P(None, (DATA_AXIS, FSDP_AXIS), SEQUENCE_AXIS)


def batch_spec_2d() -> P:
    """PartitionSpec for a plain ``[batch, seq]`` batch (eval/inference)."""
    return P((DATA_AXIS, FSDP_AXIS), SEQUENCE_AXIS)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def dp_size(mesh: Mesh) -> int:
    """Number of distinct data shards (data × fsdp axes)."""
    return mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]


def host_feed_info(sharding, global_shape, row_dim: int,
                   process_of_device=None, process_index=None):
    """Which batch-row slice this host must load: ``(feed_rank, feed_world)``.

    Derived from the sharding's device->index map, so it is correct for any
    mesh topology: hosts whose devices address the same global row range
    form one feed group and must load IDENTICAL rows (e.g. a sequence or
    tensor axis spanning hosts — the long-context pod layout); hosts with
    disjoint ranges get consecutive ranks ordered by row start. For the
    common dp%hosts==0 layout this degenerates to
    ``(process_index, process_count)``.

    ``process_of_device`` / ``process_index`` are injectable for tests
    (simulating a multi-host device->process assignment on one real
    process).

    Raises if the distinct host row-coverages do not form an ordered
    equal-size partition of the rows — then no consistent loader sharding
    exists for this mesh layout.
    """
    pod = process_of_device or (lambda d: d.process_index)
    pidx = jax.process_index() if process_index is None else process_index
    rows_total = global_shape[row_dim]
    cover = {}
    for dev, idx in sharding.devices_indices_map(tuple(global_shape)).items():
        sl = idx[row_dim]
        start = 0 if sl.start is None else sl.start
        stop = rows_total if sl.stop is None else sl.stop
        cover.setdefault(pod(dev), set()).add((int(start), int(stop)))

    def span(ranges):
        # Each host's covered rows must be one contiguous run.
        rs = sorted(ranges)
        lo, hi = rs[0][0], rs[0][1]
        for a, b in rs[1:]:
            if a > hi:
                raise ValueError(
                    f"host row coverage {rs} is not contiguous — this mesh "
                    f"device layout interleaves data shards within a host; "
                    f"no consistent data feeding order exists"
                )
            hi = max(hi, b)
        return (lo, hi)

    spans = {p: span(rngs) for p, rngs in cover.items()}
    groups = sorted(set(spans.values()))
    size = groups[0][1] - groups[0][0]
    for g, (lo, hi) in enumerate(groups):
        if lo != g * size or hi - lo != size:
            raise ValueError(
                f"host row spans {groups} do not partition {rows_total} rows "
                f"into equal ordered slices; no consistent data feeding "
                f"order exists for this mesh layout"
            )
    if pidx not in spans:
        raise ValueError(f"process {pidx} holds no addressable batch rows")
    return groups.index(spans[pidx]), len(groups)


def attention_shard_spec(mesh: Mesh, batch: int, heads: int,
                         kv_heads: Optional[int] = None):
    """PartitionSpec components for ``[b, s, h, d]`` attention operands.

    Attention is independent across batch and heads, so those dims shard
    losslessly: batch over ``data x fsdp`` (every device is both a data and
    a shard rank, as in torch FSDP) and heads over ``tensor``. An axis whose
    size doesn't divide the dim (tiny test batches) falls back to
    replicated. Shared by the flash-kernel shard_map wrapper
    (``ops/attention.py``) and ring attention (``ops/ring.py``).

    Under GQA pass ``kv_heads``: heads shard over ``tensor`` only when the
    K/V heads divide too — a manual region whose q-head shard doesn't own
    its group's K/V head would read the wrong one.

    Returns ``(b_spec, h_spec)`` — each an axis (tuple) or None.
    """
    dp = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    b_spec = (DATA_AXIS, FSDP_AXIS) if (dp > 1 and batch % dp == 0) else None
    tp = mesh.shape[TENSOR_AXIS]
    kv_heads = heads if kv_heads is None else kv_heads
    h_spec = (
        TENSOR_AXIS
        if (tp > 1 and heads % tp == 0 and kv_heads % tp == 0)
        else None
    )
    return b_spec, h_spec


def attention_shard_coord(mesh: Mesh, b_spec, h_spec):
    """Linearized coordinate of this shard along the axes that actually
    shard the attention inputs (0 when none). Must be called inside the
    shard_map body. Folding this into a dropout PRNG key decorrelates masks
    across shards — and *only* across sharded axes: folding a replicated
    axis's coordinate would make devices along it compute different outputs
    for identical data, breaking the replicated out_spec.
    """
    coord = 0
    if b_spec is not None:
        for ax in (DATA_AXIS, FSDP_AXIS):
            coord = coord * mesh.shape[ax] + jax.lax.axis_index(ax)
    if h_spec is not None:
        coord = coord * mesh.shape[TENSOR_AXIS] + jax.lax.axis_index(
            TENSOR_AXIS
        )
    return coord


def barrier(name: str = "barrier") -> None:
    """Cross-host barrier (↔ ``dist.barrier()``, reference fsdp_trainer.py:465)."""
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(name)


def global_any(flag: bool) -> bool:
    """True on every host iff ``flag`` is True on any host — the coordination
    primitive for preemption (one host's SIGTERM must make *all* hosts enter
    the collective checkpoint save together, or the save deadlocks)."""
    if jax.process_count() <= 1:
        return flag
    votes = multihost_utils.process_allgather(np.asarray([bool(flag)]))
    return bool(np.any(votes))


def shutdown_distributed() -> None:
    """Best-effort clean exit from the rendezvous — the proactive-drain path
    (``utils/preemption.py`` notice → checkpoint → deregister → exit) calls
    this so the coordinator sees an orderly departure instead of a dropped
    connection. Failures are swallowed: the process is exiting either way,
    and a drain must never turn into a crash over coordinator teardown."""
    try:
        if jax.process_count() > 1:
            jax.distributed.shutdown()
    except Exception:
        pass


def broadcast_from_host0(pytree):
    """Host-0 → all hosts value broadcast
    (↔ ``dist.broadcast_object_list``, reference fsdp_trainer.py:469-478)."""
    if jax.process_count() > 1:
        return multihost_utils.broadcast_one_to_all(pytree)
    return pytree
