"""Trace-time mesh context.

The model is parallelism-blind (the reference's load-bearing property,
SURVEY.md §1): it never receives a mesh. Most ops need none — GSPMD
partitions plain jnp from the in/out shardings alone. The exception is the
Pallas flash kernel: a ``pallas_call`` is opaque to the SPMD partitioner, so
without help XLA replicates it (all-gathering q/k/v to every device — the
"replication cliff" on a DP/FSDP/TP mesh).

The trainer publishes its mesh here while tracing the step; the attention
dispatch (``ops/attention.py``) reads it and wraps the kernel in a
``shard_map`` over the batch (``data`` x ``fsdp``) and heads (``tensor``)
axes — attention is independent along both, so the kernel runs unchanged on
each shard. The same pattern as ``ops/ring.py``'s sequence-parallel context,
for the non-sequence axes.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional

from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshContext:
    mesh: Optional[Mesh]


_ACTIVE: Optional[MeshContext] = None


@contextlib.contextmanager
def mesh_scope(mesh: Optional[Mesh]):
    """While active (static, trace-time), mesh-aware ops may shard_map
    themselves over ``mesh`` instead of appearing opaque to GSPMD.
    ``mesh_scope(None)`` masks an outer scope if a region ever needs to
    hide the mesh from nested ops (no current caller does: the pipeline
    stage body is *partial*-manual over {stage, sequence} only, and ops
    that must behave differently inside it key off
    ``ring.current_manual_context()`` instead)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = MeshContext(mesh)
    try:
        yield
    finally:
        _ACTIVE = prev


def current_mesh() -> Optional[Mesh]:
    return _ACTIVE.mesh if _ACTIVE is not None else None
