"""Static per-step collective-traffic model for a Trainer's mesh + sharding.

Answers "why does a step cost what it costs" *before* the profiler runs:
from the mesh shape, the sharding strategy, and the abstract parameter
tree alone, predict the bytes each device moves per optimizer step on
every mesh axis — DP grad all-reduce, FSDP param all-gather / grad
reduce-scatter, TP activation all-reduces, ring-attention K/V rotation,
MoE all-to-all dispatch/combine, and pipeline stage boundary transfers —
then put that next to the analytic FLOPs as a comms-vs-compute roofline.
The MegaScale-style production question ("is this config interconnect-
bound?") becomes a one-time ``kind:"comms_model"`` JSONL record instead
of a profile-reading session.

The model is *analytic*: every formula assumes bidirectional-ring
collectives (the TPU ICI native algorithm) and no compute/comms overlap,
so the time estimates are upper bounds for classification, not step-time
predictions. ``crosscheck`` counts the collective ops GSPMD actually
inserted in the compiled HLO and flags axes the model charges traffic to
that show no matching collective (soft notes — the partitioner may
legally substitute op forms, e.g. an all-reduce for a reduce-scatter +
all-gather pair).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_trainer.parallel import mesh as mesh_lib
from tpu_trainer.parallel import sharding as shard_lib
from tpu_trainer.utils.logging import device_peak_flops, flops_per_token

# Gradients accumulate and reduce in float32 regardless of compute dtype.
GRAD_BYTES = 4

# Assumed per-device interconnect bandwidth (bytes/s) by device_kind
# substring, for the roofline estimate only. Aggregate ICI figures good to
# a factor of ~2 — enough to classify a config as comms- or compute-bound,
# not to predict step time. Matched longest-substring-first.
_ICI_BYTES_PER_SEC = {
    "v6": 1.8e11,
    "v5p": 1.2e11,
    "v5lite": 4.5e10,
    "v5e": 4.5e10,
    "v4": 1.2e11,
    "v3": 7.0e10,
    "v2": 5.0e10,
}
_DEFAULT_ICI = 4.5e10

_HLO_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)
# Which compiled collectives each modeled axis may legitimately appear as.
_AXIS_EXPECTED_OPS = {
    "data": ("all-reduce", "reduce-scatter"),
    "fsdp": ("all-gather", "reduce-scatter", "all-reduce"),
    "tensor": ("all-reduce", "reduce-scatter", "all-gather"),
    "sequence": ("collective-permute", "all-to-all"),
    "expert": ("all-to-all", "all-gather"),
    "stage": ("collective-permute",),
}


# --- ring-collective per-device byte costs ---------------------------------

def ring_all_reduce_bytes(payload: float, n: int) -> float:
    """Ring all-reduce of ``payload`` bytes over ``n`` devices: a
    reduce-scatter then an all-gather, each moving (n-1)/n of the payload
    through every device."""
    return 2.0 * (n - 1) / n * payload if n > 1 else 0.0


def ring_all_gather_bytes(payload: float, n: int) -> float:
    """All-gather whose *result* is ``payload`` bytes: each device
    receives the (n-1)/n of it that it doesn't already hold."""
    return (n - 1) / n * payload if n > 1 else 0.0


def ring_reduce_scatter_bytes(payload: float, n: int) -> float:
    """Reduce-scatter of a ``payload``-byte addend per device: (n-1)/n of
    it leaves each device."""
    return (n - 1) / n * payload if n > 1 else 0.0


def all_to_all_bytes(payload: float, n: int) -> float:
    """All-to-all of a ``payload``-byte per-device buffer: (n-1)/n of it is
    destined for other devices."""
    return (n - 1) / n * payload if n > 1 else 0.0


def ring_sendrecv_bytes(shard_bytes: float, n: int) -> float:
    """Full ring rotation (ring attention): every device forwards its
    ``shard_bytes`` neighbour block ``n-1`` times."""
    return (n - 1) * shard_bytes if n > 1 else 0.0


# --- the model -------------------------------------------------------------

def _spec_axes(spec) -> tuple:
    axes: List[str] = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(entry)
        else:
            axes.append(entry)
    return tuple(axes)


def _shard_factor(spec, axis_sizes, exclude=()) -> int:
    f = 1
    for ax in _spec_axes(spec):
        if ax not in exclude:
            f *= axis_sizes[ax]
    return f


def _ici_bytes_per_sec(device_kind: str) -> float:
    kind = (device_kind or "").lower()
    for key in sorted(_ICI_BYTES_PER_SEC, key=len, reverse=True):
        if key in kind:
            return _ICI_BYTES_PER_SEC[key]
    return _DEFAULT_ICI


def build_core(
    param_shapes,
    axis_sizes,
    strategy: str,
    *,
    model_config,
    batch_size: int,
    max_seq_len: int,
    grad_accum: int,
    device_kind: str = "",
    peak_flops: Optional[float] = None,
) -> dict:
    """Trainer-independent core of the comms model.

    Everything the model needs is shape arithmetic over an abstract param
    tree plus the run's dimensions — no live ``Trainer`` or ``Mesh``:

    - ``param_shapes``: abstract parameter tree (``jax.eval_shape`` output);
    - ``axis_sizes``: ``{axis_name: size}`` for the six mesh axes (missing
      axes default to 1) — ``mesh.shape`` or a planner candidate;
    - ``strategy``: sharding strategy (aliases accepted);
    - ``batch_size``: per-data-shard rows per micro-batch;
    - ``device_kind`` / ``peak_flops``: roofline hardware constants.
      ``peak_flops=None`` keeps the live-trainer behavior (local device
      lookup); the offline planner passes an explicit figure so plans for a
      different device kind don't inherit this process's hardware.

    This is what the mesh auto-planner (``parallel/planner.py``) scores
    candidate meshes with; :func:`build` is the thin trainer wrapper and its
    output is byte-for-byte what it always was.
    """
    strategy = shard_lib.canonical_strategy(strategy)
    mc = model_config
    d = axis_sizes.get(mesh_lib.DATA_AXIS, 1)
    f = axis_sizes.get(mesh_lib.FSDP_AXIS, 1)
    sp = axis_sizes.get(mesh_lib.SEQUENCE_AXIS, 1)
    tp = axis_sizes.get(mesh_lib.TENSOR_AXIS, 1)
    ep = axis_sizes.get(mesh_lib.EXPERT_AXIS, 1)
    st = axis_sizes.get(mesh_lib.STAGE_AXIS, 1)
    sizes = {ax: axis_sizes.get(ax, 1) for ax in mesh_lib.MESH_AXES}
    n_devices = d * f * sp * tp * ep * st
    accum = grad_accum
    rows = batch_size                         # per-data-shard rows per micro
    seq_local = max_seq_len // sp
    act_bytes = jnp.dtype(mc.compute_dtype).itemsize
    hidden = mc.hidden_size
    layers = mc.num_layers

    p_specs = shard_lib.params_specs_from_sizes(param_shapes, sizes, strategy)
    g_specs = shard_lib.grads_specs_from_sizes(param_shapes, sizes, strategy)
    params_total = int(sum(
        int(np.prod(x.shape)) if x.shape else 1
        for x in jax.tree_util.tree_leaves(param_shapes)))

    # Param-tree traffic: DP grad all-reduce + FSDP gathers/scatters.
    acc = {"data": 0.0, "fsdp_gather": 0.0, "fsdp_scatter": 0.0}
    zero2_regather = strategy == "zero2"

    def per_leaf(leaf, pspec, gspec):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        # data axis: all-reduce of the per-device f32 grad shard (for
        # ZeRO meshes this runs on the post-reduce-scatter shard).
        gshard = size * GRAD_BYTES / _shard_factor(gspec, sizes)
        acc["data"] += ring_all_reduce_bytes(gshard, d)
        if f > 1 and mesh_lib.FSDP_AXIS in _spec_axes(gspec):
            # fsdp grad reduce-scatter, on the pre-scatter f32 payload.
            pre = size * GRAD_BYTES / _shard_factor(
                gspec, sizes, exclude=(mesh_lib.FSDP_AXIS,))
            acc["fsdp_scatter"] += ring_reduce_scatter_bytes(pre, f)
            if zero2_regather and mesh_lib.FSDP_AXIS not in _spec_axes(pspec):
                # zero2: params stay replicated, so the fsdp-sharded
                # update is all-gathered back once per step (f32).
                acc["fsdp_gather"] += ring_all_gather_bytes(pre, f)
        if f > 1 and mesh_lib.FSDP_AXIS in _spec_axes(pspec):
            # zero3 param all-gather in compute dtype (>=2-D leaves are
            # cast; scalars/vectors stay f32), once for the forward and
            # once for the backward re-gather (no full-tree liveness).
            itemsize = act_bytes if len(leaf.shape) >= 2 else 4
            pre = size * itemsize / _shard_factor(
                pspec, sizes, exclude=(mesh_lib.FSDP_AXIS,))
            acc["fsdp_gather"] += 2.0 * ring_all_gather_bytes(pre, f)

    jax.tree_util.tree_map(per_leaf, param_shapes, p_specs, g_specs)

    # tensor axis: 2 forward + 2 backward activation all-reduces per layer
    # per micro-batch (row-parallel o_proj and down_proj outputs, and their
    # grads w.r.t. the column-parallel inputs). The vocab-sharded fused
    # head reduces scalars only — excluded.
    act_payload = rows * seq_local * hidden * act_bytes
    tensor_bytes = (
        accum * layers * 4 * ring_all_reduce_bytes(act_payload, tp))

    # sequence axis: ring attention rotates each device's K/V shard around
    # the ring once per layer forward and twice backward (K/V again plus
    # the dK/dV accumulators riding the reverse ring).
    kv_shard = (2 * rows * seq_local * mc.kv_heads * mc.head_dim * act_bytes)
    seq_bytes = accum * layers * 3 * ring_sendrecv_bytes(kv_shard, sp)

    # expert axis: dispatch + combine all-to-alls, forward and backward
    # (4 total per layer per micro).  Capacity routing moves the padded
    # E*C slot buffer (top_k * capacity_factor rows per token); dropless
    # routing moves exactly the k*T routed rows — no padding factor.
    expert_bytes = 0.0
    if mc.num_experts > 0 and ep > 1:
        routed_scale = (mc.moe_top_k if mc.moe_impl == "dropless"
                        else mc.moe_top_k * mc.expert_capacity_factor)
        tok_payload = (rows * seq_local * routed_scale
                       * hidden * act_bytes)
        expert_bytes = (
            accum * layers * 4 * all_to_all_bytes(tok_payload, ep))

    # stage axis: every microbatch's activations cross each stage boundary
    # forward and backward; per device that is (st-1)/st of the per-micro
    # activation rows (the microbatch split cancels out of the total).
    stage_bytes = 0.0
    if st > 1:
        stage_bytes = (accum * 2.0 * (st - 1) / st
                       * rows * seq_local * hidden * act_bytes)

    per_axis = {
        "data": {
            "size": d,
            "collective": "grad all-reduce (ring)",
            "bytes": acc["data"],
        },
        "fsdp": {
            "size": f,
            "collective": "param all-gather + grad reduce-scatter (ring)",
            "bytes": acc["fsdp_gather"] + acc["fsdp_scatter"],
            "gather_bytes": acc["fsdp_gather"],
            "scatter_bytes": acc["fsdp_scatter"],
        },
        "tensor": {
            "size": tp,
            "collective": "activation all-reduce (ring)",
            "bytes": tensor_bytes,
        },
        "sequence": {
            "size": sp,
            "collective": "ring-attention K/V sendrecv",
            "bytes": seq_bytes,
        },
        "expert": {
            "size": ep,
            "collective": "MoE dispatch/combine all-to-all",
            "bytes": expert_bytes,
        },
        "stage": {
            "size": st,
            "collective": "pipeline boundary transfer",
            "bytes": stage_bytes,
        },
    }
    total = sum(v["bytes"] for v in per_axis.values())

    # Roofline: serial (no-overlap) comms time vs analytic compute time.
    peak = peak_flops if peak_flops is not None else device_peak_flops()
    ici = _ici_bytes_per_sec(device_kind)
    tokens_per_step = rows * accum * d * f * max_seq_len
    flops_step = flops_per_token(mc, seq_len=max_seq_len) * tokens_per_step
    per_device_flops = flops_step / n_devices
    compute_s = per_device_flops / peak
    comms_s = total / ici
    ratio = comms_s / compute_s if compute_s > 0 else float("inf")

    return {
        "kind": "comms_model",
        "mesh": sizes,
        "strategy": strategy,
        "params": params_total,
        "per_axis": per_axis,
        "total_bytes_per_device_per_step": total,
        "compute_seconds_est": compute_s,
        "comms_seconds_est": comms_s,
        "comms_compute_ratio": ratio,
        "bound": "comms" if comms_s > compute_s else "compute",
        "assumptions": {
            "collectives": "bidirectional ring, no compute/comms overlap",
            "grad_bytes": GRAD_BYTES,
            "activation_bytes": act_bytes,
            "fsdp_param_gathers_per_step": 2,
            "tp_head_excluded": "vocab-sharded fused head reduces scalars",
            "peak_flops_per_device": peak,
            "ici_bytes_per_sec": ici,
            "device_kind": device_kind or "unknown",
        },
    }


def abstract_params(model_config):
    """Abstract parameter tree for a model config (no weights allocated).

    Exactly the tree :func:`build` derives from a live trainer — valid for
    planning because nothing mesh-dependent changes the parameter *shapes*
    (TP/FSDP change PartitionSpecs only, and the fused-projections toggle
    the Trainer flips under TP keeps the tree identical: fusion is disabled
    whenever ``tensor > 1``, and the planner follows the same rule via the
    model config it is handed).
    """
    from tpu_trainer.models.gpt import GPT

    model = GPT(model_config)
    return jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0),
    )


def build(trainer) -> dict:
    """Analytic per-device bytes/step for every mesh axis of ``trainer``.

    Pure shape arithmetic — evaluates no step, compiles nothing (parameter
    shapes come from ``jax.eval_shape`` on ``model.init``). Returns the
    ``kind:"comms_model"`` record; the caller stamps ``step`` and logs it.

    Thin wrapper over :func:`build_core` (same output, byte for byte): it
    only extracts the trainer's abstract param tree, mesh axis sizes, and
    run dimensions.
    """
    mesh = trainer.mesh
    tc = trainer.training_config
    param_shapes = jax.eval_shape(
        lambda rng: trainer.model.init(
            rng, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0),
    )
    device = next(iter(mesh.devices.flat))
    return build_core(
        param_shapes,
        dict(mesh.shape),
        trainer.strategy,
        model_config=trainer.model_config,
        batch_size=tc.batch_size,
        max_seq_len=tc.max_seq_len,
        grad_accum=tc.gradient_accumulation_steps,
        device_kind=getattr(device, "device_kind", ""),
    )


def summary_lines(record: dict) -> List[str]:
    """Two human-readable stdout lines for a comms_model record."""
    active = {k: v for k, v in record["per_axis"].items() if v["bytes"] > 0}
    parts = ", ".join(
        f"{k}[{v['size']}] {v['bytes'] / 1e6:.1f} MB" for k, v in active.items()
    ) or "none (single-device or fully replicated compute)"
    lines = [
        f"comms_model | per-device traffic/step: {parts}",
        (f"comms_model | roofline: comms {record['comms_seconds_est'] * 1e3:.2f} ms"
         f" vs compute {record['compute_seconds_est'] * 1e3:.2f} ms"
         f" -> {record['bound']}-bound"
         f" (ratio {record['comms_compute_ratio']:.2f})"),
    ]
    mism = record.get("hlo_mismatches")
    if mism:
        lines.extend(f"comms_model | HLO cross-check: {m}" for m in mism)
    return lines


# --- HLO cross-check -------------------------------------------------------

_HLO_OP_RE = re.compile(
    r"(?<![%\w-])(" + "|".join(_HLO_COLLECTIVES) + r")(?:-start)?\("
)


def hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    """Count collective *instructions* in compiled HLO text.

    Matches the opcode position (``= <type> all-reduce(...)`` or the async
    ``-start`` form) and not operand references (``%all-reduce.1``) or the
    paired ``-done`` ops, so each collective is counted once.
    """
    counts = {op: 0 for op in _HLO_COLLECTIVES}
    for m in _HLO_OP_RE.finditer(hlo_text):
        counts[m.group(1)] += 1
    return counts


def crosscheck(record: dict, hlo_text: str) -> dict:
    """Compare the model against the collectives GSPMD actually inserted.

    Soft validation: for every axis the model charges bytes to, at least
    one of the collective forms that axis can legally compile to must
    appear in the HLO. Returns ``{"hlo_collective_counts", "hlo_mismatches"}``
    for the caller to merge into the record.
    """
    counts = hlo_collective_counts(hlo_text)
    mismatches = []
    for axis, info in record["per_axis"].items():
        if info["bytes"] <= 0:
            continue
        expected = _AXIS_EXPECTED_OPS[axis]
        if not any(counts.get(op, 0) > 0 for op in expected):
            mismatches.append(
                f"modeled {info['bytes']:.3g} B/step on axis '{axis}' but "
                f"none of {expected} appear in the compiled HLO")
    return {"hlo_collective_counts": counts, "hlo_mismatches": mismatches}
