"""Parameter / optimizer-state sharding rules (GSPMD).

The TPU-native equivalent of the reference's two parallelism strategies
(SURVEY.md C9/C10):

- **DDP** (reference ``ddp_trainer.py:167-172``): params and optimizer state
  replicated; the batch sharded over the data axes. XLA's SPMD partitioner
  inserts the gradient all-reduce that DDP's bucket hooks perform.
- **FSDP** (reference ``fsdp_trainer.py:236-310``): the ``sharding_strategy``
  modes map onto NamedShardings instead of wrapper classes:

  | reference mode  | ZeRO | params    | grads     | optimizer state |
  |-----------------|------|-----------|-----------|-----------------|
  | FULL_SHARD      | 3    | sharded   | sharded   | sharded         |
  | SHARD_GRAD_OP   | 2    | replicated| sharded   | sharded         |
  | NO_SHARD        | -    | replicated| replicated| replicated      |
  | HYBRID_SHARD    | 3*   | sharded over fsdp, replicated over data |

  (HYBRID_SHARD is docstring-only/broken in the reference —
  ``fsdp_trainer.py:258-261`` vs the strategy dict ``:269-273``; here it is
  simply ``data > 1 and fsdp > 1``.)

The all-gather (param use) and reduce-scatter (grad reduction) that torch
FSDP issues per wrapped module are emitted automatically by the XLA SPMD
partitioner, with overlap handled by the latency-hiding scheduler — the
analogue of ``backward_prefetch``/``limit_all_gathers``
(``fsdp_trainer.py:296,304-307``).

Sharding rule: for each array leaf, shard the **largest** dimension that is
divisible by the fsdp axis size (ties → later dim). This is shape-driven, so
one rule covers params, grads, and Adam's mu/nu (whose trees mirror params).
A ``tensor`` axis (Megatron-style op sharding) is reserved in the mesh; rules
for it live in ``tensor_rules`` and activate when ``tensor > 1``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_trainer.parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS

# Strategy names: ours (zero3/zero2/replicated) with the reference's
# FSDP spellings accepted as aliases.
STRATEGY_ALIASES = {
    "FULL_SHARD": "zero3",
    "SHARD_GRAD_OP": "zero2",
    "NO_SHARD": "replicated",
    "HYBRID_SHARD": "zero3",  # hybrid = zero3 rules + data axis > 1
    "zero3": "zero3",
    "zero2": "zero2",
    "replicated": "replicated",
    "ddp": "replicated",
}


def canonical_strategy(name: str) -> str:
    if name not in STRATEGY_ALIASES:
        raise ValueError(
            f"unknown sharding strategy {name!r}; choose from {sorted(STRATEGY_ALIASES)}"
        )
    return STRATEGY_ALIASES[name]


def fsdp_spec(shape, fsdp_size: int) -> P:
    """Shard the largest fsdp-divisible dim over the fsdp axis."""
    if fsdp_size <= 1 or not shape:
        return P()
    best: Optional[int] = None
    for i, d in enumerate(shape):
        if d % fsdp_size == 0 and d >= fsdp_size:
            if best is None or d >= shape[best]:
                best = i
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best] = FSDP_AXIS
    return P(*spec)


def params_specs(params: Any, mesh: Mesh, strategy: str) -> Any:
    """PartitionSpec tree for model parameters under a strategy."""
    strategy = canonical_strategy(strategy)
    fsdp_size = mesh.shape[FSDP_AXIS]
    if strategy in ("replicated", "zero2"):
        return jax.tree_util.tree_map(lambda _: P(), params)
    return jax.tree_util.tree_map(lambda x: fsdp_spec(x.shape, fsdp_size), params)


def opt_state_specs(opt_state: Any, mesh: Mesh, strategy: str) -> Any:
    """PartitionSpec tree for optimizer state.

    zero2 and zero3 both shard the (param-shaped) Adam moments; scalars (step
    counts) stay replicated. ``opt_state`` may be a tree of concrete arrays or
    of ShapeDtypeStructs (from ``jax.eval_shape``).
    """
    strategy = canonical_strategy(strategy)
    fsdp_size = mesh.shape[FSDP_AXIS]
    if strategy == "replicated":
        return jax.tree_util.tree_map(lambda _: P(), opt_state)
    return jax.tree_util.tree_map(
        lambda x: fsdp_spec(x.shape, fsdp_size) if getattr(x, "ndim", 0) >= 1 else P(),
        opt_state,
    )


def grads_specs(params: Any, mesh: Mesh, strategy: str) -> Any:
    """PartitionSpec tree for gradients (reduce-scatter target under ZeRO)."""
    strategy = canonical_strategy(strategy)
    fsdp_size = mesh.shape[FSDP_AXIS]
    if strategy == "replicated":
        return jax.tree_util.tree_map(lambda _: P(), params)
    return jax.tree_util.tree_map(lambda x: fsdp_spec(x.shape, fsdp_size), params)


def to_shardings(spec_tree: Any, mesh: Mesh) -> Any:
    """PartitionSpec tree → NamedSharding tree."""
    return jax.tree_util.tree_map(lambda spec: NamedSharding(mesh, spec), spec_tree)


def constrain(tree: Any, spec_tree: Any) -> Any:
    """Apply ``with_sharding_constraint`` leaf-wise (inside jit)."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.lax.with_sharding_constraint(x, spec), tree, spec_tree
    )
