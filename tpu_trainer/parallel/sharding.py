"""Parameter / optimizer-state sharding rules (GSPMD).

The TPU-native equivalent of the reference's two parallelism strategies
(SURVEY.md C9/C10), plus tensor parallelism (absent upstream — an
aspirational README bullet, ``README.md:9``; here a working mesh axis):

- **DDP** (reference ``ddp_trainer.py:167-172``): params and optimizer state
  replicated; the batch sharded over the data axes. XLA's SPMD partitioner
  inserts the gradient all-reduce that DDP's bucket hooks perform.
- **FSDP** (reference ``fsdp_trainer.py:236-310``): the ``sharding_strategy``
  modes map onto NamedShardings instead of wrapper classes:

  | reference mode  | ZeRO | params    | grads     | optimizer state |
  |-----------------|------|-----------|-----------|-----------------|
  | FULL_SHARD      | 3    | sharded   | sharded   | sharded         |
  | SHARD_GRAD_OP   | 2    | replicated| sharded   | sharded         |
  | NO_SHARD        | -    | replicated| replicated| replicated      |
  | HYBRID_SHARD    | 3*   | sharded over fsdp, replicated over data |

  (HYBRID_SHARD is docstring-only/broken in the reference —
  ``fsdp_trainer.py:258-261`` vs the strategy dict ``:269-273``; here it is
  simply ``data > 1 and fsdp > 1``.)
- **TP (Megatron-style)**: when the mesh's ``tensor`` axis is > 1, the
  per-layer projections split column-/row-parallel via path rules
  (``_TENSOR_RULES``); GSPMD emits the all-reduce after each row-parallel
  matmul. No explicit collectives appear anywhere — TP is purely a change of
  PartitionSpec, composable with every ZeRO mode.

The all-gather (param use) and reduce-scatter (grad reduction) that torch
FSDP issues per wrapped module are emitted automatically by the XLA SPMD
partitioner, with overlap handled by the latency-hiding scheduler — the
analogue of ``backward_prefetch``/``limit_all_gathers``
(``fsdp_trainer.py:296,304-307``).

FSDP rule: for each array leaf, shard the **largest** dimension that is
divisible by the fsdp axis size and not already tensor-sharded (ties → later
dim). Shape-driven, so one rule covers params, grads, and Adam's mu/nu
(whose trees mirror params — path matching uses suffix match, which survives
the optax state nesting).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_trainer.parallel.mesh import (
    DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, STAGE_AXIS, TENSOR_AXIS,
)

# Strategy names: ours (zero3/zero2/replicated) with the reference's
# FSDP spellings accepted as aliases.
STRATEGY_ALIASES = {
    "FULL_SHARD": "zero3",
    "SHARD_GRAD_OP": "zero2",
    "NO_SHARD": "replicated",
    "HYBRID_SHARD": "zero3",  # hybrid = zero3 rules + data axis > 1
    "zero3": "zero3",
    "zero2": "zero2",
    "replicated": "replicated",
    "ddp": "replicated",
}

# Megatron-style tensor-parallel placement, by parameter-path suffix.
# Column-parallel = shard the output dim (last); row-parallel = shard the
# input dim (second to last); the row-parallel matmuls (o_proj, down_proj)
# are where GSPMD inserts the TP all-reduce. The tied embedding shards its
# hidden dim (vocab 50257 is not divisible by practical axis sizes), making
# ``embed.attend`` a row-parallel matmul too.
_TENSOR_RULES: List[Tuple[Tuple[str, ...], int]] = [
    (("attention", "q_proj", "kernel"), -1),
    (("attention", "k_proj", "kernel"), -1),
    (("attention", "v_proj", "kernel"), -1),
    (("attention", "o_proj", "kernel"), -2),
    (("mlp", "gate_proj", "kernel"), -1),
    (("mlp", "up_proj", "kernel"), -1),
    (("mlp", "down_proj", "kernel"), -2),
    (("embed_tokens", "embedding"), -1),
    # Expert FFN weights ([.., E, H, I] / [.., E, I, H]): the same
    # column/row-parallel split as the dense MLP, per expert — composes
    # with the expert-dim sharding (_expert_dim) into EP x TP.
    (("experts_gate",), -1),
    (("experts_up",), -1),
    (("experts_down",), -2),
]


def canonical_strategy(name: str) -> str:
    if name not in STRATEGY_ALIASES:
        raise ValueError(
            f"unknown sharding strategy {name!r}; choose from {sorted(STRATEGY_ALIASES)}"
        )
    return STRATEGY_ALIASES[name]


def _path_keys(path) -> Tuple[str, ...]:
    return tuple(
        str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", "")))) for p in path
    )


# Expert-parallel placement: stacked expert FFN weights ([E, H, I] — or
# [L, E, H, I] under the layer scan) shard their expert dim, which sits at
# ndim-3. The router stays replicated (it is tiny).
_EXPERT_PARAM_PREFIX = "experts_"


def _expert_dim(path_keys: Tuple[str, ...], shape, expert_size: int) -> Optional[int]:
    if expert_size <= 1 or not path_keys or len(shape) < 3:
        return None
    if not path_keys[-1].startswith(_EXPERT_PARAM_PREFIX):
        return None
    d = len(shape) - 3
    return d if shape[d] % expert_size == 0 else None


def _tensor_dim(path_keys: Tuple[str, ...], shape, tensor_size: int) -> Optional[int]:
    """Dim to shard over the tensor axis for this param path, or None."""
    if tensor_size <= 1:
        return None
    for suffix, dim in _TENSOR_RULES:
        if path_keys[-len(suffix):] == suffix:
            d = dim % len(shape)
            if shape[d] % tensor_size == 0:
                return d
            return None
    return None


def fsdp_spec(shape, fsdp_size: int) -> P:
    """Shape-only FSDP rule: shard the largest fsdp-divisible dim (ties →
    later dim); replicate when nothing divides."""
    return _leaf_spec((), shape, fsdp_size=fsdp_size, tensor_size=1,
                      shard_fsdp=True)


def _leaf_spec(path_keys, shape, *, fsdp_size: int, tensor_size: int,
               shard_fsdp: bool, expert_size: int = 1,
               stage_size: int = 1) -> P:
    """Combined PP + EP + TP + FSDP PartitionSpec for one array leaf."""
    if not shape:
        return P()
    dims: List[Optional[str]] = [None] * len(shape)
    if (
        stage_size > 1
        and "layers" in path_keys
        and shape
        and shape[0] % stage_size == 0
    ):
        # Pipeline parallelism: the nn.scan stacked [num_layers, ...]
        # leading dim splits into contiguous stages. Everything outside the
        # layer stack (embedding, final norm) replicates over `stage`.
        dims[0] = STAGE_AXIS
    edim = _expert_dim(path_keys, shape, expert_size)
    if edim is not None and dims[edim] is None:
        dims[edim] = EXPERT_AXIS
    tdim = _tensor_dim(path_keys, shape, tensor_size)
    if tdim is not None and dims[tdim] is None:
        dims[tdim] = TENSOR_AXIS
    if shard_fsdp and fsdp_size > 1:
        best: Optional[int] = None
        for i, d in enumerate(shape):
            if dims[i] is None and d % fsdp_size == 0 and d >= fsdp_size:
                if best is None or d >= shape[best]:
                    best = i
        if best is not None:
            dims[best] = FSDP_AXIS
    if all(d is None for d in dims):
        return P()
    return P(*dims)


def _specs_for_sizes(tree: Any, axis_sizes, *, shard_fsdp: bool) -> Any:
    """Spec tree from axis sizes alone (a ``{axis_name: size}`` mapping).

    The placement rules are pure shape/path arithmetic — no live ``Mesh``
    required — which is what lets the mesh auto-planner score candidate
    meshes that were never materialized. ``mesh.shape`` is such a mapping,
    so the Mesh entry points below just delegate here.
    """
    fsdp_size = axis_sizes.get(FSDP_AXIS, 1)
    tensor_size = axis_sizes.get(TENSOR_AXIS, 1)
    expert_size = axis_sizes.get(EXPERT_AXIS, 1)
    stage_size = axis_sizes.get(STAGE_AXIS, 1)
    return jax.tree_util.tree_map_with_path(
        lambda path, x: _leaf_spec(
            _path_keys(path), getattr(x, "shape", ()),
            fsdp_size=fsdp_size, tensor_size=tensor_size,
            shard_fsdp=shard_fsdp, expert_size=expert_size,
            stage_size=stage_size,
        ),
        tree,
    )


def _specs_for_tree(tree: Any, mesh: Mesh, *, shard_fsdp: bool) -> Any:
    return _specs_for_sizes(tree, mesh.shape, shard_fsdp=shard_fsdp)


def params_specs_from_sizes(params: Any, axis_sizes, strategy: str) -> Any:
    """``params_specs`` from a ``{axis: size}`` mapping instead of a Mesh."""
    strategy = canonical_strategy(strategy)
    return _specs_for_sizes(params, axis_sizes, shard_fsdp=strategy == "zero3")


def params_specs(params: Any, mesh: Mesh, strategy: str) -> Any:
    """PartitionSpec tree for model parameters under a strategy.

    TP placement applies in every strategy (a TP-sharded param is never
    replicated over ``tensor``); the fsdp axis applies only under zero3.
    """
    return params_specs_from_sizes(params, mesh.shape, strategy)


def opt_state_specs_from_sizes(opt_state: Any, axis_sizes, strategy: str) -> Any:
    """``opt_state_specs`` from a ``{axis: size}`` mapping instead of a Mesh."""
    strategy = canonical_strategy(strategy)
    return _specs_for_sizes(
        opt_state, axis_sizes, shard_fsdp=strategy in ("zero2", "zero3")
    )


def opt_state_specs(opt_state: Any, mesh: Mesh, strategy: str) -> Any:
    """PartitionSpec tree for optimizer state.

    zero2 and zero3 both shard the (param-shaped) Adam moments; scalars (step
    counts) stay replicated. The moments live nested inside optax state, but
    suffix-matching the param path still applies the TP rules correctly.
    ``opt_state`` may be a tree of arrays or of ShapeDtypeStructs.
    """
    return opt_state_specs_from_sizes(opt_state, mesh.shape, strategy)


def grads_specs_from_sizes(params: Any, axis_sizes, strategy: str) -> Any:
    """``grads_specs`` from a ``{axis: size}`` mapping instead of a Mesh."""
    strategy = canonical_strategy(strategy)
    return _specs_for_sizes(
        params, axis_sizes, shard_fsdp=strategy in ("zero2", "zero3")
    )


def grads_specs(params: Any, mesh: Mesh, strategy: str) -> Any:
    """PartitionSpec tree for gradients (reduce-scatter target under ZeRO).

    Gradients of TP-sharded params carry the same tensor dims in every
    strategy; the fsdp axis applies under zero2/zero3.
    """
    return grads_specs_from_sizes(params, mesh.shape, strategy)


def to_shardings(spec_tree: Any, mesh: Mesh) -> Any:
    """PartitionSpec tree → NamedSharding tree."""
    return jax.tree_util.tree_map(lambda spec: NamedSharding(mesh, spec), spec_tree)


def constrain(tree: Any, spec_tree: Any) -> Any:
    """Apply ``with_sharding_constraint`` leaf-wise (inside jit)."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.lax.with_sharding_constraint(x, spec), tree, spec_tree
    )
