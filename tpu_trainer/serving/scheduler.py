"""Continuous-batching scheduler (Orca-style iteration-level scheduling).

Decisions happen per *iteration*, not per request-batch: every engine
step is either ONE prefill pass or ONE single-token decode over
everything running — finished requests retire and release blocks
immediately, and a newly admitted request joins the very next decode
batch instead of waiting for the oldest request in flight to drain (the
static-batching failure mode).

Policies, all deterministic host-side Python over ``PagedKVCache``'s
mirrors (no device syncs):

- **Admission** (FIFO, by block budget): the head of the waiting queue
  is admitted when a slot is free and the pool (free blocks plus what
  LRU prefix eviction can reclaim) covers the blocks its current context
  needs plus ``watermark_blocks``. With prefix caching on, admission
  first matches the prompt against the prefix index: matched full blocks
  are shared (refcounted, copy-on-write by construction) and the
  request's ``prefill_cursor`` starts past them — only the remainder is
  ever prefilled. Head-of-line blocking is deliberate — arrival order is
  completion-fairness here.
- **Chunked prefill** (``prefill_chunk_tokens``): a prefill iteration
  feeds at most that many prompt tokens, split FIFO over the requests
  still mid-prefill (each carries a ``prefill_cursor``). When both
  mid-prefill and decodable requests exist, prefill and decode
  iterations strictly alternate, so no decode step ever waits for more
  than one chunk — the p99 TPOT contract. ``None`` (default) keeps the
  original whole-prompt-per-iteration behavior exactly.
- **Decode growth**: a running request crossing a block boundary
  allocates one block just-in-time.
- **Preemption** (recompute-style, when the pool runs dry): the
  latest-admitted running request frees everything and goes back to the
  FRONT of the waiting queue; on re-admission it re-prefills prompt +
  generated-so-far (minus whatever the prefix index still covers) from a
  reset cursor. Sampling is keyed by (seed, token index) —
  serving/sampling.py — so the resumed continuation is token-identical
  to the uninterrupted one, chunked or not.
- **Retirement**: EOS or max_new_tokens; the request's block references
  drop the same iteration (shared blocks survive in the prefix index).
- **Cancellation / deadlines**: ``cancel`` retires a waiting or running
  request on the spot with a terminal ``cancelled`` status (blocks and
  slot freed immediately for running requests; waiting ones just leave
  the queue), and ``expire`` sweeps every request whose ``deadline`` has
  passed into ``deadline_exceeded`` the same way. The engine runs the
  sweep at the top of each step, so expiry lands at an iteration
  boundary — never mid-dispatch — and a chunked prefill in progress
  simply stops at its current chunk.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, List, Optional, Tuple

from tpu_trainer.serving.paged_cache import PagedKVCache

# Every status a request can end in. "finished" is the only one that
# produced a complete stream; "failed" is reserved for unrecoverable
# per-request errors (no current producer, but the accounting schema
# carries it so adding one is not a schema change).
TERMINAL_STATES = frozenset(
    {"finished", "cancelled", "deadline_exceeded", "failed"})


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling knobs (``temperature == 0`` = exact greedy;
    ``top_p == 1`` = no nucleus filter). Validated at construction —
    i.e. at ``Request`` build time, before anything reaches the jitted
    sampler — so a bad knob is a ValueError here, not a NaN inside jit."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(f"temperature {self.temperature} < 0")
        if self.top_k < 0:
            raise ValueError(f"top_k {self.top_k} < 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p {self.top_p} outside (0, 1]")


@dataclasses.dataclass
class Request:
    """One generation request plus its scheduler/engine runtime state."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    arrival_time: float = 0.0
    eos_id: Optional[int] = None
    # Absolute completion deadline in the engine's clock domain (front-end
    # iteration number in ``steps`` mode, seconds since run start in
    # ``wall``). None = no deadline. Expiry is swept at iteration
    # boundaries: strictly past the deadline -> ``deadline_exceeded``.
    deadline: Optional[float] = None

    # Runtime state (engine/scheduler-owned).
    generated: List[int] = dataclasses.field(default_factory=list)
    # waiting | running | finished | cancelled | deadline_exceeded | failed
    status: str = "waiting"
    slot: Optional[int] = None
    preemptions: int = 0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # First admission time (engine clock domain). Set once — re-admission
    # after preemption keeps the original, so ``admitted_at -
    # arrival_time`` is the request's true queue wait (the ``queue_wait``
    # series in ``engine.request_metrics``).
    admitted_at: Optional[float] = None
    # Wall-clock emission time of every generated token (inter-token-gap
    # telemetry: ``engine.request_metrics`` derives TPOT from the diffs).
    token_times: List[float] = dataclasses.field(default_factory=list)
    # Chunked-prefill cursor: tokens of (prompt + generated-at-admission)
    # already resident in the cache — via earlier chunks or a prefix-index
    # hit. The request decodes once cursor reaches prefill_target.
    prefill_cursor: int = 0
    prefill_target: int = 0
    prefill_chunk: int = 0             # tokens to feed THIS iteration
    prefix_hit_tokens: int = 0         # prompt tokens skipped at admission
    # Speculative-decode acceptance telemetry (serving/spec.py): drafts
    # proposed / accepted over this request's verify steps.
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_steps: int = 0
    _blocks_registered: int = 0        # prompt blocks published to the index
    # Chained block digests of the prompt, hashed ONCE per request — the
    # front-end caches them at submit; the router's affinity key, admission
    # prefix_lookup and the prefix-index registration all read this list.
    _prompt_digests = None
    # Migrated raw-tail KV payload ({"tail_ntok", "leaves"}) attached by the
    # front-end between extract on a prefill replica and admission on the
    # decode replica; consumed (and cleared) by the first admission.
    _kv_migration = None
    _key = None                        # lazily built [2] uint32 PRNG key

    def context_len(self) -> int:
        """Tokens fed to the model so far (prompt + sampled)."""
        return len(self.prompt) + len(self.generated)

    def cached_tokens(self) -> int:
        """Tokens whose K/V sit in the paged cache. The newest sampled
        token is NOT cached yet — it is the next decode step's input."""
        n = self.context_len()
        return n - 1 if self.generated else n

    def prefilling(self) -> bool:
        return self.prefill_cursor < self.prefill_target

    def key(self):
        if self._key is None:
            from tpu_trainer.serving.sampling import request_key

            self._key = request_key(self.sampling.seed)
        return self._key


class Scheduler:
    """Iteration-level scheduler over one ``PagedKVCache`` slot batch."""

    def __init__(self, cache: PagedKVCache, *, watermark_blocks: int = 0,
                 max_prefill_rows: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 spec_reserve_tokens: int = 0):
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 1:
            raise ValueError(f"prefill_chunk_tokens={prefill_chunk_tokens}")
        self.cache = cache
        self.watermark = watermark_blocks
        self.max_prefill_rows = max_prefill_rows or cache.slots
        self.prefill_chunk_tokens = prefill_chunk_tokens
        # Speculative decode: admission budgets blocks for the context
        # PLUS a worst-case draft window (K+1 tokens), so a verify step's
        # write-ahead growth is pre-priced and almost never needs the
        # preemption backstop mid-flight.
        self.spec_reserve_tokens = spec_reserve_tokens
        self.waiting: Deque[Request] = deque()
        self.running: List[Request] = []   # admission order
        self._free_slots = list(range(cache.slots))
        self._last_was_prefill = False
        # False on prefill-role engines (disaggregated serving): requests
        # finish prefill + their first sampled token, then idle until the
        # front-end extracts them for migration to a decode replica.
        self.decode_enabled = True
        self.n_preemptions = 0
        self.n_admissions = 0          # admission events (re-admits count)
        self.prefix_hit_tokens = 0
        self.prompt_tokens = 0         # prompt tokens over all admissions
        self.n_migrated_tail_fills = 0  # migrated raw tails admitted
        self.n_migration_declined = 0   # tails priced out (recompute won)
        # Terminal transitions by state, counted at the single funnel
        # (retire + the waiting-queue branches of cancel/expire that
        # bypass it). The metrics plane mirrors these monotone counts.
        self.terminal_counts = {s: 0 for s in sorted(TERMINAL_STATES)}
        # Span-tracing hooks, wired by the owning engine: ``tracer`` is a
        # serving.tracing.SpanTracer, ``now_fn`` the engine clock. The
        # scheduler is the single funnel for admission / preemption /
        # terminal transitions, so emitting here covers every path.
        self.tracer = None
        self.now_fn = None

    def _emit(self, req: Request, event: str, **attrs) -> None:
        if self.tracer is not None and self.now_fn is not None:
            self.tracer.emit(req.rid, event, self.now_fn(), **attrs)

    # -- queue interface ---------------------------------------------------

    def add(self, req: Request) -> None:
        if not req.prompt:
            raise ValueError(f"request {req.rid}: empty prompt")
        need = self.cache.blocks_for(len(req.prompt) + req.max_new_tokens)
        if need > self.cache.max_blocks:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + max_new "
                f"{req.max_new_tokens} needs {need} blocks > table width "
                f"{self.cache.max_blocks}")
        req.status = "waiting"
        self.waiting.append(req)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def pool_shard_stats(self) -> dict:
        """Block budget per shard: how the replica's total pool splits
        over its tensor-parallel mesh (serving/sharding.py). With
        kv-head-sharded pools each device holds 1/tp of every block, so
        ``device_pool_blocks = total / tp`` — the per-device HBM budget
        the engine's ``device_block_budget`` kwarg sizes against. On an
        unsharded replica (tp=1, or GQA-replicated pools) device ==
        total. Scheduling itself is shard-agnostic — block accounting
        is in whole (logical) blocks either way."""
        cfg = self.cache.config
        tp = getattr(cfg, "paged_tp", 1)
        from tpu_trainer.serving.sharding import shard_factor

        total = cfg.paged_num_blocks
        return {
            "tp": int(tp),
            "total_pool_blocks": int(total),
            "device_pool_blocks": int(
                total // shard_factor(cfg.kv_heads, tp)),
        }

    # -- load signals (cheap, host-only — the multi-replica router's
    # routing/admission inputs, and useful standalone telemetry) ----------

    @property
    def queue_depth(self) -> int:
        """Requests waiting for admission."""
        return len(self.waiting)

    @property
    def oldest_waiting_arrival(self) -> Optional[float]:
        """Earliest ``arrival_time`` in the waiting queue (None when
        empty). Not simply ``waiting[0]`` — preemption requeues at the
        front, so arrival order and queue order can differ."""
        return min((r.arrival_time for r in self.waiting), default=None)

    @property
    def outstanding_tokens(self) -> int:
        """Total token-steps of work still owed: remaining prefill plus
        remaining decode for running requests; full context re-prefill
        (prompt + generated-so-far) plus remaining decode for waiting
        ones. The router's least-loaded signal — O(requests), no device
        syncs."""
        total = 0
        for r in self.waiting:
            total += r.context_len() + r.max_new_tokens - len(r.generated)
        for r in self.running:
            total += max(0, r.prefill_target - r.prefill_cursor)
            total += r.max_new_tokens - len(r.generated)
        return total

    # -- drain/export (failover and shrink-teardown) -----------------------

    def export_requests(self, *, waiting_only: bool = False) -> List[Request]:
        """Strip every queued (and, unless ``waiting_only``, in-flight)
        request out of this scheduler, reset to fresh-waiting state, for
        resubmission elsewhere. Running requests are preempted first
        (blocks released, cursors reset), so the export is also a clean
        local teardown. Generated tokens, timestamps and sampling state
        survive — re-admission re-prefills prompt + generated and the
        (seed, token_index) sampling contract makes the resumed stream
        token-identical, the preemption-resume argument. Returned in
        (arrival_time, rid) order."""
        if not waiting_only:
            while self.running:
                self.preempt(self.running[-1])
        out = sorted(self.waiting, key=lambda r: (r.arrival_time, r.rid))
        self.waiting.clear()
        for req in out:
            # Handoff, not a terminal: this timeline's conservation
            # obligation moves to whoever ingests the request next.
            self._emit(req, "exported", generated=len(req.generated))
        return out

    def extract(self, req: Request) -> None:
        """Migration handoff: strip ONE running request out of this
        scheduler (blocks released, cursors reset, status waiting) for
        resubmission on another replica. ``export_requests``' contract
        without the preemption accounting: the K/V it computed survives
        in the prefix index / fleet store (the caller harvests BEFORE
        calling), and the (seed, token_index) sampling contract keeps
        the resumed stream token-identical wherever it lands."""
        self._vacate(req)
        req.status = "waiting"
        req.prefill_cursor = 0
        req.prefill_target = 0
        req.prefill_chunk = 0
        self._emit(req, "exported", generated=len(req.generated),
                   migrated=True)

    # -- the per-iteration decision ---------------------------------------

    def _admit(self) -> List[Request]:
        """FIFO admission of the waiting-queue head while slots and the
        block budget (free + prefix-evictable, minus the watermark)
        last. A prefix-index hit shares the matched blocks and starts
        the cursor past them."""
        admitted: List[Request] = []
        while (self.waiting and self._free_slots
               and len(admitted) < self.max_prefill_rows):
            req = self.waiting[0]
            ctx = req.context_len()
            if req._prompt_digests is None and self.cache.prefix_cache:
                req._prompt_digests = self.cache.block_digests(req.prompt)
            mig = req._kv_migration
            # A migrating request arrives with generated tokens, so the
            # copy-on-write cap widens to every full prompt block — the
            # token it feeds next is a generated one.
            # prefix_lookup hands back blocks already retained on our
            # behalf — the reference transfers to the slot table below
            # (release/shrink drop it), or is freed when the budget
            # check leaves the request waiting.
            shared, matched = self.cache.prefix_lookup(
                req.prompt, digests=req._prompt_digests,
                context_len=ctx if mig is not None else None)
            budget_blocks = min(
                self.cache.blocks_for(ctx + self.spec_reserve_tokens),
                self.cache.max_blocks)
            need = budget_blocks - len(shared)
            if need + self.watermark > self.cache.available_blocks:
                if shared:
                    self.cache.pool.free(shared)
                break
            # Only the context's blocks are allocated now; the reserve
            # margin just gates admission (growth stays just-in-time).
            need = self.cache.blocks_for(ctx) - len(shared)
            self.waiting.popleft()
            fresh = self.cache.alloc_blocks(need)
            assert fresh is not None  # guarded by the budget check
            slot = self._free_slots.pop(0)
            self.cache.assign(slot, shared + fresh)
            if mig is not None:
                matched = self._ingest_migrated_tail(
                    req, mig, matched, fresh)
                req._kv_migration = None
            self.cache.lengths[slot] = matched
            req.slot = slot
            req.status = "running"
            req.prefill_cursor = matched
            req.prefill_target = ctx
            req.prefix_hit_tokens = matched
            req._blocks_registered = matched // self.cache.block_size
            self.prefix_hit_tokens += matched
            self.prompt_tokens += len(req.prompt)
            self.n_admissions += 1
            self.running.append(req)
            admitted.append(req)
            if req.admitted_at is None and self.now_fn is not None:
                req.admitted_at = self.now_fn()
                self._emit(req, "admitted", prefix_hit=matched,
                           queue_wait=max(
                               0.0, req.admitted_at - req.arrival_time))
            else:
                # Re-admission after preemption/failover: the original
                # queue wait stands, but the event marks the resume.
                self._emit(req, "admitted", prefix_hit=matched,
                           resumed=True)
        return admitted

    def _ingest_migrated_tail(self, req: Request, mig: dict,
                              matched: int, fresh: List[int]) -> int:
        """Admission half of KV migration: the sender's full prompt
        blocks arrived digest-addressed through the store/prefix index
        (``matched`` covers them), and the sub-block tail rides raw in
        ``mig``. When every full block matched, the tail's leaves are
        written into the request's FIRST private block — exactly where
        prefill would have put them — and the cursor starts past them.
        Any shortfall (partial match, dry hook, pricer preferring
        recompute) falls back to plain prefill of the remainder, which
        is always correct."""
        ntok = int(mig.get("tail_ntok") or 0)
        leaves = mig.get("leaves")
        full = (len(req.prompt) // self.cache.block_size) * self.cache.block_size
        if ntok <= 0 or leaves is None or matched != full or not fresh:
            return matched
        pricer = self.cache.pricer
        if pricer is not None:
            from tpu_trainer.serving.kv_store import leaves_nbytes

            if not pricer.prefers_transfer(ntok, leaves_nbytes(leaves)):
                self.n_migration_declined += 1
                return matched
        if not self.cache.fill_raw(fresh[0], leaves):
            return matched
        self.n_migrated_tail_fills += 1
        return matched + ntok

    def schedule(self) -> Tuple[str, List[Request]]:
        """Decide this iteration. Unchunked: ``("prefill", admitted)``
        when the queue head fits the budget (prefill has priority — it
        is what keeps slots full), else ``("decode", running)``, else
        ``("idle", [])``. Chunked: mid-prefill requests get chunks up to
        the token budget, and prefill/decode iterations alternate
        whenever both kinds of work exist. Each returned prefill request
        has ``prefill_chunk`` set to the tokens to feed now."""
        self._admit()
        prefilling = [r for r in self.running if r.prefilling()]
        decodable = ([r for r in self.running if not r.prefilling()]
                     if self.decode_enabled else [])
        if prefilling and decodable and self.prefill_chunk_tokens:
            do_prefill = not self._last_was_prefill
        else:
            do_prefill = bool(prefilling)
        if do_prefill:
            budget = self.prefill_chunk_tokens or float("inf")
            batch: List[Request] = []
            for r in prefilling[:self.max_prefill_rows]:
                if budget <= 0:
                    break
                n = int(min(r.prefill_target - r.prefill_cursor, budget))
                r.prefill_chunk = n
                budget -= n
                batch.append(r)
            self._last_was_prefill = True
            return "prefill", batch
        self._last_was_prefill = False
        if decodable:
            return "decode", decodable
        return "idle", []

    def ensure_decode_blocks(self) -> List[Request]:
        """Pre-decode block growth: every decodable request about to
        write at a block boundary gets one block, preempting from the
        back of the admission order when the pool is dry. Returns the
        requests that actually decode this iteration (preemption victims
        drop out — including, worst case, the requester itself)."""
        stepped: List[Request] = []
        for req in list(self.running):
            if req.status != "running":
                continue  # preempted as an earlier request's victim
            if req.prefilling():
                continue  # mid-prefill rows never decode
            pos = req.cached_tokens()
            n_blocks = len(self.cache.slot_blocks(req.slot))
            if pos == n_blocks * self.cache.block_size:
                got = self._alloc_with_preemption(1, req)
                if got is None:
                    continue  # req itself was the last resort victim
                self.cache.extend(req.slot, got)
            stepped.append(req)
        return stepped

    def ensure_spec_blocks(self, reqs: List[Request],
                           window_tokens) -> List[Request]:
        """Speculative-decode block growth: each request about to verify
        a draft window gets enough blocks for ``cached_tokens() +
        window_tokens[rid]`` BEFORE the step, so the verifier's
        write-ahead K/V scatter can never land outside the table.
        Same preemption backstop and return contract as
        ``ensure_decode_blocks``."""
        want = {r.rid for r in reqs}
        stepped: List[Request] = []
        for req in list(self.running):
            if req.status != "running" or req.rid not in want:
                continue  # preempted as an earlier request's victim
            if req.prefilling():
                continue
            need_tokens = req.cached_tokens() + window_tokens[req.rid]
            need = (self.cache.blocks_for(need_tokens)
                    - len(self.cache.slot_blocks(req.slot)))
            if need > 0:
                got = self._alloc_with_preemption(need, req)
                if got is None:
                    continue  # req itself was the last resort victim
                self.cache.extend(req.slot, got)
            stepped.append(req)
        return stepped

    def shrink_spec_blocks(self, req: Request) -> int:
        """Post-verify rewind: reclaim blocks grown past the accept
        point. Keeps exactly the blocks the accepted cache contents
        occupy; the next step's growth re-allocates just-in-time."""
        keep = self.cache.blocks_for(max(1, req.cached_tokens()))
        return self.cache.shrink(req.slot, keep)

    def _alloc_with_preemption(self, n: int, requester: Request):
        while True:
            got = self.cache.alloc_blocks(n)
            if got is not None:
                return got
            victim = self.running[-1]
            self.preempt(victim)
            if victim is requester:
                return None

    # -- state transitions -------------------------------------------------

    def preempt(self, victim: Request) -> None:
        """Recompute-preemption: free everything, requeue at the FRONT so
        re-admission preserves arrival order among the preempted. The
        prefill cursor resets — re-admission re-derives it (prompt +
        generated-so-far, minus any prefix-index hit)."""
        self._vacate(victim)
        victim.status = "waiting"
        victim.prefill_cursor = 0
        victim.prefill_target = 0
        victim.prefill_chunk = 0
        victim.preemptions += 1
        self.n_preemptions += 1
        self.waiting.appendleft(victim)
        self._emit(victim, "preempted", n=victim.preemptions)

    def retire(self, req: Request, status: str = "finished") -> None:
        assert status in TERMINAL_STATES, status
        self._vacate(req)
        req.status = status
        self.terminal_counts[status] += 1
        self._emit(req, status, generated=len(req.generated))

    def cancel(self, rid: int, *, status: str = "cancelled"):
        """Retire request ``rid`` NOW with a terminal status, wherever it
        sits: a waiting request just leaves the queue (it holds no
        blocks), a running one is vacated — slot and every non-shared
        block back in the pool this instant, not at drain. Returns the
        request, or None if ``rid`` is not queued or in flight (already
        terminal, or never submitted here)."""
        assert status in TERMINAL_STATES, status
        for req in self.waiting:
            if req.rid == rid:
                self.waiting.remove(req)
                req.status = status
                self.terminal_counts[status] += 1
                self._emit(req, status, generated=len(req.generated))
                return req
        for req in self.running:
            if req.rid == rid:
                self.retire(req, status)
                req.prefill_cursor = 0
                req.prefill_target = 0
                req.prefill_chunk = 0
                return req
        return None

    def expire(self, now: float) -> List[Request]:
        """Retire every waiting/running request strictly past its
        deadline as ``deadline_exceeded``; returns them. Called by the
        engine at the top of each step, so expiry always lands at an
        iteration boundary — a mid-chunked-prefill request keeps the
        chunks already fed and simply never schedules again (its blocks
        are freed here, like any other retirement)."""
        expired: List[Request] = []
        for req in [r for r in self.waiting
                    if r.deadline is not None and now > r.deadline]:
            self.waiting.remove(req)
            req.status = "deadline_exceeded"
            self.terminal_counts["deadline_exceeded"] += 1
            self._emit(req, "deadline_exceeded",
                       generated=len(req.generated))
            expired.append(req)
        for req in [r for r in self.running
                    if r.deadline is not None and now > r.deadline]:
            self.retire(req, "deadline_exceeded")
            expired.append(req)
        return expired

    def _vacate(self, req: Request) -> None:
        self.cache.release(req.slot)
        self._free_slots.append(req.slot)
        self._free_slots.sort()
        req.slot = None
        self.running.remove(req)
