"""Continuous-batching scheduler (Orca-style iteration-level scheduling).

Decisions happen per *iteration*, not per request-batch: every engine
step is either ONE prefill over the requests admitted this iteration or
ONE single-token decode over everything running — finished requests
retire and release blocks immediately, and a newly admitted request
joins the very next decode batch instead of waiting for the oldest
request in flight to drain (the static-batching failure mode).

Policies, all deterministic host-side Python over ``PagedKVCache``'s
mirrors (no device syncs):

- **Admission** (FIFO, by free-block budget): the head of the waiting
  queue is admitted when a slot is free and the pool covers the blocks
  its current context needs plus ``watermark_blocks``. Head-of-line
  blocking is deliberate — arrival order is completion-fairness here.
- **Decode growth**: a running request crossing a block boundary
  allocates one block just-in-time.
- **Preemption** (recompute-style, when the pool runs dry): the
  latest-admitted running request frees everything and goes back to the
  FRONT of the waiting queue; on re-admission it re-prefills prompt +
  generated-so-far in one pass. Sampling is keyed by (seed, token
  index) — serving/sampling.py — so the resumed continuation is
  token-identical to the uninterrupted one.
- **Retirement**: EOS or max_new_tokens; blocks return to the free list
  the same iteration.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, List, Optional, Tuple

from tpu_trainer.serving.paged_cache import PagedKVCache


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling knobs (``temperature == 0`` = exact greedy)."""

    temperature: float = 1.0
    top_k: int = 0
    seed: int = 0


@dataclasses.dataclass
class Request:
    """One generation request plus its scheduler/engine runtime state."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    arrival_time: float = 0.0
    eos_id: Optional[int] = None

    # Runtime state (engine/scheduler-owned).
    generated: List[int] = dataclasses.field(default_factory=list)
    status: str = "waiting"            # waiting | running | finished
    slot: Optional[int] = None
    preemptions: int = 0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    _key = None                        # lazily built [2] uint32 PRNG key

    def context_len(self) -> int:
        """Tokens fed to the model so far (prompt + sampled)."""
        return len(self.prompt) + len(self.generated)

    def cached_tokens(self) -> int:
        """Tokens whose K/V sit in the paged cache. The newest sampled
        token is NOT cached yet — it is the next decode step's input."""
        n = self.context_len()
        return n - 1 if self.generated else n

    def key(self):
        if self._key is None:
            from tpu_trainer.serving.sampling import request_key

            self._key = request_key(self.sampling.seed)
        return self._key


class Scheduler:
    """Iteration-level scheduler over one ``PagedKVCache`` slot batch."""

    def __init__(self, cache: PagedKVCache, *, watermark_blocks: int = 0,
                 max_prefill_rows: Optional[int] = None):
        self.cache = cache
        self.watermark = watermark_blocks
        self.max_prefill_rows = max_prefill_rows or cache.slots
        self.waiting: Deque[Request] = deque()
        self.running: List[Request] = []   # admission order
        self._free_slots = list(range(cache.slots))
        self.n_preemptions = 0

    # -- queue interface ---------------------------------------------------

    def add(self, req: Request) -> None:
        if not req.prompt:
            raise ValueError(f"request {req.rid}: empty prompt")
        need = self.cache.blocks_for(len(req.prompt) + req.max_new_tokens)
        if need > self.cache.max_blocks:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + max_new "
                f"{req.max_new_tokens} needs {need} blocks > table width "
                f"{self.cache.max_blocks}")
        req.status = "waiting"
        self.waiting.append(req)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- the per-iteration decision ---------------------------------------

    def schedule(self) -> Tuple[str, List[Request]]:
        """Decide this iteration: ``("prefill", admitted)`` when the head
        of the queue fits the budget (prefill has priority — it is what
        keeps slots full), else ``("decode", running)``, else
        ``("idle", [])``."""
        admitted: List[Request] = []
        while (self.waiting and self._free_slots
               and len(admitted) < self.max_prefill_rows):
            req = self.waiting[0]
            need = self.cache.blocks_for(req.context_len())
            if need + self.watermark > self.cache.pool.free_blocks:
                break
            self.waiting.popleft()
            blocks = self.cache.pool.alloc(need)
            assert blocks is not None  # guarded by the free_blocks check
            slot = self._free_slots.pop(0)
            self.cache.assign(slot, blocks)
            req.slot = slot
            req.status = "running"
            self.running.append(req)
            admitted.append(req)
        if admitted:
            return "prefill", admitted
        if self.running:
            return "decode", list(self.running)
        return "idle", []

    def ensure_decode_blocks(self) -> List[Request]:
        """Pre-decode block growth: every running request about to write
        at a block boundary gets one block, preempting from the back of
        the admission order when the pool is dry. Returns the requests
        that actually decode this iteration (preemption victims drop
        out — including, worst case, the requester itself)."""
        stepped: List[Request] = []
        for req in list(self.running):
            if req.status != "running":
                continue  # preempted as an earlier request's victim
            pos = req.cached_tokens()
            n_blocks = len(self.cache.slot_blocks(req.slot))
            if pos == n_blocks * self.cache.block_size:
                got = self._alloc_with_preemption(1, req)
                if got is None:
                    continue  # req itself was the last resort victim
                self.cache.extend(req.slot, got)
            stepped.append(req)
        return stepped

    def _alloc_with_preemption(self, n: int, requester: Request):
        while True:
            got = self.cache.pool.alloc(n)
            if got is not None:
                return got
            victim = self.running[-1]
            self.preempt(victim)
            if victim is requester:
                return None

    # -- state transitions -------------------------------------------------

    def preempt(self, victim: Request) -> None:
        """Recompute-preemption: free everything, requeue at the FRONT so
        re-admission preserves arrival order among the preempted."""
        self._vacate(victim)
        victim.status = "waiting"
        victim.preemptions += 1
        self.n_preemptions += 1
        self.waiting.appendleft(victim)

    def retire(self, req: Request) -> None:
        self._vacate(req)
        req.status = "finished"

    def _vacate(self, req: Request) -> None:
        self.cache.release(req.slot)
        self._free_slots.append(req.slot)
        self._free_slots.sort()
        req.slot = None
        self.running.remove(req)
