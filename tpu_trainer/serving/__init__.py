"""Serving engine: continuous batching over a paged KV cache.

The inference-side counterpart of the training stack (ROADMAP open item
1): ``eval/infer.py`` drives one contiguous-cache ``generate_kv`` call
per batch; this package turns the same model into an engine that serves
a *request stream* —

- ``paged_cache``  — vLLM-style block pool: KV memory in fixed-size
  blocks with per-request block tables and free-list reclaim, so cache
  HBM scales with tokens actually held, not slots * context limit.
- ``scheduler``    — Orca-style iteration-level scheduling: admission by
  free-block budget, prefill/decode interleaving, EOS/max-token
  retirement, recompute-preemption when the pool runs dry.
- ``sampling``     — batched per-request sampling (temperature / top-k /
  seed), deterministic per (seed, token index) so preempted requests
  resume with identical continuations.
- ``spec``         — speculative decoding: model-free n-gram or small
  draft-model proposers drafting K tokens that ONE target forward
  verifies over the paged cache (write-ahead + host rewind), greedy
  streams bit-identical to non-speculative decode.
- ``engine``       — one replica: jitted prefill/decode steps over the
  paged model path (``GPTConfig.decode_paged``), latency/throughput
  stats, and a ``python -m tpu_trainer.serving.engine`` CLI replaying a
  seeded open-loop Poisson arrival trace.
- ``frontend``     — the request tier above N engine replicas:
  prefix-affinity routing (same chained block digests as the prefix
  index, rendezvous-hashed over the live set), bounded queues with
  reject-at-submit backpressure, replica failover with token-identical
  resume, and capacity-file driven grow/shrink.
- ``remote`` / ``worker`` — cross-process replicas: ``worker`` runs one
  engine per OS process behind a length-prefixed JSON RPC socket;
  ``remote`` provides the drop-in ``RemoteReplica`` adapter and the
  ``WorkerSupervisor`` (heartbeat + exit-code death detection, real
  ``SIGKILL`` drills) that plugs into ``ServingFrontend`` as its
  ``replica_factory`` — same routing/admission/failover logic, one
  front-end clock domain spanning the process fleet.
"""

from tpu_trainer.serving.engine import ServingEngine, poisson_trace  # noqa: F401
from tpu_trainer.serving.frontend import (  # noqa: F401
    LocalReplica,
    ServingFrontend,
    SubmitResult,
)
from tpu_trainer.serving.remote import (  # noqa: F401
    RemoteReplica,
    ReplicaDied,
    WorkerSupervisor,
)
from tpu_trainer.serving.paged_cache import BlockPool, PagedKVCache  # noqa: F401
from tpu_trainer.serving.scheduler import (  # noqa: F401
    Request,
    SamplingParams,
    Scheduler,
)
from tpu_trainer.serving.spec import (  # noqa: F401
    AdaptiveK,
    DraftModelProposer,
    NGramProposer,
    SpecDecoder,
    draft_from_target,
)
