"""Cross-process serving replicas: wire protocol, remote adapter, and
worker-process supervision.

This is the front-end half of the worker-process runtime
(``serving/worker.py`` is the other half). It turns the multi-replica
front-end's "replica" from an in-process object into an OS process
reached over a socket, WITHOUT touching the routing / admission /
failover logic: ``RemoteReplica`` implements the exact narrow surface
``ServingFrontend`` consumes (submit, step, load counters, export,
release) and ``ServingFrontend(replica_factory=WorkerSupervisor(...))``
is the only wiring change.

**Wire protocol** — length-prefixed JSON frames over a unix socket
(TCP opt-in)::

    +----------------+---------------------------+
    | 4 bytes        | <len> bytes               |
    | big-endian len | UTF-8 JSON payload        |
    +----------------+---------------------------+

Requests are ``{"id", "method", ...params}``; responses are
``{"id", "ok": true, "result"}`` or ``{"id", "ok": false, "error":
{"type", "msg"}}``. A torn or oversized frame is a ``FrameError``: the
worker closes that connection (and keeps accepting); the client marks
the replica dead (``ReplicaDied``) — neither side wedges.

**Why the front-end's cached load snapshot is EXACT, not stale**: the
worker is a pure RPC reactor — its engine only mutates inside a
submit/step/export/reset handler, never on its own clock. Every
response therefore carries a ``load`` snapshot (queue depth,
outstanding tokens, oldest waiting *arrival time* — time-independent,
so wait AGE is computed client-side against the front-end clock) that
is correct until the front-end's own next RPC. Routing and admission
read the snapshot with zero extra round-trips.

**Failover state lives on the front-end side**: ``RemoteReplica`` keeps
the caller's ``Request`` objects as mirrors and applies the worker's
per-step token deltas to them in place, so the objects the caller
submitted are the objects that come back finished — and when a worker
is SIGKILL'd mid-flight, the mirrors ARE the export: runtime state is
reset exactly like ``Scheduler.export_requests`` (status waiting, slot
None, cursors zeroed, sorted by ``(arrival_time, rid)``) and the
``(seed, token_index)`` sampling state crosses the wire verbatim, so
the resumed stream on a survivor is bit-identical to an undisturbed
run. Tokens a worker generated but never managed to report are simply
re-generated — same key, same tokens.

``WorkerSupervisor`` reuses the elastic trainer's death machinery:
``utils/flight_recorder`` heartbeats (one file per worker, beaten every
RPC-loop wakeup) detect a wedged-but-alive worker by flatline, and
``proc.poll()`` detects real deaths by exit code — the same two signals
``training/elastic.py`` uses for hosts. Deaths are reported once and
drive ``ServingFrontend.kill_replica``'s existing failover path.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional

from tpu_trainer.serving.scheduler import Request, SamplingParams
from tpu_trainer.utils.flight_recorder import read_heartbeat

_HEADER = struct.Struct(">I")
MAX_FRAME_BYTES = 1 << 26   # 64 MiB: a garbage length prefix must not OOM us
# Length-prefix high bit marks a BINARY frame (raw bytes, no JSON): the
# KV-block transport for the kv_put/kv_get verbs and migration tails.
# Binary frames only ever follow a JSON frame that announced them
# (``nframes``), so the two kinds never have to be disambiguated blind.
_BINARY_BIT = 0x8000_0000
# A JSON frame may announce at most this many attached binary frames —
# a garbage ``nframes`` must not make the reactor read forever.
MAX_ATTACHED_FRAMES = 64


class FrameError(Exception):
    """Torn, oversized, or non-JSON frame — the connection is poisoned
    and must be closed (the stream has no way to resynchronise)."""


class ReplicaDied(RuntimeError):
    """The worker behind a ``RemoteReplica`` is unreachable (killed,
    exited, or sent a poisoned frame)."""


# -- framing ---------------------------------------------------------------


def encode_frame(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(f"frame of {len(body)} bytes exceeds max")
    return _HEADER.pack(len(body)) + body


def _recv_exact(sock: socket.socket, n: int, *, start: bytes = b"") -> bytes:
    buf = start
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise FrameError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return buf


def recv_frame(sock: socket.socket):
    """Read one frame. Returns the decoded object, or None on a CLEAN
    EOF (peer closed between frames). Raises ``FrameError`` on a torn
    header/body, a length outside (0, MAX], or a non-JSON payload."""
    first = sock.recv(_HEADER.size)
    if not first:
        return None                     # clean close between frames
    hdr = _recv_exact(sock, _HEADER.size, start=first)
    (length,) = _HEADER.unpack(hdr)
    if length == 0 or length > MAX_FRAME_BYTES:
        raise FrameError(f"bad frame length {length}")
    body = _recv_exact(sock, length)
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"undecodable frame body: {e}") from e


def send_frame(sock: socket.socket, obj) -> None:
    sock.sendall(encode_frame(obj))


def send_binary_frame(sock: socket.socket, payload: bytes) -> None:
    if not payload or len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"binary frame of {len(payload)} bytes out of range")
    sock.sendall(_HEADER.pack(len(payload) | _BINARY_BIT) + payload)


def recv_binary_frame(sock: socket.socket) -> bytes:
    """Read one binary frame (announced by the preceding JSON frame's
    ``nframes``). Raises ``FrameError`` on a torn header/body, a JSON
    frame where binary was promised, or a length outside (0, MAX]."""
    hdr = _recv_exact(sock, _HEADER.size)
    (length,) = _HEADER.unpack(hdr)
    if not (length & _BINARY_BIT):
        raise FrameError("expected a binary frame, got a JSON length")
    n = length & ~_BINARY_BIT
    if n == 0 or n > MAX_FRAME_BYTES:
        raise FrameError(f"bad binary frame length {n}")
    return _recv_exact(sock, n)


# -- KV block wire codec ---------------------------------------------------

# One KV block entry as a self-describing binary payload:
#
#     +-------+---------+--- per leaf, n_leaves times ------------------+
#     | magic | n_leaves| dtype_len | dtype | ndim | dims... | raw_len  |
#     | KVB1  | u16     | u8        | ascii | u8   | u32 each| u32 + raw|
#     +-------+---------+-----------------------------------------------+
#
# Leaves are the pool slices of one block in tree-flatten order
# (pool_k/pool_v, plus scale_k/scale_v for int8 pools) with dtype and
# shape preserved exactly — the raw bytes ARE the device values, so a
# round-trip is bitwise lossless for f32 and int8 alike. The numpy
# import stays lazy: remote.py must stay importable jax/numpy-free on
# the supervisor side.

KV_MAGIC = b"KVB1"
_U8 = struct.Struct(">B")
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")


def encode_kv_block(leaves) -> bytes:
    import numpy as np

    parts = [KV_MAGIC, _U16.pack(len(leaves))]
    for a in leaves:
        a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode("ascii")
        raw = a.tobytes()
        parts.append(_U8.pack(len(dt)))
        parts.append(dt)
        parts.append(_U8.pack(a.ndim))
        parts.append(struct.pack(f">{a.ndim}I", *a.shape))
        parts.append(_U32.pack(len(raw)))
        parts.append(raw)
    body = b"".join(parts)
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(f"kv block of {len(body)} bytes exceeds max frame")
    return body


def decode_kv_block(buf: bytes):
    """Inverse of ``encode_kv_block``. Raises ``FrameError`` on any
    inconsistency (bad magic, torn header, length/shape mismatch,
    trailing garbage) — the caller treats it exactly like a torn
    transport frame: poison the connection, never the process."""
    import numpy as np

    view = memoryview(buf)
    pos = 0

    def take(n: int) -> memoryview:
        nonlocal pos
        if pos + n > len(view):
            raise FrameError(
                f"kv block truncated at byte {pos} (+{n}/{len(view)})")
        out = view[pos:pos + n]
        pos += n
        return out

    if bytes(take(len(KV_MAGIC))) != KV_MAGIC:
        raise FrameError("kv block: bad magic")
    (n_leaves,) = _U16.unpack(take(_U16.size))
    leaves = []
    for _ in range(n_leaves):
        (dt_len,) = _U8.unpack(take(_U8.size))
        try:
            dtype = np.dtype(bytes(take(dt_len)).decode("ascii"))
        except (UnicodeDecodeError, TypeError) as e:
            raise FrameError(f"kv block: bad dtype: {e}") from e
        (ndim,) = _U8.unpack(take(_U8.size))
        shape = struct.unpack(f">{ndim}I", take(4 * ndim))
        (raw_len,) = _U32.unpack(take(_U32.size))
        want = int(dtype.itemsize) * int(np.prod(shape, dtype=np.int64))
        if raw_len != want:
            raise FrameError(
                f"kv block: leaf {dtype}{shape} wants {want} bytes, "
                f"frame carries {raw_len}")
        leaves.append(
            np.frombuffer(take(raw_len), dtype=dtype).reshape(shape).copy())
    if pos != len(view):
        raise FrameError(f"kv block: {len(view) - pos} trailing bytes")
    return leaves


def rpc(sock: socket.socket, req_id: int, method: str, params: dict,
        frames=None):
    """One blocking request/response exchange. Raises ``ReplicaDied``
    when the peer is gone or the stream is poisoned, and re-raises
    worker-side ``ValueError`` as ``ValueError`` (so e.g. a
    too-long-prompt reject behaves exactly like the in-process
    ``Scheduler.add``)."""
    msg = dict(params)
    msg["id"] = req_id
    msg["method"] = method
    if frames:
        msg["nframes"] = len(frames)
    try:
        send_frame(sock, msg)
        for fr in frames or ():
            send_binary_frame(sock, fr)
        resp = recv_frame(sock)
        nresp = int(resp.get("nframes", 0)) if resp else 0
        if nresp < 0 or nresp > MAX_ATTACHED_FRAMES:
            raise FrameError(f"response announces {nresp} binary frames")
        attached = [recv_binary_frame(sock) for _ in range(nresp)]
    except (OSError, FrameError) as e:
        raise ReplicaDied(f"rpc {method!r} failed: {e}") from e
    if resp is None:
        raise ReplicaDied(f"connection closed during rpc {method!r}")
    if resp.get("id") != req_id:
        raise ReplicaDied(
            f"rpc {method!r}: response id {resp.get('id')} != {req_id}")
    if not resp.get("ok"):
        err = resp.get("error") or {}
        if err.get("type") == "ValueError":
            raise ValueError(err.get("msg", "worker ValueError"))
        raise ReplicaDied(f"rpc {method!r}: worker error {err}")
    result = resp.get("result") or {}
    if attached:
        result["_frames"] = attached
    return result


# -- Request wire codec ----------------------------------------------------

# Runtime fields synced by ``request_apply_wire`` (everything that can
# change after construction; identity fields rid/prompt/... stay put).
_RUNTIME_FIELDS = (
    "status", "slot", "preemptions", "first_token_at", "finished_at",
    "admitted_at",
    "prefill_cursor", "prefill_target", "prefill_chunk",
    "prefix_hit_tokens", "spec_drafted", "spec_accepted", "spec_steps",
)


def request_to_wire(req: Request) -> dict:
    """Lossless JSON form of a ``Request`` — sampling state (incl.
    ``top_p``), generated tokens, timestamps, cursors, and the
    prefix-index registration watermark all cross the wire, so a
    failover re-submit on the far side resumes exactly where the
    original stood (the preemption-resume contract, now cross-process)."""
    d = {
        "rid": int(req.rid),
        "prompt": [int(t) for t in req.prompt],
        "max_new_tokens": int(req.max_new_tokens),
        "sampling": dataclasses.asdict(req.sampling),
        "arrival_time": float(req.arrival_time),
        "eos_id": None if req.eos_id is None else int(req.eos_id),
        "deadline": None if req.deadline is None else float(req.deadline),
        "generated": [int(t) for t in req.generated],
        "token_times": [float(t) for t in req.token_times],
        "blocks_registered": int(req._blocks_registered),
    }
    if req._prompt_digests is not None:
        # Hash-once, fleet-wide: the chained block digests computed at
        # submit cross the wire so the worker's admission (and a later
        # migration) never re-hashes the prompt.
        d["prompt_digests"] = [dg.hex() for dg in req._prompt_digests]
    for f in _RUNTIME_FIELDS:
        d[f] = getattr(req, f)
    return d


def request_from_wire(d: dict) -> Request:
    req = Request(
        rid=int(d["rid"]),
        prompt=list(d["prompt"]),
        max_new_tokens=int(d["max_new_tokens"]),
        sampling=SamplingParams(**d["sampling"]),
        arrival_time=float(d["arrival_time"]),
        eos_id=d.get("eos_id"),
        deadline=d.get("deadline"),
    )
    req.generated = list(d.get("generated", ()))
    req.token_times = list(d.get("token_times", ()))
    req._blocks_registered = int(d.get("blocks_registered", 0))
    request_apply_wire(req, d)
    return req


def request_apply_wire(req: Request, d: dict) -> None:
    """Sync a local mirror's runtime state from a wire dict (used when a
    live worker exports: the worker's view is authoritative)."""
    req.generated = list(d.get("generated", req.generated))
    req.token_times = list(d.get("token_times", req.token_times))
    if d.get("prompt_digests") is not None:
        req._prompt_digests = [
            bytes.fromhex(h) for h in d["prompt_digests"]]
    for f in _RUNTIME_FIELDS:
        if f in d:
            setattr(req, f, d[f])


# -- params transport ------------------------------------------------------


def save_params_npz(path: str, params) -> None:
    """Flatten a (possibly nested-Mapping) param tree to ``a/b/c`` keys
    and save as one npz (atomic via tmp + replace). jax-free on purpose:
    the supervisor side must stay importable without an accelerator."""
    import numpy as np

    flat: Dict[str, "np.ndarray"] = {}

    def walk(node, prefix):
        if hasattr(node, "items"):
            for k, v in node.items():
                walk(v, f"{prefix}/{k}" if prefix else str(k))
        else:
            flat[prefix] = np.asarray(node)

    walk(params, "")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, path)


def load_params_npz(path: str) -> dict:
    import numpy as np

    out: dict = {}
    with np.load(path) as z:
        for key in z.files:
            parts = key.split("/")
            cur = out
            for p in parts[:-1]:
                cur = cur.setdefault(p, {})
            cur[parts[-1]] = z[key]
    return out


def _param_nbytes(params) -> int:
    """Logical byte size of a (possibly nested-Mapping) param tree — the
    per-worker wire cost a full-copy (non-sharded) launch pays."""
    import numpy as np

    total = 0

    def walk(node):
        nonlocal total
        if hasattr(node, "items"):
            for v in node.values():
                walk(v)
        else:
            total += int(np.asarray(node).nbytes)

    walk(params)
    return total


# -- transport fault shim (the net_* chaos kinds, utils/faults.py) ---------

NET_DELAY_MS_ENV = "TPU_TRAINER_NET_DELAY_MS"


def _inject_net_fault(kind: str, sock: socket.socket) -> None:
    """Apply one armed fault to the framed transport, in place of (or
    before) the next exchange. ``net_delay`` just adds latency and lets
    the call proceed; the other kinds sabotage the stream the way a real
    network does and raise ``ReplicaDied`` so the caller takes the exact
    failover path an organic transport failure takes."""
    if kind == "net_delay":
        time.sleep(float(os.environ.get(NET_DELAY_MS_ENV, "50")) / 1e3)
        return
    if kind == "net_garble":
        # A correctly-framed body that is not UTF-8: the worker's
        # recv_frame raises FrameError, drops ONLY that connection, and
        # goes back to accept; our read then sees the close.
        try:
            sock.sendall(_HEADER.pack(16) + b"\xff" * 16)
            sock.recv(1)
        except OSError:
            pass
        raise ReplicaDied("injected net_garble: stream poisoned")
    if kind == "net_drop":
        # Torn frame: promise a body, deliver nothing, close. The peer
        # sees EOF mid-frame (FrameError) and drops the connection.
        try:
            sock.sendall(_HEADER.pack(64))
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        raise ReplicaDied("injected net_drop: frame torn mid-send")
    if kind == "net_hang":
        # Dead air: nothing sent, nothing will arrive — the per-call
        # timeout is the only way out (the hung-RPC fence drill without
        # needing to SIGSTOP anything).
        try:
            sock.recv(1)
        except OSError as e:            # socket.timeout is an OSError
            raise ReplicaDied(f"injected net_hang: {e}") from e
        raise ReplicaDied("injected net_hang: unexpected data")
    raise ValueError(f"unknown net fault kind {kind!r}")


# -- the remote replica adapter --------------------------------------------


@dataclasses.dataclass
class WorkerHandle:
    """One spawned worker process plus its control connection."""

    worker_id: int
    proc: object                        # subprocess.Popen (duck-typed in tests)
    sock: Optional[socket.socket]
    log_path: str = ""
    block_size: int = 0
    pid: int = 0
    rid: Optional[int] = None           # front-end replica id, once assigned
    retired: bool = False               # deliberately shut down, not a death
    next_id: int = 0
    # Per-call socket deadlines: every call before the first completed
    # ``step`` may sit behind the worker's engine build or first-step
    # compile, so it gets the compile-scale budget; once a step response
    # has arrived the worker is warm and every later call gets the small
    # per-call timeout — a hung worker then stalls the caller for at most
    # ``rpc_timeout_s``, not 600 s.
    rpc_timeout_s: float = 30.0
    first_call_timeout_s: float = 600.0
    first_step_done: bool = False
    # One-shot armed transport fault (a net_* kind) for the next rpc().
    net_fault: Optional[str] = None

    def rpc(self, method: str, params: Optional[dict] = None, frames=None):
        if self.sock is None:
            raise ReplicaDied(f"worker {self.worker_id}: no connection")
        self.next_id += 1
        timeout = (self.rpc_timeout_s if self.first_step_done
                   else self.first_call_timeout_s)
        try:
            self.sock.settimeout(timeout)
        except OSError as e:
            raise ReplicaDied(
                f"worker {self.worker_id}: socket unusable: {e}") from e
        fault, self.net_fault = self.net_fault, None
        if fault is not None:
            _inject_net_fault(fault, self.sock)
        result = rpc(self.sock, self.next_id, method, params or {},
                     frames=frames)
        if method == "step":
            self.first_step_done = True
        return result

    def close(self, *, grace_s: float = 5.0) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            self.proc.wait(timeout=grace_s)
        except Exception:
            self.proc.kill()
            try:
                self.proc.wait(timeout=grace_s)
            except Exception:
                pass


class RemoteReplica:
    """Drop-in for an in-process replica (``frontend.LocalReplica``):
    same surface, state mutated only by our own RPCs — see the module
    docstring for why the cached ``load`` snapshot is exact."""

    def __init__(self, handle: WorkerHandle, clock: Callable[[], float], *,
                 supervisor: Optional["WorkerSupervisor"] = None):
        self._handle = handle
        self.clock = clock
        self._supervisor = supervisor
        self.dead = False
        self.block_size = handle.block_size
        self._reqs: Dict[int, Request] = {}     # unfinished mirrors
        # Worker-side span events carried home on RPC replies, buffered
        # until the front-end's next drain_span_events() merge.
        self._span_pending: List[dict] = []
        self._load: Dict[str, object] = {
            "queue_depth": 0, "outstanding_tokens": 0, "has_work": False,
            "oldest_arrival": None, "generated_tokens": 0,
            "prefix_hit_tokens": 0, "prompt_tokens": 0, "n_preemptions": 0,
        }
        # Store digests the worker reported as newly put (piggybacked on
        # load snapshots), buffered for the front-end's catalog drain.
        self._kv_new: List[bytes] = []

    @property
    def worker_id(self) -> int:
        return self._handle.worker_id

    @property
    def worker_pid(self) -> int:
        return self._handle.pid

    def _rpc(self, method: str, params: Optional[dict] = None, frames=None):
        if self.dead:
            raise ReplicaDied(
                f"worker {self._handle.worker_id} is already dead")
        try:
            result = self._handle.rpc(method, params, frames=frames)
        except ReplicaDied:
            # The hung-RPC fence: a timed-out or poisoned exchange makes
            # this replica SUSPECT — maybe dead, maybe wedged, maybe
            # about to answer late. The supervisor kills the process so
            # the state is unambiguous BEFORE the caller re-runs the
            # mirrors elsewhere (a wedged worker waking up later and
            # double-generating is the failure this prevents); the
            # raise then rides the exact replica_kill failover path.
            self.dead = True
            if self._supervisor is not None:
                self._supervisor.fence(self._handle)
            raise
        load = result.get("load")
        if load is not None:
            self._load = load
            for h in load.get("kv_new") or ():
                self._kv_new.append(bytes.fromhex(h))
        # Every reply may piggyback the worker tracer's event delta —
        # one wire, no extra round-trips (worker.py drains per handler).
        trace = result.get("trace")
        if trace:
            self._span_pending.extend(trace)
        return result

    def drain_span_events(self) -> List[dict]:
        """Worker span events accumulated off RPC replies since the last
        drain — the delta surface ``frontend.LocalReplica`` exposes from
        its engine tracer, so the front-end merges both transports
        identically. Timestamps are already front-end times (the worker
        clock is the shipped ``now`` with a zero epoch)."""
        out, self._span_pending = self._span_pending, []
        return out

    # -- the replica surface the front-end consumes ------------------------

    def submit(self, req: Request, trace: Optional[List[dict]] = None,
               migration: Optional[dict] = None) -> None:
        params = {"req": request_to_wire(req), "now": self.clock()}
        if trace:
            # Front-door span context (submitted/routed) travels with the
            # request so the worker tracer holds the rid's full timeline.
            params["trace"] = trace
        frames = None
        if migration is not None:
            # Migrated admission: the raw prompt tail (the last partial
            # block, exact K/V bytes) rides a binary frame; full blocks
            # travel separately as digest-addressed kv_put frames.
            params["mig"] = {"tail_ntok": int(migration.get("tail_ntok", 0))}
            if migration.get("leaves") is not None:
                frames = [encode_kv_block(migration["leaves"])]
        self._rpc("submit", params, frames=frames)
        self._reqs[req.rid] = req

    def step(self) -> List[Request]:
        """One frontend-driven engine step on the worker. Ships the
        front-end clock (``now``) — the worker NEVER free-runs a wall
        clock, so one clock domain spans the fleet and ``steps`` mode is
        deterministic cross-process. Token deltas are applied to the
        caller's own ``Request`` objects."""
        result = self._rpc("step", {"now": self.clock()})
        finished: List[Request] = []
        for d in result.get("deltas", ()):
            req = self._reqs.get(d["rid"])
            if req is None:
                continue
            self._apply_delta(req, d)
            if d["done"]:
                finished.append(self._reqs.pop(d["rid"]))
        return finished

    def _apply_delta(self, req: Request, d: dict) -> None:
        req.generated.extend(d["gen"])
        req.token_times.extend(d["times"])
        req.first_token_at = d["first"]
        req.preemptions = d["preempt"]
        req.prefix_hit_tokens = d["hit"]
        req.spec_drafted, req.spec_accepted, req.spec_steps = d["spec"]
        req.status = d["status"]
        if d["done"]:
            req.finished_at = d["finished_at"]

    def cancel(self, rid: int) -> bool:
        """Cancel on the worker: its engine frees the request's slot and
        blocks before the response is framed, the terminal delta lands
        on the mirror HERE, and the rid never appears in a later step
        delta — so in-process and RPC replicas retire identically."""
        if rid not in self._reqs:
            return False
        result = self._rpc("cancel", {"rid": rid, "now": self.clock()})
        if not result.get("cancelled"):
            return False
        req = self._reqs.pop(rid)
        d = result.get("delta")
        if d:
            self._apply_delta(req, d)
        else:
            req.status = "cancelled"
        return True

    def inject_net_fault(self, kind: str) -> None:
        """Arm a one-shot transport fault (a ``net_*`` chaos kind) on
        this replica's next RPC."""
        self._handle.net_fault = kind

    # -- KV store / disaggregation verbs -----------------------------------

    def kv_put(self, digest: bytes, leaves) -> bool:
        """Push one block entry into the worker's local store (binary
        frame attached to the JSON verb). Idempotent like the store."""
        result = self._rpc("kv_put", {"digest": digest.hex()},
                           frames=[encode_kv_block(leaves)])
        return bool(result.get("stored"))

    def kv_get(self, digest: bytes):
        """``(tier, leaves)`` from the worker's store, or None."""
        result = self._rpc("kv_get", {"digest": digest.hex()})
        if not result.get("found"):
            return None
        return result["tier"], decode_kv_block(result["_frames"][0])

    def kv_has(self, digests) -> List[bool]:
        result = self._rpc("kv_has",
                           {"digests": [d.hex() for d in digests]})
        return [bool(b) for b in result.get("has", ())]

    def set_role(self, role: Optional[str]) -> None:
        self._rpc("set_role", {"role": role})

    def migratable_rids(self) -> List[int]:
        """Prefill-complete rids from the worker's last load snapshot —
        exact between our own RPCs, like every other load field."""
        return [int(r) for r in self._load.get("migratable") or ()]

    def drain_new_digests(self) -> List[bytes]:
        out, self._kv_new = self._kv_new, []
        return out

    def extract(self, rid: int):
        """Pull one prefill-complete request off the worker for
        migration: the worker vacates it (slot + blocks freed, full
        blocks already in its store via write-through) and ships the
        authoritative request state plus the raw prompt-tail block.
        Returns ``(req, payload)`` or None; the mirror is popped — the
        request now belongs to whichever replica it is resubmitted to."""
        result = self._rpc("extract", {"rid": rid, "now": self.clock()})
        if not result.get("found"):
            return None
        d = result["req"]
        req = self._reqs.pop(rid, None)
        if req is None:
            req = request_from_wire(d)
        else:
            request_apply_wire(req, d)
        payload = {"tail_ntok": int(result.get("tail_ntok", 0)),
                   "leaves": None}
        if payload["tail_ntok"] and result.get("_frames"):
            payload["leaves"] = decode_kv_block(result["_frames"][0])
        return req, payload

    def has_work(self) -> bool:
        return bool(self._load["has_work"])

    @property
    def queue_depth(self) -> int:
        return int(self._load["queue_depth"])

    @property
    def outstanding_tokens(self) -> int:
        return int(self._load["outstanding_tokens"])

    def oldest_wait_age(self, now: float) -> float:
        arr = self._load.get("oldest_arrival")
        if arr is None:
            return 0.0
        return max(0.0, now - float(arr))

    def export_requests(self, *, waiting_only: bool = False) -> List[Request]:
        """Drain requeueable requests. Live worker: the worker's export
        is authoritative (preemption counts etc. sync onto the
        mirrors). Dead worker: the mirrors are the export, reset to the
        exact ``Scheduler.export_requests`` contract — this is the
        SIGKILL failover path."""
        if not self.dead:
            try:
                result = self._rpc("export", {"waiting_only": waiting_only})
                out: List[Request] = []
                for d in result.get("requests", ()):
                    req = self._reqs.pop(d["rid"], None)
                    if req is None:        # shouldn't happen; keep honest
                        req = request_from_wire(d)
                    else:
                        request_apply_wire(req, d)
                    out.append(req)
                return out
            except ReplicaDied:
                pass
        out = []
        for req in self._reqs.values():
            req.status = "waiting"
            req.slot = None
            req.prefill_cursor = 0
            req.prefill_target = 0
            req.prefill_chunk = 0
            out.append(req)
        self._reqs.clear()
        return sorted(out, key=lambda r: (r.arrival_time, r.rid))

    def metrics_snapshot(self) -> dict:
        """Pull the worker engine's registry snapshot over the
        ``metrics`` verb — plain JSON scalars, callbacks already
        resolved worker-side. The front-end merges it label-wise
        (``replica=N``) into its own registry. MAIN-thread only, like
        every RPC here: the scrape thread must never touch the
        socket."""
        return self._rpc("metrics").get("metrics", {})

    def release(self) -> None:
        """Tear the worker down (graceful shutdown RPC when reachable,
        then reap the process). A deliberate release is marked retired
        so the supervisor does not report it as a death."""
        self._handle.retired = True
        if not self.dead:
            try:
                self._rpc("shutdown")
            except (ReplicaDied, ValueError):
                pass
            self.dead = True
        self._handle.close()

    # -- counters mirrored for fleet telemetry -----------------------------

    @property
    def generated_tokens(self) -> int:
        return int(self._load["generated_tokens"])

    @property
    def prefix_hit_tokens(self) -> int:
        return int(self._load["prefix_hit_tokens"])

    @property
    def prompt_tokens(self) -> int:
        return int(self._load["prompt_tokens"])

    @property
    def n_preemptions(self) -> int:
        return int(self._load["n_preemptions"])

    @property
    def store_hit_tokens_host(self) -> int:
        return int(self._load.get("store_hit_tokens_host", 0))

    @property
    def store_hit_tokens_disk(self) -> int:
        return int(self._load.get("store_hit_tokens_disk", 0))


# -- supervision -----------------------------------------------------------

# The worker beats its heartbeat on every RPC-loop wakeup (0.5 s select
# timeout; writes throttled to 0.2 s), so a healthy worker's beat stream
# never gaps past ~1 s while it is idle or reachable. A worker is only
# ever busy inside an RPC handler the front-end is itself blocked on —
# the supervisor cannot be polling a worker mid-compile — so 20x the
# wakeup cadence is far past any legitimate gap while still fencing a
# wedged-but-alive worker out of the box (the SIGSTOP failure mode exit
# codes can never catch).
_WORKER_LOOP_WAKEUP_S = 0.5
DEFAULT_HEARTBEAT_TIMEOUT_S = 20 * _WORKER_LOOP_WAKEUP_S
# Sentinel: "derive the default" (None must stay a meaningful value —
# the explicit detection opt-out).
_AUTO = "auto"


class WorkerSupervisor:
    """Launches and watches worker processes; IS the front-end's
    ``replica_factory`` (callable ``(rid, clock) -> RemoteReplica``).

    Death detection mirrors ``training/elastic.py``: a worker is dead
    when its process exited (``proc.poll()`` — a SIGKILL shows up here
    by exit code) or when its heartbeat file has flatlined for longer
    than ``heartbeat_timeout_s`` (a wedged-but-alive process; the
    supervisor SIGKILLs it on detection so the state is unambiguous).
    ``poll_deaths`` reports each death exactly once; the front-end turns
    each report into its existing ``kill_replica`` failover.

    ``reset()`` implements warm A/B benching: every live worker rebuilds
    a fresh engine in place (the jitted step is memoised per config
    inside the process, so no recompile) and returns to the spawn pool —
    the next front-end built over this supervisor adopts warm processes
    with clean serving state.
    """

    def __init__(self, params, config, *, engine_kwargs=None,
                 run_dir: Optional[str] = None,
                 heartbeat_timeout_s=_AUTO,
                 connect_timeout_s: float = 240.0,
                 rpc_timeout_s: float = 30.0,
                 first_step_timeout_s: float = 600.0,
                 tcp: bool = False,
                 param_shard_world: Optional[int] = None,
                 device_sets=None,
                 launch_prefix=None):
        if heartbeat_timeout_s == _AUTO:
            heartbeat_timeout_s = DEFAULT_HEARTBEAT_TIMEOUT_S
        # None = explicit opt-out of flatline detection (exit codes only).
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.first_step_timeout_s = float(first_step_timeout_s)
        self.n_fenced = 0
        self.tcp = tcp
        if run_dir is None or len(run_dir) > 70:
            # unix socket paths are capped near 108 bytes — keep ours short
            run_dir = tempfile.mkdtemp(prefix="tt-workers-")
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self.heartbeat_dir = os.path.join(run_dir, "hb")
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        self._params_path = os.path.join(run_dir, "params.npz")
        self._shards_path = os.path.join(run_dir, "param_shards")
        self._spec_path = os.path.join(run_dir, "spec.json")
        # Shard-streaming launch (``param_shard_world``): instead of one
        # full npz every worker re-reads, the tree is written ONCE as a
        # ``world``-way host_shards export (utils/checkpoint.py) — the
        # per-worker shard file is ~P/world bytes, which is what crosses
        # the wire to a remote host (via the existing TCP transport +
        # ``launch_prefix``); on a shared filesystem the worker stitches
        # all shard files back locally. ``param_bytes_full`` /
        # ``param_shard_bytes`` expose the two wire costs for bench
        # records. jax-free unless the sharded path is taken (the
        # checkpoint import is lazy).
        self.launch_prefix = list(launch_prefix or [])
        self.param_shard_world = (
            int(param_shard_world) if param_shard_world else None)
        self.param_bytes_full = 0
        self.param_shard_bytes: Optional[List[int]] = None
        params_shards = None
        if params is not None and self.param_shard_world:
            from tpu_trainer.utils.checkpoint import export_param_shards

            export_param_shards(
                params, self._shards_path, world=self.param_shard_world)
            params_shards = self._shards_path
            sdir = os.path.join(self._shards_path, "shards")
            self.param_shard_bytes = [
                os.path.getsize(os.path.join(sdir, f"host{h:05d}.npz"))
                for h in range(self.param_shard_world)]
            self.param_bytes_full = _param_nbytes(params)
        elif params is not None:
            save_params_npz(self._params_path, params)
            self.param_bytes_full = os.path.getsize(self._params_path)
        # One PRNG scheme spans the fleet: the partitionable-threefry
        # flag changes sampled bit streams, so the worker must run with
        # the front-end process's setting or sampled streams lose
        # cross-process bit-identity. Read via sys.modules — this module
        # stays importable without jax.
        jax_cfg = {}
        jaxm = sys.modules.get("jax")
        if jaxm is not None:
            jax_cfg["threefry_partitionable"] = bool(
                jaxm.config.jax_threefry_partitionable)
        spec = {
            "config": dataclasses.asdict(config) if config is not None else {},
            "engine": dict(engine_kwargs or {}),
            "params_npz": self._params_path,
            "jax": jax_cfg,
        }
        if params_shards is not None:
            spec["params_shards"] = params_shards
        if device_sets is not None:
            # Per-worker device sets (disjoint meshes over one host's
            # devices): worker ``wid`` takes ``device_sets[wid % len]``
            # as its ``mesh_devices``. Top-level in the spec — engine
            # kwargs are scalar-only on the wire.
            spec["device_sets"] = [
                [int(d) for d in ds] for ds in device_sets]
        for k, v in spec["engine"].items():
            if not isinstance(v, (int, float, str, bool, type(None))):
                raise ValueError(
                    f"engine kwarg {k!r} is not wire-able: {type(v)}")
        with open(self._spec_path, "w") as f:
            json.dump(spec, f)
        self._handles: Dict[int, WorkerHandle] = {}   # by front-end rid
        self._pool: List[WorkerHandle] = []           # warm, unassigned
        self._spawned = 0
        self._reported_dead: set = set()

    # -- factory surface ---------------------------------------------------

    def __call__(self, rid: int, clock: Callable[[], float]) -> RemoteReplica:
        handle = self._pool.pop(0) if self._pool else self._spawn()
        handle.rid = rid
        self._handles[rid] = handle
        return RemoteReplica(handle, clock, supervisor=self)

    # kept as an explicit alias so call sites can say what they mean
    def replica_factory(self, rid: int, clock) -> RemoteReplica:
        return self(rid, clock)

    def prewarm(self, n: int) -> None:
        """Spawn ``n`` workers CONCURRENTLY into the pool: all processes
        launch first (their jax imports and engine builds overlap), then
        each is connected and handshaken. The front-end's sequential
        ``replica_factory`` calls then adopt warm workers, so fleet
        startup costs ~one worker build instead of N."""
        launched = [self._launch() for _ in range(n)]
        for wid, proc, log_path in launched:
            self._pool.append(self._handshake(wid, proc, log_path))

    def _spawn(self) -> WorkerHandle:
        return self._handshake(*self._launch())

    def _launch(self):
        wid = self._spawned
        self._spawned += 1
        log_path = os.path.join(self.run_dir, f"worker{wid}.log")
        cmd = [sys.executable, "-m", "tpu_trainer.serving.worker",
               "--spec", self._spec_path,
               "--heartbeat-dir", self.heartbeat_dir,
               "--worker-id", str(wid)]
        if self.tcp:
            cmd += ["--tcp", "127.0.0.1:0", "--addr-file",
                    os.path.join(self.run_dir, f"worker{wid}.addr")]
        else:
            cmd += ["--socket", os.path.join(self.run_dir, f"w{wid}.sock")]
        if self.launch_prefix:
            # e.g. ["ssh", "host"] (remote launch over the TCP transport
            # + a shared run_dir) or an env wrapper for the fake-device
            # CPU mesh; the worker command itself is unchanged.
            cmd = self.launch_prefix + cmd
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(cmd, stdout=log, stderr=log)
        return wid, proc, log_path

    def _handshake(self, wid: int, proc, log_path: str) -> WorkerHandle:
        # Bounded retry with backoff — for the IDEMPOTENT handshake only.
        # A torn accept or ECONNRESET between connect and hello is a
        # transient (the worker is still coming up and still listening);
        # reconnecting and re-saying hello is always safe. Non-idempotent
        # in-flight calls (step/submit) are NEVER retried anywhere: their
        # response may have been lost AFTER the worker advanced, and a
        # replay would double-generate — those errors fence and fail
        # over instead (RemoteReplica._rpc).
        last: Optional[Exception] = None
        for attempt in range(3):
            try:
                sock = self._connect(wid, proc)
            except Exception:
                proc.kill()
                raise
            handle = WorkerHandle(
                worker_id=wid, proc=proc, sock=sock, log_path=log_path,
                rpc_timeout_s=self.rpc_timeout_s,
                first_call_timeout_s=self.first_step_timeout_s)
            try:
                hello = handle.rpc("hello")
            except ReplicaDied as e:
                last = e
                try:
                    sock.close()
                except OSError:
                    pass
                time.sleep(0.05 * (2 ** attempt))
                continue
            handle.block_size = int(hello["block_size"])
            handle.pid = int(hello["pid"])
            return handle
        proc.kill()
        raise RuntimeError(
            f"worker {wid}: handshake failed after 3 attempts "
            f"(see {log_path}): {last}")

    def _connect(self, wid: int, proc) -> socket.socket:
        deadline = time.monotonic() + self.connect_timeout_s
        addr_file = os.path.join(self.run_dir, f"worker{wid}.addr")
        sock_path = os.path.join(self.run_dir, f"w{wid}.sock")
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker {wid} exited rc={proc.returncode} before "
                    f"accepting (see {self.run_dir}/worker{wid}.log)")
            try:
                if self.tcp:
                    with open(addr_file) as f:
                        host, port = f.read().strip().rsplit(":", 1)
                    s = socket.create_connection((host, int(port)), timeout=5)
                else:
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.connect(sock_path)
                # Initial budget only: WorkerHandle.rpc re-arms the
                # timeout per call (compile-scale until the first step
                # response, small per-call after — see WorkerHandle).
                s.settimeout(self.first_step_timeout_s)
                return s
            except (OSError, FileNotFoundError, ValueError):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"worker {wid}: no socket within "
                        f"{self.connect_timeout_s}s")
                time.sleep(0.05)

    # -- death detection ---------------------------------------------------

    def sigkill(self, rid: Optional[int] = None) -> int:
        """Hard-kill one worker process (the ``worker_kill`` fault).
        Target: ``TPU_TRAINER_FAULT_REPLICA`` env override, else the
        highest assigned live rid — the same convention as
        ``replica_kill``. Waits for the exit to settle so the very next
        ``poll_deaths`` reports it deterministically."""
        cands = {r: h for r, h in self._handles.items()
                 if not h.retired and h.proc.poll() is None}
        if not cands:
            raise RuntimeError("no live workers to kill")
        if rid is None:
            raw = os.environ.get("TPU_TRAINER_FAULT_REPLICA")
            rid = int(raw) if raw is not None else max(cands)
        if rid not in cands:
            raise ValueError(f"worker for replica {rid} is not alive")
        h = cands[rid]
        os.kill(h.proc.pid, signal.SIGKILL)
        try:
            h.proc.wait(timeout=10)
        except Exception:
            pass
        return rid

    def sigstop(self, rid: Optional[int] = None) -> int:
        """Freeze one worker process (the ``worker_hang`` fault):
        SIGSTOP leaves it alive — exit-code detection can never see it —
        but wedged, so its heartbeat flatlines and any RPC to it hangs
        until the per-call timeout fences it. Same targeting convention
        as ``sigkill``."""
        cands = {r: h for r, h in self._handles.items()
                 if not h.retired and h.proc.poll() is None}
        if not cands:
            raise RuntimeError("no live workers to hang")
        if rid is None:
            raw = os.environ.get("TPU_TRAINER_FAULT_REPLICA")
            rid = int(raw) if raw is not None else max(cands)
        if rid not in cands:
            raise ValueError(f"worker for replica {rid} is not alive")
        os.kill(cands[rid].proc.pid, signal.SIGSTOP)
        return rid

    def fence(self, handle: WorkerHandle) -> None:
        """Make a SUSPECT worker unambiguously dead. Called by
        ``RemoteReplica._rpc`` when an exchange times out or the stream
        poisons: the process may be wedged, half-connected, or about to
        answer late — SIGKILL (which lands on a SIGSTOPped process too)
        guarantees it can never wake up and double-generate after its
        requests have been re-run on a survivor. The death report is
        swallowed (``_reported_dead``): the caller that hit the error IS
        the failover path, so ``poll_deaths`` must not re-report it."""
        self.n_fenced += 1
        if handle.rid is not None:
            self._reported_dead.add(handle.rid)
        if handle.retired or handle.proc.poll() is not None:
            return
        try:
            handle.proc.kill()
            handle.proc.wait(timeout=10)
        except Exception:
            pass

    def poll_deaths(self) -> List[int]:
        """Replica ids whose worker died since the last poll (exit code
        OR heartbeat flatline), each reported exactly once."""
        dead: List[int] = []
        now = time.time()
        for rid, h in self._handles.items():
            if h.retired or rid in self._reported_dead:
                continue
            if h.proc.poll() is not None:
                dead.append(rid)
                continue
            if self.heartbeat_timeout_s is not None:
                beat = read_heartbeat(self.heartbeat_dir, h.worker_id)
                if beat is not None and (
                        now - float(beat.get("unix", now))
                        > self.heartbeat_timeout_s):
                    h.proc.kill()       # settle the wedged process
                    dead.append(rid)
        self._reported_dead.update(dead)
        return dead

    def live_worker_count(self) -> int:
        return sum(1 for h in list(self._handles.values()) + self._pool
                   if not h.retired and h.proc.poll() is None)

    # -- lifecycle ---------------------------------------------------------

    def reset(self) -> None:
        """Return every live assigned worker to the pool with a fresh
        engine (process + compile cache kept). Dead/retired handles are
        dropped."""
        for rid, h in list(self._handles.items()):
            if h.retired or h.proc.poll() is not None:
                continue
            try:
                h.rpc("reset")
            except (ReplicaDied, ValueError):
                h.close(grace_s=1.0)
                continue
            h.rid = None
            self._pool.append(h)
        self._handles.clear()
        self._reported_dead.clear()

    def close(self) -> None:
        for h in list(self._handles.values()) + self._pool:
            if not h.retired and h.sock is not None:
                try:
                    h.rpc("shutdown")
                except (ReplicaDied, ValueError):
                    pass
            h.retired = True
            h.close(grace_s=2.0)
        self._handles.clear()
        self._pool.clear()

    def __enter__(self) -> "WorkerSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
