"""Tensor-parallel (head-sharded) layout helpers for the serving engine.

One replica = one mesh: a single-axis ``("tp",)`` device mesh over which
the paged KV pools are sharded on their *kv-heads* axis with
``NamedSharding``, while block tables, lengths and offsets stay
replicated host mirrors (scheduling never syncs the device — unchanged).
Params are committed to the mesh *sharded* on each leaf's largest
tp-divisible axis (persistent per-device bytes ~ P/tp) and gathered back
to replicated *inside* the jitted step with a sharding constraint: the
all-gather is an exact concatenation, so every matmul downstream sees
bit-identical operands to the single-device engine — which is what makes
the sharded greedy streams token-identical by construction rather than
by tolerance.

GQA composes the same way ``generate_kv``'s TP path does: when
``kv_heads < tp`` the KV pools are replicated (every device holds all kv
heads) and only the Q heads are sharded — each device's contiguous
Q-head slice attends to exactly one kv head, selected inside the
``shard_map`` body by ``axis_index // (tp // kv_heads)``.

All helpers are no-ops / identities at ``tp == 1`` so the single-device
engine never pays for them.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TP_AXIS = "tp"

# Cache-collection leaves sharded on their kv-heads axis (axis 2). Every
# other cache leaf (tables / lengths / offsets) replicates — they are the
# host-mirror scheduling state.
_POOL_LEAVES = ("pool_k", "pool_v", "scale_k", "scale_v")


def validate_tp(num_heads: int, kv_heads: int, tp: int) -> None:
    """The head-sharding feasibility rule: Q heads split evenly over the
    mesh, and KV heads either split evenly too or are replicated with
    whole Q-head groups per device (``tp % kv_heads == 0``)."""
    if tp < 1:
        raise ValueError(f"paged_tp={tp} < 1")
    if tp == 1:
        return
    if num_heads % tp:
        raise ValueError(
            f"paged_tp={tp} does not divide num_heads={num_heads}")
    if kv_heads % tp and tp % kv_heads:
        raise ValueError(
            f"paged_tp={tp} vs kv_heads={kv_heads}: need kv_heads % tp "
            f"== 0 (sharded KV) or tp % kv_heads == 0 (replicated KV, "
            f"GQA)")


def resolve_devices(tp: int,
                    device_ids: Optional[Sequence[int]] = None) -> tuple:
    """The device set backing a tp-way mesh: explicit ids when the worker
    spec names them (one fleet, disjoint meshes), else the first ``tp``
    visible devices."""
    devs = jax.devices()
    if device_ids:
        by_id = {d.id: d for d in devs}
        missing = [i for i in device_ids if i not in by_id]
        if missing:
            raise ValueError(
                f"device ids {missing} not visible (have "
                f"{sorted(by_id)}); is XLA_FLAGS="
                f"--xla_force_host_platform_device_count set?)")
        devs = [by_id[int(i)] for i in device_ids]
    if len(devs) < tp:
        raise ValueError(f"paged_tp={tp} > {len(devs)} visible devices")
    return tuple(devs[:tp])


@functools.lru_cache(maxsize=None)
def tp_mesh(tp: int,
            device_ids: Optional[Tuple[int, ...]] = None) -> Mesh:
    """The (cached) single-axis decode mesh. Caching matters twice over:
    mesh construction is not free, and the jitted-step memo keys on the
    config's ``(paged_tp, paged_tp_devices)`` — one mesh object per key
    keeps placements stable across steps."""
    return Mesh(np.array(resolve_devices(tp, device_ids)), (TP_AXIS,))


def kv_sharded(kv_heads: int, tp: int) -> bool:
    """True when the KV pools shard over heads (the capacity win); False
    in GQA-replicate mode (``tp % kv_heads == 0``), where every device
    holds the full pools."""
    return tp > 1 and kv_heads % tp == 0


def shard_factor(kv_heads: int, tp: int) -> int:
    """Pool capacity multiplier: with kv-head-sharded pools each block
    costs 1/tp of its single-device bytes per device, so a per-device
    block budget B affords B*tp pool blocks. Replicated (GQA) pools gain
    nothing."""
    return tp if kv_sharded(kv_heads, tp) else 1


def _cache_spec(key: Optional[str], kv_heads: int, tp: int) -> P:
    if key in _POOL_LEAVES and kv_sharded(kv_heads, tp):
        return P(None, None, TP_AXIS, None)
    return P()


def shard_cache(cache, mesh: Mesh, kv_heads: int):
    """Commit a freshly initialized cache collection to the mesh: pools
    (and int8 scales) sharded on their kv-heads axis when divisible,
    everything replicated otherwise. Committed placement is what lets
    jit leave uncommitted per-step inputs (tables, ids, ...) to implicit
    replication."""
    tp = mesh.devices.size

    def put(path, leaf):
        spec = _cache_spec(getattr(path[-1], "key", None), kv_heads, tp)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(put, cache)


def constrain_cache(cache, mesh: Mesh, kv_heads: int):
    """The in-jit twin of ``shard_cache``: pin the step's output cache to
    the same layout so the pool scatter's result stays sharded instead of
    drifting to whatever GSPMD infers."""
    tp = mesh.devices.size

    def pin(path, leaf):
        spec = _cache_spec(getattr(path[-1], "key", None), kv_heads, tp)
        return jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(pin, cache)


def pick_shard_axis(shape: Sequence[int], tp: int) -> Optional[int]:
    """Device-placement rule for a param leaf: the largest axis ``tp``
    divides evenly (ties -> lowest axis index), or None to replicate.
    Deterministic so every engine in a fleet commits the same layout."""
    best = None
    for ax, n in enumerate(shape):
        if n % tp == 0 and (best is None or n > shape[best]):
            best = ax
    return best


def param_spec(shape: Sequence[int], tp: int) -> P:
    ax = pick_shard_axis(shape, tp) if tp > 1 else None
    if ax is None:
        return P()
    spec = [None] * len(shape)
    spec[ax] = TP_AXIS
    return P(*spec)


def shard_params(params, mesh: Mesh):
    """Commit params to the mesh sharded per ``param_spec`` — the
    persistent-HBM side of the capacity story (~P/tp resident bytes per
    device; the step's gather is transient)."""
    tp = mesh.devices.size
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, param_spec(np.shape(x), tp))),
        params)


def gather_params(params, mesh: Mesh):
    """Inside the jitted step: constrain every param leaf to replicated.
    GSPMD lowers this to an all-gather of contiguous shards — an exact
    concatenation, no arithmetic — so the compute that follows is
    bitwise the single-device compute."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P())),
        params)
