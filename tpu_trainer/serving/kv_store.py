"""Fleet-wide digest-addressed tiered KV block store.

The chained blake2b block digests (``paged_cache.chained_block_digests``)
are exact content addresses: a digest pins the block's tokens AND its
whole left context, so two engines that computed the same digest hold
bit-identical K/V for that block (same reduce order, same quantization).
That makes the digest a safe fleet-wide cache key — this module is the
tier behind every engine's device pool:

    device pool (HBM)  ->  host DRAM tier (byte-budgeted LRU)
                       ->  optional disk tier (npz files, LRU)

``PagedKVCache`` spills refcount-1 prefix-index blocks here on LRU
eviction instead of destroying them, and ``prefix_lookup`` falls through
a device-index miss to a store hit, filling a fresh device block — so
admission skips prefill for any block the *fleet* has ever computed.
In-process replicas share one ``KVBlockStore`` object; cross-process
workers each hold a local store synchronized over the ``kv_put`` /
``kv_get`` / ``kv_has`` RPC verbs (serving/remote.py).

Tiers are exclusive: a disk hit promotes the entry to the host tier and
removes the file; host eviction writes it back out. Entries are lists of
numpy arrays — one per pool leaf of one block (pool_k/pool_v and, for
int8 pools, scale_k/scale_v, in the device cache's tree-flatten order),
dtype and shape preserved exactly, so fill-then-read round-trips
bitwise.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


def leaves_nbytes(leaves: List[np.ndarray]) -> int:
    """Total payload bytes of one block entry."""
    return sum(int(a.nbytes) for a in leaves)


class KVBlockStore:
    """Digest-addressed block store: host-DRAM LRU over an optional
    disk tier.

    ``put`` is idempotent per digest (content-addressed — a duplicate
    put is by definition the same bytes) and never blocks: inserting
    past the byte budget evicts oldest-first, spilling to disk when a
    ``disk_dir`` is configured. ``get`` returns ``(tier, leaves)`` or
    None; hits touch the LRU order and promote disk entries to host.
    """

    def __init__(self, *, host_bytes: int = 64 << 20,
                 disk_dir: Optional[str] = None,
                 disk_bytes: int = 256 << 20):
        if host_bytes <= 0:
            raise ValueError(f"host_bytes={host_bytes}")
        self.host_budget = int(host_bytes)
        self.disk_budget = int(disk_bytes)
        self.disk_dir = disk_dir
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
        self._host: "OrderedDict[bytes, List[np.ndarray]]" = OrderedDict()
        self._host_nbytes: Dict[bytes, int] = {}
        self.host_bytes_used = 0
        self._disk: "OrderedDict[bytes, int]" = OrderedDict()  # digest -> nbytes
        self.disk_bytes_used = 0
        # Digests put since the last drain — cross-process workers report
        # these on step replies so the front-end can catalog who holds what.
        self._new: List[bytes] = []
        self.reset_stats()

    def reset_stats(self) -> None:
        self.counters = {
            "puts": 0, "dup_puts": 0, "put_bytes": 0,
            "hits_host": 0, "hits_disk": 0, "misses": 0, "hit_bytes": 0,
            "evictions_host": 0, "evictions_disk": 0, "spills_to_disk": 0,
        }

    # -- tier bookkeeping --------------------------------------------------

    def _disk_path(self, digest: bytes) -> str:
        return os.path.join(self.disk_dir, digest.hex() + ".npz")

    def _disk_put(self, digest: bytes, leaves: List[np.ndarray],
                  nbytes: int) -> bool:
        """True when the entry landed on disk. An entry too big for the
        whole tier is rejected BEFORE the eviction loop — it could never
        fit, so evicting for it would just flush the tier for nothing."""
        if nbytes > self.disk_budget:
            return False
        while self._disk and self.disk_bytes_used + nbytes > self.disk_budget:
            old, old_n = self._disk.popitem(last=False)
            self.disk_bytes_used -= old_n
            self.counters["evictions_disk"] += 1
            try:
                os.remove(self._disk_path(old))
            except OSError:
                pass
        # Atomic publish: a torn write must never surface as a partial npz.
        fd, tmp = tempfile.mkstemp(dir=self.disk_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **{f"a{i}": a for i, a in enumerate(leaves)})
            os.replace(tmp, self._disk_path(digest))
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        self._disk[digest] = nbytes
        self.disk_bytes_used += nbytes
        return True

    def _disk_get(self, digest: bytes) -> Optional[List[np.ndarray]]:
        if digest not in self._disk:
            return None
        try:
            with np.load(self._disk_path(digest)) as z:
                leaves = [z[f"a{i}"] for i in range(len(z.files))]
        except (OSError, KeyError, ValueError):
            self.disk_bytes_used -= self._disk.pop(digest)
            return None
        return leaves

    def _disk_remove(self, digest: bytes) -> None:
        n = self._disk.pop(digest, None)
        if n is not None:
            self.disk_bytes_used -= n
            try:
                os.remove(self._disk_path(digest))
            except OSError:
                pass

    def _host_insert(self, digest: bytes, leaves: List[np.ndarray],
                     nbytes: int) -> bool:
        """True when the entry is actually held by SOME tier afterwards
        — the caller only counts/announces a put that stuck."""
        if nbytes > self.host_budget:
            # Oversized for the host tier entirely: disk or drop.
            return bool(self.disk_dir) and self._disk_put(
                digest, leaves, nbytes)
        while self._host and self.host_bytes_used + nbytes > self.host_budget:
            old, old_leaves = self._host.popitem(last=False)
            old_n = self._host_nbytes.pop(old)
            self.host_bytes_used -= old_n
            self.counters["evictions_host"] += 1
            if self.disk_dir and old not in self._disk:
                if self._disk_put(old, old_leaves, old_n):
                    self.counters["spills_to_disk"] += 1
        self._host[digest] = leaves
        self._host_nbytes[digest] = nbytes
        self.host_bytes_used += nbytes
        return True

    # -- public surface ----------------------------------------------------

    def put(self, digest: bytes, leaves: List[np.ndarray], *,
            announce: bool = True) -> bool:
        """Insert one block entry. Returns True only when the entry was
        actually stored in some tier: False (with an LRU touch) for a
        duplicate digest — content addressing makes the duplicate bytes
        identical by construction — and False for an entry no tier
        could hold, which is neither counted nor announced (the catalog
        must never advertise a digest the store doesn't have).
        ``announce=False`` skips the new-digest catalog feed — for
        blocks PUSHED by the front-end, which knows them already."""
        if digest in self._host:
            self._host.move_to_end(digest)
            self.counters["dup_puts"] += 1
            return False
        if digest in self._disk:
            self.counters["dup_puts"] += 1
            return False
        leaves = [np.ascontiguousarray(a) for a in leaves]
        nbytes = leaves_nbytes(leaves)
        if not self._host_insert(digest, leaves, nbytes):
            return False
        self.counters["puts"] += 1
        self.counters["put_bytes"] += nbytes
        if announce:
            self._new.append(digest)
            # A standalone engine never drains the catalog feed; keep
            # only the newest announcements rather than growing without
            # bound.
            if len(self._new) > 4096:
                del self._new[:-4096]
        return True

    def get(self, digest: bytes) -> Optional[Tuple[str, List[np.ndarray]]]:
        """``(tier, leaves)`` for a stored digest, else None. Disk hits
        promote to the host tier (exclusive tiers)."""
        leaves = self._host.get(digest)
        if leaves is not None:
            self._host.move_to_end(digest)
            self.counters["hits_host"] += 1
            self.counters["hit_bytes"] += self._host_nbytes[digest]
            return "host", leaves
        leaves = self._disk_get(digest)
        if leaves is not None:
            self.counters["hits_disk"] += 1
            self.counters["hit_bytes"] += leaves_nbytes(leaves)
            self._disk_remove(digest)
            self._host_insert(digest, leaves, leaves_nbytes(leaves))
            return "disk", leaves
        self.counters["misses"] += 1
        return None

    def has(self, digest: bytes) -> bool:
        return digest in self._host or digest in self._disk

    def entry_nbytes(self, digest: bytes) -> Optional[int]:
        """Stored payload size without fetching (the admission pricer's
        transfer-bytes input)."""
        n = self._host_nbytes.get(digest)
        if n is not None:
            return n
        return self._disk.get(digest)

    def drain_new_digests(self) -> List[bytes]:
        out, self._new = self._new, []
        return out

    def __len__(self) -> int:
        return len(self._host) + len(self._disk)

    def stats(self) -> dict:
        s = {
            "host_entries": len(self._host),
            "host_bytes": self.host_bytes_used,
            "disk_entries": len(self._disk),
            "disk_bytes": self.disk_bytes_used,
        }
        s.update(self.counters)
        return s

    def clear(self) -> None:
        self._host.clear()
        self._host_nbytes.clear()
        self.host_bytes_used = 0
        for dig in list(self._disk):
            self._disk_remove(dig)
        self._new = []


@dataclasses.dataclass
class MigrationPricer:
    """Migration-vs-recompute admission pricing, comms-model style
    (parallel/comms_model.py): both sides reduce to seconds on an
    analytic roofline — transfer moves ``nbytes`` over the fleet link,
    recompute replays ``tokens`` forward passes at the device's peak.
    Admission takes the store/migration path only when transfer wins;
    otherwise it falls back to plain prefill, which is always correct
    (the digests guarantee either path produces identical K/V)."""

    flops_per_token: float       # forward FLOPs per token of this model
    device_flops: float          # peak FLOP/s of one serving device
    link_bytes_per_s: float      # host-to-host / host-DRAM transfer rate
    # Fixed cost of the prefill dispatch the transfer avoids (jitted step
    # launch + host scheduling). Chunked prefill pays it per chunk, so
    # charging it per priced unit is the right order of magnitude; without
    # it the FLOP term alone claims a tiny model "recomputes" a block in
    # nanoseconds, which no real dispatch path can do.
    dispatch_overhead_s: float = 5e-4

    def recompute_s(self, tokens: int) -> float:
        return (self.dispatch_overhead_s
                + tokens * self.flops_per_token / max(1.0, self.device_flops))

    def transfer_s(self, nbytes: int) -> float:
        return nbytes / max(1.0, self.link_bytes_per_s)

    def prefers_transfer(self, tokens: int, nbytes: int) -> bool:
        return self.transfer_s(nbytes) <= self.recompute_s(tokens)
