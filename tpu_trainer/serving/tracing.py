"""Serving observability: per-request span tracing + serve-loop ledger.

Two host-side instruments for the serving stack (ISSUE 17), both cheap
enough to stay on by default and both deliberately outside the jitted
path — enabling them cannot change a single sampled token:

1. **SpanTracer** — per-rid lifecycle timelines in the *engine clock
   domain*. Every request accumulates an ordered list of span events::

       submitted -> routed(replica, reason) -> admitted(queue_wait,
       prefix_hit) -> prefill_chunk x N -> first_token -> spec_window
       (k, accepted) x M -> preempted / exported / failed_over / fenced
       -> finished | cancelled | deadline_exceeded | failed

   Events are plain dicts ``{"rid", "event", "t", ...attrs}`` so they
   serialize losslessly over the RPC wire (``serving/remote.py`` ships
   them in submit payloads and step-delta replies) and the front-end
   merges worker-side events into one fleet timeline. Because
   cross-process workers already run their engine clock in the
   front-end's domain (``worker.py`` pins ``_t0 = 0`` and advances the
   clock from the shipped ``now``), merged timestamps need no skew
   correction.

   The tracer carries the span-conservation invariant mirroring the
   front-end accounting law (``accepted == finished + cancelled +
   deadline_exceeded``): every opened rid must close with **exactly
   one** terminal event, unless it was handed off to another replica
   (``exported``) whose timeline continues it.

2. **ServingLedger** — wall-clock attribution for a serve loop, the
   GoodputLedger pattern applied to serving: non-overlapping
   ``track()`` blocks split elapsed time into jitted dispatch vs host
   scheduling vs RPC wait vs idle ticks, so the fractions sum to
   <= 1.0 with the remainder reported as ``untracked_frac``.
   ``record(gauges)`` stamps a ``kind:"serve_ts"`` JSONL-able sample —
   the ledger fractions plus as-of-now fleet gauges (queue depth,
   outstanding tokens, occupancy, prefix hit rate, spec acceptance) —
   the time series ``tools/analyze.py`` sparklines and later SLO /
   autotuner work reads.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, List, Optional

from tpu_trainer.utils.logging import SCHEMA_VERSION

# Terminal span events: one per accepted rid, mirroring the scheduler's
# TERMINAL_STATES — the conservation law checked at drain.
TERMINAL_EVENTS = frozenset(
    {"finished", "cancelled", "deadline_exceeded", "failed"})
# Events that open a timeline (submit at the front door, or admission
# for a bare engine driven without a front-end).
OPENING_EVENTS = frozenset({"submitted", "admitted"})
# The request left THIS tracer's replica for another one (failover /
# drain migration): the local timeline ends without a terminal event;
# the merged front-end timeline still owes exactly one.
HANDOFF_EVENTS = frozenset({"exported"})


class SpanTracer:
    """Per-rid span-event timelines (host-side, engine clock domain).

    ``emit()`` appends locally-produced events; ``ingest()`` merges
    events produced elsewhere (the RPC wire, a local replica's own
    tracer). Both feed ``on_event`` (the front-end hooks per-replica
    flight-recorder rings there) and the ``drain()`` buffer of
    not-yet-shipped events (the worker's step-delta stream).
    ``enabled=False`` turns ``emit`` into a no-op — the bit-identity
    escape hatch and the A/B for the "tracing is free" claim.
    """

    def __init__(self, on_event: Optional[Callable[[dict], None]] = None,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self.on_event = on_event
        self._events: Dict[object, List[dict]] = {}
        self._pending: List[dict] = []

    def emit(self, rid, event: str, t: float, **attrs) -> Optional[dict]:
        if not self.enabled:
            return None
        ev = {"rid": rid, "event": event, "t": float(t)}
        for k, v in attrs.items():
            if v is not None:
                ev[k] = v
        self._events.setdefault(rid, []).append(ev)
        self._pending.append(ev)
        if self.on_event is not None:
            self.on_event(ev)
        return ev

    def ingest(self, events, pending: bool = False) -> None:
        """Merge foreign events (already dicts) into the timelines in
        their arrival order. ``pending=True`` re-queues them for this
        tracer's own ``drain()`` consumers (relay topologies)."""
        for ev in events:
            ev = dict(ev)
            self._events.setdefault(ev.get("rid"), []).append(ev)
            if pending:
                self._pending.append(ev)
            if self.on_event is not None:
                self.on_event(ev)

    def drain(self) -> List[dict]:
        """Events emitted since the last drain (the wire delta)."""
        out, self._pending = self._pending, []
        return out

    def events(self, rid) -> List[dict]:
        return list(self._events.get(rid, ()))

    def rids(self) -> List[object]:
        return list(self._events)

    def __len__(self) -> int:
        return sum(len(v) for v in self._events.values())

    def reset(self) -> None:
        self._events.clear()
        self._pending.clear()

    # --- the conservation invariant -------------------------------------

    def conservation(self) -> dict:
        """Check every opened rid closed with exactly one terminal event.

        Rejected submissions never opened (backpressure is not a loss);
        an ``exported`` rid's obligation moved to the timeline that
        ingested it. Returns ``{"ok", "open", "multi_terminal",
        "rids"}`` — the categorical gate in analyze FAILs on ok=False.
        """
        open_rids, multi = [], []
        for rid, evs in self._events.items():
            kinds = [e.get("event") for e in evs]
            if "rejected" in kinds:
                continue
            if not any(k in OPENING_EVENTS for k in kinds):
                continue
            n_term = sum(1 for k in kinds if k in TERMINAL_EVENTS)
            if n_term > 1:
                multi.append(rid)
            elif n_term == 0 and not any(k in HANDOFF_EVENTS for k in kinds):
                open_rids.append(rid)
        return {
            "ok": not open_rids and not multi,
            "open": sorted(open_rids, key=str),
            "multi_terminal": sorted(multi, key=str),
            "rids": len(self._events),
        }


def phase_breakdown(events: List[dict]) -> Dict[str, float]:
    """Per-phase durations of one rid's timeline (seconds, engine clock).

    ``queue_wait`` is admission minus *arrival* (carried on the admitted
    event — a request can arrive before the loop first sees it, so
    submit-event time alone under-counts), ``prefill`` is admission to
    first token (chunk scheduling gaps included — that IS the phase),
    ``decode`` first token to the terminal event, ``total`` open to
    terminal.
    """
    t_of: Dict[str, float] = {}
    for ev in events:
        t_of.setdefault(ev.get("event"), float(ev.get("t", 0.0)))
    out: Dict[str, float] = {}
    for ev in events:
        if ev.get("event") == "admitted" and "queue_wait" in ev:
            out["queue_wait"] = float(ev["queue_wait"])
            break
    admitted = t_of.get("admitted")
    first = t_of.get("first_token")
    term = next((float(e["t"]) for e in events
                 if e.get("event") in TERMINAL_EVENTS), None)
    if admitted is not None and first is not None:
        out["prefill"] = max(0.0, first - admitted)
    if first is not None and term is not None:
        out["decode"] = max(0.0, term - first)
    if term is not None:
        start = t_of.get("submitted", admitted)
        if start is not None:
            out["total"] = max(0.0, term - start)
    return out


def span_record(rid, events: List[dict], *, lane: Optional[str] = None,
                replica=None) -> dict:
    """One schema-stamped JSONL record per rid: the raw event list plus
    the derived phase durations (``queue_wait_s``/``prefill_s``/...)
    so analyze can gate phases without re-deriving them."""
    rec = {
        "kind": "span",
        "schema_version": SCHEMA_VERSION,
        "rid": rid,
        "n_events": len(events),
        "events": list(events),
    }
    if lane is not None:
        rec["lane"] = lane
    if replica is not None:
        rec["replica"] = replica
    for name, secs in phase_breakdown(events).items():
        rec[f"{name}_s"] = round(secs, 6)
    return rec


class ServingLedger:
    """Wall-clock attribution for a serve loop (GoodputLedger's shape).

    Categories are tracked via non-overlapping ``with track(cat):``
    blocks, so the per-category fractions of elapsed time sum to
    <= 1.0 and the gap is ``untracked_frac``. ``dispatch_frac`` is the
    serving analogue of goodput's ``productive_frac`` — the share of
    wall clock spent inside jitted dispatch.
    """

    CATEGORIES = (
        # Jitted engine work: prefill/decode/verify dispatch + host sync
        # on the result (the "productive" share).
        "dispatch",
        # Host-side scheduling: admission, deadline sweeps, routing,
        # mirror bookkeeping.
        "host_sched",
        # Blocking on a worker RPC reply (cross-process fleets only).
        "rpc_wait",
        # Loop ticks with no runnable work (waiting on arrivals).
        "idle",
    )

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._acc: Dict[str, float] = {}

    @contextlib.contextmanager
    def track(self, category: str):
        t = self._clock()
        try:
            yield
        finally:
            self.add(category, self._clock() - t)

    def add(self, category: str, seconds: float) -> None:
        self._acc[category] = self._acc.get(category, 0.0) + seconds

    def seconds(self, category: str) -> float:
        return self._acc.get(category, 0.0)

    def total_seconds(self) -> float:
        return max(self._clock() - self._t0, 1e-9)

    def reset(self) -> None:
        self._t0 = self._clock()
        self._acc.clear()

    def record(self, gauges: Optional[dict] = None, *,
               final: bool = False) -> dict:
        """One ``kind:"serve_ts"`` sample: ledger fractions as of now
        plus the caller's as-of-now fleet gauges (merged in verbatim)."""
        total = self.total_seconds()
        tracked = sum(self._acc.values())
        rec = {
            "kind": "serve_ts",
            "schema_version": SCHEMA_VERSION,
            "total_seconds": total,
            "dispatch_frac": self._acc.get("dispatch", 0.0) / total,
            "untracked_frac": max(0.0, 1.0 - tracked / total),
        }
        if final:
            rec["final"] = True
        for cat in self.CATEGORIES:
            if cat in self._acc:
                rec[f"{cat}_seconds"] = self._acc[cat]
                rec[f"{cat}_frac"] = self._acc[cat] / total
        if gauges:
            rec.update(gauges)
        return rec
